"""Train/eval loop — the reference's `main()` re-shaped for XLA.

Canonical reference loop: ddp_tutorial_multi_gpu.py:65-118. Per epoch it
(a) reshuffles via sampler.set_epoch(i), (b) runs the train pass — flatten,
forward, CE loss, backward (allreduce inside), SGD step, per-step scalar
logging — then (c) evaluates the FULL test set on every rank with dropout off,
and prints `Epoch=i, train_loss=…, val_loss=…`.

XLA-native restructurings (reported numbers keep the reference's meaning,
SURVEY.md §7 item 7):
  * the whole step (fwd+bwd+SGD) is one jitted function with donated params —
    no optimizer object, no zero_grad; XLA fuses the pipeline;
  * per-step `.item()` host syncs (ddp_tutorial_multi_gpu.py:96 — a
    device→host round trip EVERY step) are replaced by accumulating the
    per-batch mean losses on device and fetching ONCE per epoch;
  * the reference's "epoch_loss" accumulator quirk — it sums
    batch_mean_loss / batch_size, a nonstandard unit (SURVEY.md §5.5) — is
    reproduced exactly in the printed line, with standard mean loss and test
    accuracy (capability added per BASELINE.md) reported alongside;
  * eval runs the full test set per process, dropout disabled, exactly like
    the reference (ddp_tutorial_multi_gpu.py:101-114).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from typing import Callable

import numpy as np
import jax
import jax.numpy as jnp

from ..models.mlp import mlp_apply
from ..ops.loss import cross_entropy, accuracy
from ..ops.sgd import sgd_step
from ..data.loader import BatchLoader
from ..pipeline import feed as pipeline_feed
from ..utils.logging import progress
from ..utils.profiling import CumulativeTimer
from ..telemetry.dispatch import NullProfiler
from ..telemetry.events import get_tracer
from ..telemetry.runtime import record_memory_point


@dataclass
class TrainState:
    """Params + RNG key (+ the gradient-communication strategy's optional
    error-feedback residual — `comm='int8'` carries its quantization error
    here so checkpoints can round-trip it; None everywhere else: SGD
    itself is stateless)."""
    params: dict
    key: jax.Array
    resid: object = None


def _loss_fn(params, x, y, dropout_key, apply_fn=mlp_apply):
    logits = apply_fn(params, x, train=True, dropout_key=dropout_key)
    return cross_entropy(logits, y)


def make_train_step(lr: float, *, health: bool = False,
                    apply_fn=mlp_apply) -> Callable:
    """One jitted SGD step: (params, key, x, y) -> (params', key', mean_loss).

    The RNG key is split inside the step (traced, so it stays on device); the
    dropout mask is drawn per call, matching torch Dropout's fresh mask per
    forward. Params are donated — the update is in-place in HBM.

    `health=True` appends the watchdog's auxiliary vector
    (`telemetry.health.device_health_aux`: grad norm, finite flag, param
    norm) to the outputs — computed in-program from the grads the step
    already holds, fetched once per epoch with the losses (no extra host
    sync). The returned step carries `.health_aux` so the loop knows the
    output arity.
    """
    from ..telemetry.health import device_health_aux

    @partial(jax.jit, donate_argnums=(0, 1))
    def _step(params, key, x, y):
        key, sub = jax.random.split(key)
        loss, grads = jax.value_and_grad(_loss_fn)(params, x, y, sub,
                                                   apply_fn)
        new_params = sgd_step(params, grads, lr)
        if health:
            return (new_params, key, loss,
                    device_health_aux(loss, grads, new_params))
        return new_params, key, loss

    def step(params, key, x, y):
        return _step(params, key, x, y)

    step.health_aux = health
    return step


def make_torch_dropout_train_step(lr: float, seed: int, *,
                                  skip_steps: int = 0,
                                  batch_size: int | None = None) -> Callable:
    """The `--dropout_rng torch` step: dropout masks stream from torch's
    bitwise CPU bernoulli stream (parallel/torch_rng.torch_bernoulli, the
    stream of reference ddp_tutorial_cpu.py:47) instead of jax's key chain.

    Combined with `--sampler_rng torch`, the serial trajectory —
    sampler shard, per-step dropout masks, SGD — is bitwise-reproducible
    against a live torch run that seeds its global generator with `seed`
    after model init (torch's init consumes the same generator; reseeding
    post-init is the documented comparator shim). Masks are drawn on the
    HOST per step, exactly like torch; the jitted device step takes the
    mask as an input. The RNG key is threaded through untouched so the
    TrainState contract (and checkpoint/resume sidecars) are unchanged.

    `skip_steps` re-seats the stream for a resumed run (--resume /
    --start_epoch): the mask position is a pure function of completed
    steps — every step draws exactly batch_size*HIDDEN1 masks of 2 engine
    words each (the loaders wrap-pad every batch to full size) — so
    fast-forwarding skip_steps*batch_size*HIDDEN1*2 outputs lands the
    resumed trajectory bitwise on the unbroken run's masks.
    """
    from ..models.mlp import DROPOUT_RATE, MLP_DIMS
    from ..parallel.torch_rng import TorchMT19937, torch_bernoulli

    gen = TorchMT19937(seed)
    if skip_steps:
        if batch_size is None:
            raise ValueError("skip_steps needs batch_size (the per-step "
                             "mask row count)")
        gen.skip(skip_steps * batch_size * MLP_DIMS[1] * 2)
    keep = 1.0 - DROPOUT_RATE
    hidden = MLP_DIMS[1]

    def mask_loss_fn(params, x, y, mask):
        logits = mlp_apply(params, x, train=True, dropout_mask=mask)
        return cross_entropy(logits, y)

    @partial(jax.jit, donate_argnums=(0,))
    def device_step(params, x, y, mask):
        loss, grads = jax.value_and_grad(mask_loss_fn)(params, x, y, mask)
        return sgd_step(params, grads, lr), loss

    def step(params, key, x, y):
        mask = torch_bernoulli(gen, int(x.shape[0]) * hidden, keep)
        mask = jnp.asarray(mask.reshape(x.shape[0], hidden))
        params, loss = device_step(params, x, y, mask)
        return params, key, loss

    return step


def _eval_math(params, x, y, apply_fn=mlp_apply):
    """Per-sample test-set forward: (params, x (n,784), y (n,)) ->
    (per_sample_loss, correct), both (n,) float32. Dropout off, exactly the
    reference eval pass (ddp_tutorial_multi_gpu.py:101-114). `apply_fn`
    follows the selected model (models/zoo.py); default = the reference
    MLP."""
    logits = apply_fn(params, x, train=False)
    logz = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    per_sample = -jnp.take_along_axis(
        logz, y[:, None].astype(jnp.int32), axis=-1)[:, 0]
    correct = (jnp.argmax(logits, axis=-1) == y).astype(jnp.float32)
    return per_sample, correct


def make_eval_step(apply_fn=mlp_apply) -> Callable:
    """Jitted whole-test-set eval: (params, x, y) -> (per_sample_loss,
    correct), both (n,) float32.

    ONE program and ONE device round-trip for the full eval pass — the
    reference's eval loop dispatches per batch and syncs per step
    (ddp_tutorial_multi_gpu.py:101-114); on a (possibly remote) TPU each
    dispatch+transfer is host latency on the critical path, and the whole
    10k-row forward is a single small matmul chain for the MXU anyway.
    Per-sample values come back so the caller can aggregate in any batch
    segmentation it wants.
    """
    return jax.jit(partial(_eval_math, apply_fn=apply_fn))


def make_snapshot_eval_step(apply_fn=mlp_apply) -> Callable:
    """Jitted eval over STACKED per-epoch params snapshots: (p_snaps with an
    (E, ...) leading axis on every leaf, x, y) -> (per_sample (E, n),
    correct (E, n)).

    The fused trainer (`fit_cached(fused=True)`) replays per-epoch val lines
    from snapshots AFTER the one-program training run; evaluating them one
    jit call at a time would reintroduce E dispatch round-trips — a full
    tunnel RTT each on a remote TPU, easily dwarfing the fused run itself.
    vmap over the epoch axis makes the whole replay ONE program and ONE
    fetch (E x 10k x 784 stays a trivially small batched matmul chain).
    """
    @jax.jit
    def step(p_snaps, x, y):
        return jax.vmap(lambda p: _eval_math(p, x, y, apply_fn))(p_snaps)

    return step


def evaluate(eval_step, params, x_test, y_test, batch_size: int, perm=None):
    """Full-test-set eval (reference eval loop, ddp_tutorial_multi_gpu.py:
    101-114) in one device call.

    Returns (val_loss_ref_unit, mean_loss, acc): val_loss_ref_unit replicates
    the reference accumulator Σ(batch_mean/B) including its true last-batch
    size B (the reference's DataLoader yields a short final batch; here the
    per-sample losses are segmented into the same batch layout on host).

    The reference SHUFFLES its test loader (ddp_tutorial_multi_gpu.py:43-47),
    so its ref-unit value is RNG-dependent; the default here is
    deterministic sequential order. `perm` opts into the reference's
    shuffled batch segmentation: the fetched per-sample losses are permuted
    before segmenting — mean loss and accuracy are order-invariant, so only
    the ref-unit's batch layout changes, exactly like the torch loader, and
    the DEVICE work is identical either way (no re-evaluation)."""
    # jnp.asarray is a no-op for device-resident arrays; fit() hoists the
    # test set to device ONCE so repeated evaluate() calls do no H2D.
    per_sample, correct = eval_step(
        params, jnp.asarray(x_test), jnp.asarray(y_test))
    return val_summary(per_sample, correct, batch_size,
                       perm=perm)                         # fetch + aggregate


def val_summary(per_sample: np.ndarray, correct: np.ndarray,
                batch_size: int, perm=None):
    """Host-side aggregation of fetched per-sample eval values into
    evaluate()'s (val_loss_ref_unit, mean_loss, acc) triple — shared by the
    per-epoch path and the fused snapshot-eval replay so the printed units
    can never drift between them. `perm` (the shuffled-eval opt-in) lives
    HERE for the same reason: both paths must segment identically.
    `correct` stays unpermuted — accuracy is order-invariant."""
    n = per_sample.shape[0]
    per_sample = np.asarray(per_sample, np.float64)
    if perm is not None:
        per_sample = per_sample[np.asarray(perm)]
    val_loss_ref_unit = 0.0
    for start in range(0, n, batch_size):
        b = min(batch_size, n - start)
        val_loss_ref_unit += per_sample[start:start + b].mean() / b
    return (float(val_loss_ref_unit), float(per_sample.mean()),
            float(np.asarray(correct).mean()))


def epoch_summary(epoch: int, losses: np.ndarray, batch_size: int,
                  val: tuple, dt: float,
                  io_seconds: float | None = None) -> str:
    """The reference epoch line (ddp_tutorial_multi_gpu.py:116) + extensions.

    `losses` are the epoch's per-batch mean losses; `val` is evaluate()'s
    (ref_unit, mean, acc) triple. train_loss keeps the reference accumulator
    unit Σ(batch_mean/B) (SURVEY.md §5.5 quirk); mean/acc/throughput are the
    added diagnostics. Shared by the streaming and epoch-scanned trainers so
    the two paths can never drift in format or units. `io_seconds` (streaming
    path only) reports the host time spent waiting on the data loader — the
    I/O-vs-compute split the reference's ancestral harness was built to
    measure (SURVEY.md §5.1).
    """
    val_ref_unit, val_mean, val_acc = val
    train_loss_ref_unit = float((losses / batch_size).sum())
    imgs = losses.size * batch_size
    io = (f" io={io_seconds:.2f}s/{100 * io_seconds / dt:.0f}%"
          if io_seconds is not None else "")
    return (f"Epoch={epoch}, train_loss={train_loss_ref_unit}, "
            f"val_loss={val_ref_unit}"
            f"  [mean_train={float(losses.mean()):.4f} "
            f"mean_val={val_mean:.4f} "
            f"acc={val_acc:.4f} {imgs / dt:.0f} img/s{io}]")


def make_ddp_comm_recorder(mesh, comm: str, n_devices: int, params,
                           quant_block: int | None = None,
                           bucket_elems: int | None = None):
    """Per-epoch recorder for the DDP gradient-communication metrics —
    shared by the streaming `fit` and the epoch-scanned `fit_cached` so the
    two paths can never report different units.

    Always (cheap host math): `ddp.bytes_on_wire` — a registry counter of
    cumulative analytic per-device wire bytes (ring cost model,
    parallel/collectives.bytes_on_wire). Only when telemetry is ENABLED
    (the zero-per-step-host-sync invariant stays intact — and this much is
    once per EPOCH, not per step): run the isolated comm probe
    (collectives.make_comm_probe — the strategy's collective pattern on a
    params-shaped tree), record the reps into the `ddp.collective_s`
    histogram, and emit a `ddp_comm_probe` child span so trace reports
    show comms cost beside step_compute.
    """
    from ..parallel import collectives
    from ..telemetry import get_registry
    from ..telemetry.events import NullTracer

    quant_block = (collectives.QUANT_BLOCK if quant_block is None
                   else quant_block)
    bucket_elems = (collectives.DEFAULT_BUCKET_ELEMS if bucket_elems is None
                    else bucket_elems)
    bytes_step = collectives.bytes_on_wire(params, n_devices, comm,
                                           bucket_elems=bucket_elems,
                                           quant_block=quant_block)
    reg = get_registry()
    wire = reg.counter("ddp.bytes_on_wire")
    hist = reg.histogram("ddp.collective_s")
    box = {}

    def record(steps: int, params) -> None:
        wire.inc(steps * bytes_step)
        tracer = get_tracer()
        if isinstance(tracer, NullTracer):
            return
        if "probe" not in box:
            box["probe"] = collectives.make_comm_probe(
                mesh, comm, quant_block=quant_block,
                bucket_elems=bucket_elems)
        secs = collectives.measure_collective_seconds(box["probe"], params)
        for s in secs:
            hist.record(s)
        tracer.complete_span("ddp_comm_probe", sum(secs), strategy=comm,
                             reps=len(secs), steps=steps,
                             bytes_on_wire_per_step=bytes_step)

    return record


class _LiveLoss:
    """Per-step live loss for the progress bar WITHOUT per-step syncs.

    The reference feeds `batch_loss.item()` into its tqdm bar every step
    (ddp_tutorial_multi_gpu.py:96-98) — a forced device->host round trip per
    step, the antipattern this framework removes. This restores the UX
    asynchronously: each poll checks (locally, no device traffic) whether
    recently dispatched loss values have COMPLETED via `Array.is_ready()`,
    and at most every `interval` seconds fetches one already-ready scalar —
    a 4-byte copy of a finished value, never a wait on the device. The bar
    shows `loss=<v>@<step>`, lagging the true step by however deep the
    dispatch queue runs; throughput is unchanged (locked by a test).
    """

    def __init__(self, bar, interval: float = 0.5):
        self._set = getattr(bar, "set_postfix_str", None)
        self._interval = interval
        self._last = 0.0
        self._shown = -1

    def poll(self, losses: list) -> None:
        if self._set is None or not losses:
            return
        now = time.perf_counter()
        if now - self._last < self._interval:
            return
        # Throttle from poll ATTEMPT, not success: when the device lags and
        # nothing is ready yet, the next scan still waits a full interval —
        # otherwise every step would rescan the whole pending queue.
        self._last = now
        # newest completed value, searching back from the freshest dispatch
        for i in range(len(losses) - 1, self._shown, -1):
            arr = losses[i]
            if not hasattr(arr, "is_ready") or arr.is_ready():
                self._shown = i
                self._set(f"loss={float(arr):.4f}@{i}")
                return


def step_ckpt_positions(nsteps: int, epoch: int, i: int):
    """Sampler position a step checkpoint must record after in-epoch step
    `i` (0-based) of an epoch with `nsteps` steps: (epoch, i+1), except the
    epoch-final step normalizes to (epoch+1, 0) — the state after an
    epoch's last step IS the state entering the next epoch (eval mutates
    nothing), so a resume never replays a zero-step epoch tail. Shared by
    the streaming and epoch-scanned trainers so their manifests can never
    disagree about what an offset means."""
    if i + 1 >= nsteps:
        return epoch + 1, 0
    return epoch, i + 1


def _fire_step_hook(step_hook, every: int, nsteps: int, epoch: int, i: int,
                    params, key, resid=None) -> None:
    """Invoke the step-checkpoint hook when in-epoch step `i` (0-based)
    lands on the cadence (`every` global steps) or closes the epoch.
    `step_hook(epoch', offset', global_step, state)` — positions from
    step_ckpt_positions. One helper for both trainers (cadence drift
    between them would silently break resume parity expectations).
    `resid` (the int8 strategy's error-feedback state) rides the
    TrainState so step checkpoints can round-trip it."""
    if step_hook is None or not every:
        return
    # cadence is EPOCH-LOCAL (step i+1 a multiple of `every`, plus the
    # epoch-final step): the epoch-scanned trainer chunks each epoch's scan
    # at exactly these boundaries, so both trainers save at identical
    # global steps for any nsteps/every combination
    if (i + 1) % every == 0 or i + 1 >= nsteps:
        ep, off = step_ckpt_positions(nsteps, epoch, i)
        step_hook(ep, off, epoch * nsteps + i + 1,
                  TrainState(params, key, resid))


def fit(state: TrainState, train_loader: BatchLoader, x_test, y_test, *,
        epochs: int, batch_size: int, lr: float | None = None,
        log: Callable[[str], None] = print,
        train_step: Callable | None = None, sharding=None, put=None,
        epoch_hook: Callable | None = None, start_epoch: int = 0,
        start_offset: int = 0, ckpt_every_steps: int = 0,
        step_hook: Callable | None = None,
        eval_perm: Callable | None = None,
        watchdog=None, model_apply: Callable | None = None,
        input_workers: int = 0, prefetch_depth: int = 1,
        journal=None, dispatch_profiler=None) -> TrainState:
    """Run the reference training loop for `epochs` epochs.

    Exactly one of `lr` / `train_step` must be given: `lr` builds the serial
    jitted step; a prebuilt `train_step` (e.g. the mesh-sharded DP step,
    which bakes in its own lr) is used as-is, with `sharding`/`put` for batch
    placement. The printed epoch line replicates the reference format and
    units (ddp_tutorial_multi_gpu.py:116), extended with accuracy and timing.
    `epoch_hook(epoch, state)` supports mid-training checkpointing.

    `start_epoch` resumes the run at a GLOBAL epoch index: epochs
    [start_epoch, epochs) run with their uninterrupted sampler reshuffles
    and epoch numbering, so a run resumed from epoch-k state retraces
    exactly what the unbroken run would have done from there (the
    outage-resume path of cli.train; state must carry epoch k-1's params
    AND key for bitwise fidelity).

    `start_offset` additionally resumes MID-epoch: the first run epoch
    skips its first `start_offset` batches (the step-checkpoint manager's
    resume path — state must carry the params AND key saved after exactly
    that many steps of that epoch; the resumed epoch's printed train_loss
    then covers only the remaining steps). `step_hook(epoch, offset,
    global_step, state)` fires every `ckpt_every_steps` global steps and
    at each epoch end (see step_ckpt_positions) — the save cadence of
    `train/ckpt_manager.py`. Each step is also a `kill` fault point
    (utils/faultpoints), fired AFTER the hook so an injected kill at step
    K never races the step-K checkpoint; each step's reported loss is a
    `nan` POISON point (`faultpoints.poison` — the watchdog's
    deterministic chaos input).

    `watchdog` (telemetry.health.Watchdog) observes once per epoch, over
    exactly the values the loop fetches anyway — the per-step loss curve,
    the epoch timers, and (when the step was built with `health=True`,
    which this loop does itself on the lr path) the per-step health aux
    vectors, stacked and fetched WITH the losses. A healthy or absent
    watchdog adds zero extra host syncs (pinned by tests/test_health.py).

    Batches flow through the staged input pipeline (`pipeline.feed` — the
    one front door): `input_workers` background decode threads feeding a
    bounded reorder buffer (0, the default, = synchronous reads) and
    `prefetch_depth` batches of H2D transfer lookahead (1 = the legacy
    one-slot double buffer). Every configuration is BITWISE identical to
    bare loader iteration (order-preserving pipeline, pinned by
    tests/test_pipeline.py), mid-epoch resume skips at the index level
    with workers live, and the consumer side adds zero host syncs —
    the data_wait span and the epoch-granular fetch budget
    (statics/sanitize.no_host_sync) hold unchanged. See docs/DATA.md.

    `journal` (telemetry.cluster.CollectiveJournal) is the per-rank
    collective journal: the step must declare its static collective
    schedule (`step.collective_schedule` — the XLA DDP step does;
    rejected by name otherwise), and every dispatched step then expands
    into per-collective journal records sharing the step's host dispatch
    window, while the end-of-epoch loss fetch — the host-side drain of
    every step's collectives, where a dead peer actually wedges this
    process — is bracketed as an open/close `flush` entry the collective
    watchdog can age. Pure host clock reads + JSONL writes: journaled
    training stays bitwise identical to unjournaled and adds zero host
    syncs (pinned by tests/test_cluster.py under sanitize.no_host_sync).

    `dispatch_profiler` (telemetry.dispatch.DispatchProfiler) decomposes
    the step boundary into the named overhead phases — python_prestep /
    dispatch / device_idle / sync_wait (docs/OBSERVABILITY.md §Dispatch
    forensics). Its hooks bracket sites the loop already times: prestep
    opens after the batch arrives, dispatch wraps the jitted call, the
    end-of-epoch fetch feeds sync_wait, and the flush hands over
    step_timer.total so coverage is checked against the loop's own
    clock. Only the sampled 1-in-K device-idle bracket drains the device
    (on the PREVIOUS step's live outputs); the NullProfiler default adds
    zero syncs and stays bitwise identical (pinned by
    tests/test_telemetry.py).
    """
    from ..utils import faultpoints

    if (train_step is None) == (lr is None):
        raise ValueError("pass exactly one of lr= or train_step=")
    if not 0 <= start_epoch <= epochs:
        raise ValueError(f"start_epoch={start_epoch} outside [0, {epochs}]")
    if start_offset < 0:
        raise ValueError(f"start_offset={start_offset} must be >= 0")
    step = (train_step if train_step is not None
            else make_train_step(lr, health=watchdog is not None,
                                 apply_fn=model_apply or mlp_apply))
    # health-enabled steps return a 4th per-step aux output (grad norm /
    # finite flag / param norm) that rides the loss fetch — see
    # telemetry/health.py
    step_health = bool(getattr(step, "health_aux", False))
    # comm-state steps (the int8 strategy's error feedback) additionally
    # thread the residual: (params, key, x, y, resid) in, resid' LAST out
    # — seeded from the TrainState (a resumed checkpoint) or zeros
    step_comm = bool(getattr(step, "comm_state", False))
    resid = (step.place_comm_state(
                 np.asarray(state.resid) if state.resid is not None
                 else None, state.params)
             if step_comm else None)
    eval_step = make_eval_step(model_apply or mlp_apply)
    # Hoist the test set to device ONCE — the reference re-materializes its
    # test tensors per batch per epoch (ddp_tutorial_multi_gpu.py:105-106);
    # repeating jnp.asarray inside the epoch loop would re-upload ~31 MB of
    # MNIST per epoch for no reason.
    x_test_dev, y_test_dev = jnp.asarray(x_test), jnp.asarray(y_test)
    params, key = state.params, state.key
    tracer = get_tracer()  # NullTracer unless --telemetry enabled it
    # NullProfiler unless --profile_dispatch armed one: the hooks below
    # are unconditional no-ops on the default path
    prof = (dispatch_profiler if dispatch_profiler is not None
            else NullProfiler())
    # DP steps carry their comm strategy as metadata (parallel/ddp.py):
    # wire up the ddp.* metrics without the loop knowing about meshes.
    ddp_record = None
    if getattr(step, "ddp_comm", None) is not None:
        ddp_record = make_ddp_comm_recorder(
            step.ddp_mesh, step.ddp_comm, step.ddp_devices, params,
            quant_block=getattr(step, "ddp_quant_block", None),
            bucket_elems=getattr(step, "ddp_bucket_elems", None))
    if journal is not None:
        schedule_fn = getattr(step, "collective_schedule", None)
        if schedule_fn is None:
            raise ValueError(
                "journal= needs a train step that declares its collective "
                "schedule (parallel.ddp.make_dp_train_step does); this "
                "step carries none — the journal cannot attribute "
                "collectives it cannot enumerate")
        journal.bind_program(getattr(step, "ddp_comm", "?"),
                             bool(getattr(step, "ddp_overlap", False)),
                             schedule_fn(params))
    nsteps = len(train_loader)
    if start_epoch < epochs and start_offset >= nsteps:
        raise ValueError(f"start_offset={start_offset} >= the epoch's "
                         f"{nsteps} steps (a committed step checkpoint "
                         f"never records a full-epoch offset)")
    for epoch in range(start_epoch, epochs):
        # Per-epoch trace span with the phase split the reference's
        # ancestral I/O harness existed to report (SURVEY.md §5.1):
        # data_wait (host blocked on the loader), step_compute (step
        # dispatch + the end-of-epoch loss fetch, which blocks until every
        # step's device work is done), eval. All child durations come from
        # timers the loop already pays for — the tracer itself never forces
        # a device sync, so enabling telemetry adds no per-step host sync
        # (pinned by tests/test_telemetry.py).
        with tracer.span("epoch", epoch=epoch):
            t0 = time.perf_counter()
            io_timer = CumulativeTimer("loader-wait")
            step_timer = CumulativeTimer("step-dispatch")
            train_loader.sampler.set_epoch(epoch)
            losses = []
            aux_list = []
            offset = start_offset if epoch == start_epoch else 0
            # the staged input pipeline (pipeline/): decode workers +
            # depth-K device prefetch behind one front door; the default
            # (workers=0, depth=1) is exactly the legacy synchronous
            # loader + one-slot double buffer, bitwise
            batches = progress(
                pipeline_feed(train_loader, workers=input_workers,
                              depth=prefetch_depth, start=offset,
                              sharding=sharding, put=put),
                desc=f"epoch {epoch}")
            live = _LiveLoss(batches)
            it = iter(batches)
            i = offset
            while True:
                with io_timer:   # host time blocked on the data pipeline
                    batch = next(it, None)
                if batch is None:
                    break
                x, y = batch
                # python_prestep opens here: batch in hand, everything
                # until the jitted call is host bookkeeping
                prof.mark_prestep()
                # journal stamps bracket the DISPATCH (clock reads only,
                # and only when journaling): the step's collectives share
                # this window; completion is observed at the bracketed
                # flush. The wall stamp is the window's ENTER (the
                # cross-rank comparison key — every rank stamps the same
                # boundary of the same step).
                if journal is not None:
                    jt0, jt0w = time.perf_counter(), time.time()
                else:
                    jt0 = jt0w = 0.0
                # sync_tree = the PREVIOUS step's params output: a live
                # array (donated inputs are dead buffers) the sampled
                # device-idle bracket can drain on
                prof.begin_dispatch(params)
                with step_timer:
                    if step_comm:
                        out = step(params, key, x, y, resid)
                        params, key, loss, resid = (out[0], out[1], out[2],
                                                    out[-1])
                        if step_health:
                            aux_list.append(out[3])
                    elif step_health:
                        params, key, loss, aux = step(params, key, x, y)
                        aux_list.append(aux)
                    else:
                        params, key, loss = step(params, key, x, y)
                prof.end_dispatch(epoch * nsteps + i)
                if journal is not None:
                    journal.record_step(epoch * nsteps + i,
                                        jt0, time.perf_counter(), jt0w)
                # the nan value-fault point: poisons only this REPORTED
                # loss (params untouched), staying on device — the
                # watchdog's detection path, deterministically testable
                loss = faultpoints.poison("loss", loss,
                                          step=epoch * nsteps + i + 1,
                                          epoch=epoch)
                losses.append(loss)
                _fire_step_hook(step_hook, ckpt_every_steps, nsteps,
                                epoch, i, params, key, resid=resid)
                # hook BEFORE the kill fault point: an injected kill at
                # step K must never race the step-K checkpoint it tests
                faultpoints.fire("step", step=epoch * nsteps + i + 1,
                                 epoch=epoch)
                i += 1
                live.poll(losses)  # async bar update; never waits on device
            t_fetch = time.perf_counter()
            # the epoch flush drains every dispatched step's collectives:
            # bracketed as an open journal entry, because THIS is where a
            # dead peer wedges the host — the collective watchdog ages it
            # and the hang report names the pending seq range
            fseq = (journal.enter("flush", axis="dp", steps=len(losses))
                    if journal is not None else -1)
            losses = np.asarray(jnp.stack(losses))  # single fetch per epoch
            if journal is not None:
                journal.exit(fseq)
            fetch_s = time.perf_counter() - t_fetch
            prof.note_sync_wait(fetch_s)
            # batches = STEPS this epoch (step_timer.count): io_timer also
            # wraps the end-of-epoch sentinel next() that returns None, so
            # its count is one high — the report must agree with the
            # pipeline's data.batches counter
            tracer.complete_span("data_wait", io_timer.total,
                                 batches=step_timer.count)
            tracer.complete_span("step_compute", step_timer.total + fetch_s,
                                 steps=step_timer.count, fetch_s=fetch_s)
            # the window denominator is step_timer.total — the loop's OWN
            # clock over the jitted calls — so the coverage check holds
            # the profiler to an independent measurement
            prof.flush_epoch(epoch, steps=step_timer.count,
                             step_total_s=step_timer.total)
            t_eval = time.perf_counter()
            val = evaluate(eval_step, params, x_test_dev, y_test_dev,
                           batch_size,
                           perm=eval_perm(epoch) if eval_perm else None)
            tracer.complete_span("eval", time.perf_counter() - t_eval)
            # one HBM/RSS watermark sample per epoch, under the epoch
            # span — Perfetto renders it as a memory counter track
            # (telemetry/export.py). Host-side probes only: no device
            # sync, no fetch; a NullTracer costs one attribute check.
            record_memory_point(tracer)
            if ddp_record is not None:
                ddp_record(len(losses), params)
            dt = time.perf_counter() - t0
            log(epoch_summary(epoch, losses, batch_size, val,
                              dt, io_seconds=io_timer.total))
            state = TrainState(params, key, resid)
            if watchdog is not None:
                # one observation per epoch, over the already-fetched loss
                # curve (+ the aux vectors, stacked in the same style — a
                # second fetch of finished values, never a drain). May
                # raise TrainingHealthError under the abort policy.
                aux_np = (np.asarray(jnp.stack(aux_list))
                          if aux_list else None)
                watchdog.observe(
                    losses, aux=aux_np, state=state, epoch=epoch,
                    step=(epoch + 1) * nsteps,
                    ckpt_epoch=epoch + 1, ckpt_offset=0,
                    dt_s=dt, imgs=losses.size * batch_size)
            if epoch_hook is not None:
                epoch_hook(epoch, state)
    return state
