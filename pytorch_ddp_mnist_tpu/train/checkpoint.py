"""Checkpointing.

Reference behavior (SURVEY.md §5.4): ONE final params-only save, rank 0 only,
DDP-unwrapped — torch.save(model.state_dict(), 'model.pt')
(ddp_tutorial_multi_gpu.py:118,143-144). The save-side parity is
`save_checkpoint(path, params)` called process-0-only by the trainers; the
"unwrap" has no analog because SPMD params are already a plain pytree.

Added capability beyond the reference (which has no load path at all): a
matching `load_checkpoint`, so checkpoints are actually usable, and an
epoch-granular resume hook in the CLI. Format: flax msgpack serialization of
the params pytree — single file, byte-stable, no torch dependency.

Torch interop: a `.pt`/`.pth` path switches both functions to the reference's
own checkpoint format — a torch state_dict with the exact key names the
reference's nn.Sequential produces ('0.weight', '0.bias', '3.weight',
'3.bias', '5.weight'; ddp_tutorial_cpu.py:45-51). A file we save loads into
the reference model with `model.load_state_dict(torch.load('model.pt'))`,
and a reference-produced `model.pt` seeds our trainer via `--resume` — the
two frameworks' checkpoints are interchangeable.
"""

from __future__ import annotations

import os

import jax
import numpy as np
from flax import serialization

# Our pytree layer -> the reference nn.Sequential's state_dict key stem
# (ddp_tutorial_cpu.py:45-51: Linear at indices 0, 3, 5; fc3 has no bias).
_TORCH_STEMS = (("fc1", "0"), ("fc2", "3"), ("fc3", "5"))


class CheckpointError(RuntimeError):
    """A checkpoint file that cannot be decoded (truncated, torn, or not a
    checkpoint at all) — or, from the step-checkpoint manager, a directory
    with no intact checkpoint left to fall back to.

    Exists so a corrupt file surfaces as ONE named error carrying the path
    and byte size instead of a raw flax/msgpack traceback, and so the
    manager's intact-fallback path (`train/ckpt_manager.py`) has a precise
    exception class to catch — any other exception still means a bug."""


def is_torch_path(path: str) -> bool:
    """True if `path` selects the torch state_dict checkpoint format."""
    return path.endswith((".pt", ".pth"))


_is_torch_path = is_torch_path


def params_to_torch_state_dict(params):
    """Params pytree -> the reference model's state_dict (torch tensors).

    Weights transpose from our (fan_in, fan_out) x@w layout to torch Linear's
    (out, in)."""
    import torch
    # copies: jax gives read-only host buffers; torch wants writable memory
    host = jax.tree_util.tree_map(lambda a: np.array(a, np.float32), params)
    sd = {}
    for ours, stem in _TORCH_STEMS:
        sd[f"{stem}.weight"] = torch.from_numpy(
            np.ascontiguousarray(host[ours]["w"].T))
        if "b" in host[ours]:
            sd[f"{stem}.bias"] = torch.from_numpy(host[ours]["b"])
    return sd


def params_from_torch_state_dict(sd):
    """The reference model's state_dict (torch tensors or ndarrays) -> params
    pytree, transposing weights back to (fan_in, fan_out).

    A still-DDP-wrapped save (every key prefixed 'module.' — the reference
    always unwraps first, ddp_tutorial_multi_gpu.py:118, but a user's own
    save may not) is accepted by stripping the uniform prefix. Any other
    layout fails with a named error listing the expected reference keys."""
    def _np(v):
        return v.detach().cpu().numpy() if hasattr(v, "detach") else np.asarray(v)

    if sd and all(k.startswith("module.") for k in sd):
        sd = {k[len("module."):]: v for k, v in sd.items()}
    params = {}
    for ours, stem in _TORCH_STEMS:
        key = f"{stem}.weight"
        if key not in sd:
            expected = [f"{s}.weight" for _, s in _TORCH_STEMS] + [
                f"{s}.bias" for o, s in _TORCH_STEMS if o != "fc3"]
            raise ValueError(
                f"torch state_dict is missing key {key!r}; expected the "
                f"reference nn.Sequential layout {expected} (optionally "
                f"uniformly 'module.'-prefixed), got keys {sorted(sd)}")
        layer = {"w": np.ascontiguousarray(_np(sd[key]).T)}
        if f"{stem}.bias" in sd:
            layer["b"] = _np(sd[f"{stem}.bias"])
        params[ours] = layer
    return params


def save_checkpoint(path: str, params) -> None:
    """Serialize a params pytree to `path`. Fully fetches to host.

    `.pt`/`.pth` -> reference-compatible torch state_dict; otherwise msgpack."""
    tmp = path + ".tmp"
    if _is_torch_path(path):
        import torch
        torch.save(params_to_torch_state_dict(params), tmp)
    else:
        host_params = jax.tree_util.tree_map(np.asarray, params)
        with open(tmp, "wb") as f:
            f.write(serialization.to_bytes(host_params))
    os.replace(tmp, path)  # atomic: no torn checkpoint on crash


def load_checkpoint(path: str, template):
    """Restore a params pytree from `path` using `template` for structure.

    `.pt`/`.pth` -> read a torch state_dict (ours or one the reference's
    `torch.save(model.state_dict(), 'model.pt')` wrote)."""
    if _is_torch_path(path):
        import torch
        sd = torch.load(path, map_location="cpu", weights_only=True)
        params = params_from_torch_state_dict(sd)
        # Validate against the template like the msgpack branch does
        # (structure/shape mismatches should fail HERE with a named error,
        # not as an opaque XLA error mid-train).
        if (jax.tree_util.tree_structure(params)
                != jax.tree_util.tree_structure(template)):
            raise ValueError(
                f"{path}: checkpoint layer structure "
                f"{jax.tree_util.tree_structure(params)} does not match the "
                f"model's {jax.tree_util.tree_structure(template)}")
        got = jax.tree_util.tree_leaves_with_path(params)
        want = jax.tree_util.tree_leaves(template)
        for (kp, have), exp in zip(got, want):
            if np.shape(have) != np.shape(exp):
                raise ValueError(
                    f"{path}: checkpoint param {jax.tree_util.keystr(kp)} "
                    f"has shape {np.shape(have)}, expected {np.shape(exp)}")
        return params
    with open(path, "rb") as f:
        blob = f.read()
    try:
        return serialization.from_bytes(template, blob)
    except Exception as e:
        # A truncated/torn msgpack body surfaces as a raw flax/msgpack
        # exception with no filename — wrap it with the path and size so a
        # dead relaunch names its evidence (and the step-checkpoint
        # manager's fallback can catch it by class).
        raise CheckpointError(
            f"{path}: cannot decode checkpoint ({len(blob)} bytes): "
            f"{type(e).__name__}: {e}") from e
