"""Checkpointing.

Reference behavior (SURVEY.md §5.4): ONE final params-only save, rank 0 only,
DDP-unwrapped — torch.save(model.state_dict(), 'model.pt')
(ddp_tutorial_multi_gpu.py:118,143-144). The save-side parity is
`save_checkpoint(path, params)` called process-0-only by the trainers; the
"unwrap" has no analog because SPMD params are already a plain pytree.

Added capability beyond the reference (which has no load path at all): a
matching `load_checkpoint`, so checkpoints are actually usable, and an
epoch-granular resume hook in the CLI. Format: flax msgpack serialization of
the params pytree — single file, byte-stable, no torch dependency.
"""

from __future__ import annotations

import os

import jax
import numpy as np
from flax import serialization


def save_checkpoint(path: str, params) -> None:
    """Serialize a params pytree to `path` (msgpack). Fully fetches to host."""
    host_params = jax.tree_util.tree_map(np.asarray, params)
    data = serialization.to_bytes(host_params)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)  # atomic: no torn checkpoint on crash


def load_checkpoint(path: str, template):
    """Restore a params pytree from `path` using `template` for structure."""
    with open(path, "rb") as f:
        return serialization.from_bytes(template, f.read())
