"""Config / CLI layer — the reference `configure()` analog.

The reference builds a two-section nested dict from defaults + argparse
(mnist_cpu_mp.py:208-243, mnist_pnetcdf_cpu_mp.py:274-309):
trainer.{batch_size, wireup_method, parallel, device, n_epochs, num_workers}
and data.{path, limit, label_map, hdf5}. Its tutorial scripts instead
hard-code batch_size=128 / epochs in __main__
(ddp_tutorial_multi_gpu.py:126-127); our CLIs take these as defaults.

Kept keys that are dead in the reference (label_map, hdf5, data.limit —
parsed and printed but never used by training, SURVEY.md §5.6) are accepted
for CLI compatibility; `data.limit` is actually honored here (truncates the
dataset) since that is its evident intent.

wireup_method choices map the reference's {nccl-slurm, nccl-openmpi,
nccl-mpich, gloo, mpich} onto the TPU runtime: every method resolves to
jax.distributed.initialize with coordinator discovery appropriate to the
launcher (see parallel.wireup); the names are kept so launch scripts port 1:1.
"""

from __future__ import annotations

import argparse
from typing import Any, Dict

WIREUP_CHOICES = (
    "auto",          # probe: SLURM -> OpenMPI -> MPICH -> env -> single-process
    "slurm",         # reference nccl-slurm analog (mnist_cpu_mp.py:47-89)
    "openmpi",       # reference nccl-openmpi analog (PMIx env, :94-113)
    "mpich",         # reference nccl-mpich / mpich analog (PMI env, :118-142)
    "env",           # reference fallback env:// analog (:147-185)
    "tpu",           # Cloud TPU pod metadata autodetection (no env maze)
    "single",        # no distributed init (serial / one-process multi-chip)
    # The reference's literal spellings, accepted verbatim so its launch
    # lines run unmodified (mnist_cpu_mp.py:47-188, train_cpu_mp.csh:1);
    # canonicalized by parallel.wireup.resolve_method at parse time.
    "nccl-slurm", "nccl-openmpi", "nccl-mpich", "gloo",
)


def configure(argv=None) -> Dict[str, Dict[str, Any]]:
    """Parse CLI args into the nested {trainer: {...}, data: {...}} config."""
    p = argparse.ArgumentParser(
        description="TPU-native MNIST trainer (capability parity with "
                    "pytorch_ddp_mnist; see SURVEY.md)")
    t = p.add_argument_group("trainer")
    t.add_argument("--batch_size", type=int, default=128)
    t.add_argument("--n_epochs", "--epochs", type=int, default=1,
                   help="epochs to train; --n_epochs is the reference "
                        "spelling (mnist_cpu_mp.py:213), --epochs the "
                        "common one")
    t.add_argument("--lr", type=float, default=0.01)
    t.add_argument("--seed", type=int, default=0)
    t.add_argument("--parallel", action="store_true",
                   help="data-parallel over the device mesh (DDP analog)")
    t.add_argument("--ddp-comm", "--ddp_comm", dest="ddp_comm",
                   choices=("pmean", "sharded", "bf16", "int8"),
                   default="pmean",
                   help="gradient-communication strategy for --parallel "
                        "(parallel/collectives.py): pmean (default — the "
                        "reference DDP shape: full f32 allreduce-mean + "
                        "replicated SGD update), sharded (bucketized "
                        "reduce-scatter, SGD on each device's 1/N shard, "
                        "params all-gather — 1/N update FLOPs/HBM; parity "
                        "with pmean to f32 reduction-order tolerance), "
                        "bf16 (compressed allreduce: bf16 wire bytes AND "
                        "bf16 reduction, f32 mean/update against f32 "
                        "master params — bounded drift, pinned by test), "
                        "or int8 (block-scaled quantized allreduce with "
                        "error-feedback residuals riding the step state "
                        "and step checkpoints — ~1/4 the wire bytes, "
                        "bounded drift, pinned by test; --quant_block / "
                        "--error_feedback tune it). Telemetry reports "
                        "ddp.bytes_on_wire / ddp.collective_s per strategy")
    t.add_argument("--overlap", action="store_true",
                   help="bucket-pipeline the DDP gradient collectives "
                        "(--parallel): one collective per gradient bucket, "
                        "launched as soon as that bucket's backward slice "
                        "exists, instead of one whole-tree barrier at step "
                        "end — XLA overlaps comm with the remaining "
                        "backward compute (arXiv:1711.00705). Composes "
                        "with every --ddp_comm strategy (sharded/int8 are "
                        "bucketized by construction); plain pmean without "
                        "it stays the bitwise reference baseline. Needs "
                        "the XLA kernels (the whole-epoch kernel owns its "
                        "comms in-kernel)")
    t.add_argument("--quant_block", type=int, default=None,
                   help="--ddp_comm int8 only: elements per int8 scaling "
                        "block (one f32 scale each; default "
                        "collectives.QUANT_BLOCK = 256 — ~1.6%% scale "
                        "overhead on the wire). Rejected by name on other "
                        "strategies")
    t.add_argument("--error_feedback", choices=("on", "off"), default="on",
                   help="--ddp_comm int8 only: carry each device's "
                        "quantization error into the next step's gradients "
                        "(on, default — the EQuARX residual; rides the "
                        "step state and step checkpoints) or drop it (off "
                        "— measures the residual's contribution; drift "
                        "then compounds). Rejected by name on other "
                        "strategies")
    # choices mirror models.zoo.MODELS (kept literal: this layer stays
    # jax-import-free); zoo.validate_model re-checks at train time
    t.add_argument("--model", choices=("mlp", "deep_mlp"), default="mlp",
                   help="model family (models/zoo.py): mlp (default — the "
                        "reference 784-128-128-10 MLP, bit-for-bit at "
                        "--param_scale 1) or deep_mlp (4 hidden layers). "
                        "Non-default models run the XLA kernels (the "
                        "Pallas kernels hard-code the reference MLP)")
    t.add_argument("--param_scale", type=int, default=1,
                   help="hidden-width multiplier for --model (128*N units; "
                        "params grow ~quadratically — the workload knob "
                        "that makes gradient-communication costs visible; "
                        "docs/PERF.md carries the strategy x scale "
                        "crossover table)")
    t.add_argument("--bf16_rounding", choices=("nearest", "stochastic"),
                   default="nearest",
                   help="--ddp_comm bf16 only: how gradients round into "
                        "the bf16 wire cast — nearest (default, round-to-"
                        "nearest-even) or stochastic (unbiased stochastic "
                        "rounding, per-step per-replica noise; "
                        "parallel/collectives.stochastic_round_bf16). "
                        "Rejected by name on other strategies")
    t.add_argument("--wireup_method", choices=WIREUP_CHOICES, default="auto")
    t.add_argument("--num_workers", type=int, default=0,
                   help="readahead threads for the --netcdf streaming loader "
                        "(the reference's DataLoader worker count, "
                        "mnist_pnetcdf_cpu.py:58-60); the in-memory path is "
                        "async via device prefetch regardless. Superseded "
                        "by --input_workers (the staged pipeline) — passing "
                        "both is rejected by name")
    t.add_argument("--input_workers", type=int, default=0,
                   help="staged input pipeline (pipeline/, docs/DATA.md): N "
                        "background decode/normalize threads feeding the "
                        "streaming train loop through a bounded reorder "
                        "buffer — batch order (and the trained params) stay "
                        "BITWISE identical to the synchronous default (0). "
                        "Works for the in-memory and --netcdf loaders "
                        "alike; rejected by name with --cached (the dataset "
                        "lives in HBM there — no loader to feed)")
    t.add_argument("--prefetch_depth", type=int, default=1,
                   help="input pipeline H2D lookahead: keep K batches' "
                        "host->device transfers in flight while the "
                        "current step computes (pipeline/prefetch.py; 1 = "
                        "the legacy one-slot double buffer). With --cached "
                        "it prefetches the chunk index placements instead; "
                        "--fused has one placement total and rejects a "
                        "non-default depth by name")
    t.add_argument("--device", type=int, default=0,
                   help="reference-CLI parity (per-rank device ordinal); "
                        "device placement is mesh-driven on TPU")
    t.add_argument("--checkpoint", type=str, default="model.msgpack")
    t.add_argument("--resume", type=str, default=None,
                   help="checkpoint to load before training (added capability;"
                        " the reference has no load path)")
    t.add_argument("--ckpt_every_steps", type=int, default=0,
                   help="step-granular crash-consistent checkpointing "
                        "(train/ckpt_manager.py): every N global steps "
                        "(and at each epoch end) save the FULL resume "
                        "state — params, epoch, step, sampler offset, RNG "
                        "key chain — as an atomic CRC-stamped checkpoint "
                        "under <--checkpoint>.steps/. Resume with "
                        "--resume <that directory>: training continues at "
                        "the exact step, bitwise on the unbroken "
                        "trajectory, falling back past torn checkpoints. "
                        "0 (default) = epoch-granular only. Needs "
                        "--checkpoint; rejects --fused and --kernel "
                        "pallas_epoch by name. Saves are rank-0-gated and "
                        "every rank reads the directory at resume — "
                        "multi-HOST worlds need it on a shared filesystem "
                        "(docs/ROBUSTNESS.md)")
    t.add_argument("--ckpt_keep", type=int, default=3,
                   help="keep-last-N rotation for --ckpt_every_steps "
                        "checkpoints (default 3; older ones are deleted "
                        "after each successful save)")
    t.add_argument("--fault", type=str, default=None, metavar="SPEC",
                   help="deterministic fault injection "
                        "(utils/faultpoints.py), merged with $PDMT_FAULT: "
                        "comma-separated specs like 'kill:rank=2:step=5', "
                        "'ckpt_save_io:step=3', "
                        "'loader_stall:batch=3:delay_s=0.5', "
                        "'collective_timeout'. Every fired fault lands in "
                        "the telemetry flight recorder. Chaos testing "
                        "only — see docs/ROBUSTNESS.md for the catalog")
    t.add_argument("--start_epoch", type=int, default=0,
                   help="resume the run at this GLOBAL epoch index: epochs "
                        "[start_epoch, n_epochs) run with their "
                        "uninterrupted sampler reshuffles and numbering. "
                        "Pair with --resume (epoch start_epoch-1's "
                        "checkpoint) to continue an interrupted run; the "
                        "outage-resume re-exec sets it automatically")
    t.add_argument("--outage_retries", type=int, default=0,
                   help="opt-in mid-run backend-outage resilience (serial, "
                        "non-fused runs): on a device/backend RuntimeError "
                        "mid-training, wait for the backend "
                        "(PDMT_BACKEND_WAIT, default 1h) and resume from "
                        "the last completed epoch's in-memory state, up to "
                        "N times; if the in-process client is wedged "
                        "(hang-mode outage), persist progress and re-exec "
                        "with --resume/--start_epoch. 0 (default) = fail "
                        "fast")
    t.add_argument("--dtype", choices=("float32", "bfloat16"), default="float32",
                   help="compute dtype for the train step")
    t.add_argument("--impl", choices=("threefry2x32", "rbg"),
                   default="threefry2x32",
                   help="PRNG engine for the train key (dropout stream). "
                        "threefry2x32 (default) is the reference RNG "
                        "stream — with --kernel pallas_epoch it is drawn "
                        "IN-kernel by the VPU cipher (bitwise "
                        "models/mlp.py masks at epoch-kernel speed); rbg "
                        "uses the TPU hardware generator — same Bernoulli "
                        "keep distribution, its own stream, measured 1.7x "
                        "whole-step throughput on the per-step kernels "
                        "(docs/PERF.md)")
    t.add_argument("--kernel",
                   choices=("auto", "xla", "pallas", "pallas_rng",
                            "pallas_epoch"),
                   default="auto",
                   help="train-step implementation: 'auto' (default: the "
                        "fused Pallas kernel on a TPU backend with f32, xla "
                        "otherwise — the bench.py policy; a bare run on TPU "
                        "trains at the fastest measured per-step variant), "
                        "'xla' (jit + XLA fusion), "
                        "'pallas' (the fused fwd+bwd VMEM-resident "
                        "TPU kernel, ops/pallas_step.py; composes with "
                        "--cached to run inside the epoch scan), "
                        "'pallas_rng' (dropout "
                        "drawn inside the kernel from the TPU core PRNG; "
                        "real TPU + --cached only), or 'pallas_epoch' "
                        "(the WHOLE epoch as one kernel, weights "
                        "VMEM-resident across steps; real TPU + --cached. "
                        "With --parallel: per-step DDP grad-mean via an "
                        "in-kernel ICI ring allreduce — EXPERIMENTAL, "
                        "multi-chip ring not yet hardware-verified)")
    t.add_argument("--telemetry", type=str, default=None, metavar="DIR",
                   help="write a structured JSONL event trace into DIR "
                        "(telemetry/events.py schema: per-epoch spans with "
                        "data-wait/step-compute/eval children, XLA compile "
                        "counter, end-of-run registry snapshot) and print a "
                        "rank-0 summary line; validate with "
                        "scripts/check_telemetry.py DIR. Off by default — "
                        "disabled telemetry adds no per-step host sync. See "
                        "docs/OBSERVABILITY.md")
    t.add_argument("--journal", action="store_true",
                   help="write the per-rank COLLECTIVE journal beside the "
                        "JSONL trace (telemetry/cluster.py: one record per "
                        "payload collective the step program issues — seq/"
                        "kind/bytes/bucket from the audited schedule, "
                        "enter/exit stamps from the host boundary — plus a "
                        "hang watchdog that flips /healthz when an entered "
                        "collective never exits). Read it back with `trace "
                        "report --cluster DIR`. Needs --telemetry and "
                        "--parallel on the streaming XLA path; zero device "
                        "syncs, bitwise-identical training. See "
                        "docs/OBSERVABILITY.md §Cluster forensics")
    t.add_argument("--profile_dispatch", type=int, nargs="?", const=16,
                   default=0, metavar="K",
                   help="decompose the per-step host boundary into the "
                        "named overhead phases (telemetry/dispatch.py: "
                        "python_prestep / dispatch / device_idle / "
                        "sync_wait) as dispatch.* histograms, flight-ring "
                        "samples and per-epoch trace points; read back "
                        "with `trace report --overhead DIR`. K is the "
                        "device-idle sampling period — the idle probe "
                        "drains the device on 1-in-K steps (default 16; "
                        "steady-state steps stay sync-free). Needs "
                        "--telemetry; incompatible with --fused (no "
                        "per-step host boundary). Off by default — the "
                        "NullProfiler path adds zero host syncs. See "
                        "docs/OBSERVABILITY.md §Dispatch forensics")
    t.add_argument("--health", choices=("off", "warn", "checkpoint-and-warn",
                                        "abort"),
                   default="off",
                   help="live training-health watchdog "
                        "(telemetry/health.py): rolling detectors for loss "
                        "spikes, NaN/Inf, grad-norm explosion, update-ratio "
                        "drift, throughput collapse and straggler drift, "
                        "over the values the loop already fetches (zero "
                        "extra per-step host syncs). The choice is the "
                        "FATAL-signal policy: warn (log + record), "
                        "checkpoint-and-warn (additionally save the last "
                        "known-good state via the step-checkpoint manager "
                        "— needs a non-empty --checkpoint), or abort "
                        "(flight-dump + stop the run). Off by default")
    t.add_argument("--metrics_port", type=int, default=None, metavar="PORT",
                   help="serve a live pull endpoint from a stdlib HTTP "
                        "thread on this port (rank 0; 0 = ephemeral, the "
                        "bound address prints to stderr): GET /metrics is "
                        "the unified registry in Prometheus text format "
                        "(plus the health_* gauges when --health is on), "
                        "GET /healthz the JSON health verdict. Binds "
                        "127.0.0.1 ONLY — scrape a remote run through an "
                        "ssh tunnel (the endpoint is unauthenticated)")
    t.add_argument("--profile", type=str, default=None, metavar="LOGDIR",
                   help="capture a jax.profiler trace of the training run "
                        "into LOGDIR (view in TensorBoard/XProf); restores "
                        "the timing capability the reference's ancestral "
                        "I/O-cost harness lost (SURVEY.md §5.1)")
    t.add_argument("--sampler_rng", choices=("pcg64", "torch"),
                   default="pcg64",
                   help="train-shard permutation source: pcg64 (default; "
                        "the documented fast path) or torch — the bitwise "
                        "MT19937 randperm of torch's DistributedSampler "
                        "(ddp_tutorial_multi_gpu.py:26-30), making every "
                        "epoch's shard composition index-identical to a "
                        "reference run at the same seed")
    t.add_argument("--dropout_rng", choices=("jax", "torch"),
                   default="jax",
                   help="dropout mask source: jax (default; the --impl key "
                        "chain) or torch — masks stream from torch's "
                        "bitwise CPU bernoulli stream (the nn.Dropout draw "
                        "of ddp_tutorial_cpu.py:47, seeded --seed). With "
                        "--sampler_rng torch the serial streaming "
                        "trajectory is bitwise-reproducible against a live "
                        "torch run that reseeds its generator with --seed "
                        "after model init. Serial streaming path only "
                        "(no --parallel/--cached); --resume/--start_epoch "
                        "compose (the mask stream fast-forwards to the "
                        "resume boundary), --outage_retries does not")
    t.add_argument("--eval_shuffle", action="store_true",
                   help="shuffle the eval batch segmentation per epoch like "
                        "the reference's test DataLoader(shuffle=True) "
                        "(ddp_tutorial_multi_gpu.py:43-47). Only the "
                        "Σ(mean/B) ref-unit val_loss changes — mean loss "
                        "and accuracy are order-invariant, and no extra "
                        "device work runs. Drawn with the torch-bitwise "
                        "MT19937 randperm seeded (--seed + epoch); the "
                        "reference's loader is UNseeded, so parity here is "
                        "engine-faithful determinism, not bitwise")
    t.add_argument("--elastic", action="store_true",
                   help="preemption-tolerant elastic training (elastic/"
                        "coordinator.py): on peer loss — watchdog hang "
                        "event, backend-loss error, open journal entry — "
                        "surviving ranks rescue-checkpoint (pinned save), "
                        "agree on membership via beacons, and re-exec into "
                        "the surviving world under the next world "
                        "generation, re-mapping the checkpoint geometry per "
                        "--reshape instead of refusing it. Needs --parallel, "
                        "--telemetry and a --checkpoint dir with "
                        "--ckpt_every_steps. Off (the default) is "
                        "bitwise-identical to today. See docs/ROBUSTNESS.md "
                        "§Elastic training")
    t.add_argument("--reshape", choices=("global_batch", "per_rank"),
                   default=None,
                   help="elastic geometry re-mapping mode, default "
                        "global_batch (elastic/"
                        "reshape.py): global_batch (default) preserves the "
                        "manifest's GLOBAL batch by scaling the per-device "
                        "micro-batch (must divide; int8 error-feedback "
                        "residual folds into survivors, offset preserved); "
                        "per_rank keeps the per-device batch fixed — global "
                        "batch scales with the world (degraded throughput), "
                        "offset re-mapped by samples consumed, residual "
                        "deliberately dropped. Needs --elastic")
    t.add_argument("--cached", action="store_true",
                   help="cache the dataset in HBM and run each epoch as one "
                        "jitted lax.scan program (fastest path for datasets "
                        "that fit on device; multi-process capable)")
    t.add_argument("--fused", action="store_true",
                   help="with --cached: run ALL epochs as ONE device program "
                        "(the bench.py path); per-epoch lines/checkpoints "
                        "replay from on-device snapshots AFTER the run — "
                        "fastest, but preemption mid-run leaves no "
                        "intermediate checkpoint (use plain --cached for "
                        "epoch-granular preemption resilience)")
    d = p.add_argument_group("data")
    d.add_argument("--path", "--data_path", type=str, default="data/",
                   help="dataset root (IDX or NetCDF files); --data_path is "
                        "the reference spelling (mnist_cpu_mp.py:215)")
    d.add_argument("--netcdf", action="store_true",
                   help="read mnist_{train,test}_images.nc (PnetCDF-path analog)")
    d.add_argument("--download", action="store_true",
                   help="fetch real MNIST IDX files (checksum-verified "
                        "mirrors) when absent from --path — the "
                        "datasets.MNIST(download=True) analog "
                        "(ddp_tutorial_cpu.py:22)")
    d.add_argument("--limit", "--data_limit", type=int, default=-1,
                   help="truncate dataset to N samples (reference parsed this "
                        "but never used it; honored here); --data_limit is "
                        "the reference spelling (mnist_cpu_mp.py:216)")
    d.add_argument("--hdf5", action="store_true",
                   help="dead flag kept for reference-CLI parity")
    d.add_argument("--label_map", type=int, nargs="*", default=None,
                   help="dead key kept for reference-CLI parity")
    a = p.parse_args(argv)
    from pytorch_ddp_mnist_tpu.parallel.wireup import resolve_method
    a.wireup_method = resolve_method(a.wireup_method)
    return {
        "trainer": {
            "batch_size": a.batch_size, "n_epochs": a.n_epochs, "lr": a.lr,
            "seed": a.seed, "parallel": a.parallel, "ddp_comm": a.ddp_comm,
            "bf16_rounding": a.bf16_rounding, "overlap": a.overlap,
            "quant_block": a.quant_block,
            "error_feedback": a.error_feedback == "on",
            "model": a.model, "param_scale": a.param_scale,
            "wireup_method": a.wireup_method, "num_workers": a.num_workers,
            "input_workers": a.input_workers,
            "prefetch_depth": a.prefetch_depth,
            "device": a.device, "checkpoint": a.checkpoint, "resume": a.resume,
            "start_epoch": a.start_epoch, "outage_retries": a.outage_retries,
            "ckpt_every_steps": a.ckpt_every_steps, "ckpt_keep": a.ckpt_keep,
            "fault": a.fault,
            "sampler_rng": a.sampler_rng, "eval_shuffle": a.eval_shuffle,
            "dropout_rng": a.dropout_rng,
            "dtype": a.dtype, "impl": a.impl,
            "cached": a.cached, "fused": a.fused,
            "profile": a.profile, "kernel": a.kernel,
            "telemetry": a.telemetry, "journal": a.journal,
            "profile_dispatch": a.profile_dispatch,
            "health": a.health, "metrics_port": a.metrics_port,
            "elastic": a.elastic, "reshape": a.reshape,
        },
        "data": {
            "path": a.path, "netcdf": a.netcdf, "limit": a.limit,
            "download": a.download, "hdf5": a.hdf5, "label_map": a.label_map,
        },
    }
