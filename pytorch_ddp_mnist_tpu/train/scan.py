"""Epoch-jitted training: one `lax.scan` program per epoch.

The reference's hot loop dispatches one optimizer step per Python iteration
(ddp_tutorial_multi_gpu.py:86-98) — on GPU that cost hides behind CUDA
streams; under XLA each dispatch is host work on the critical path, and for
this 118k-param MLP the step is latency-bound, so dispatch dominates. The
TPU-native restructuring: keep the (tiny) dataset resident in HBM, compute
the epoch's batch INDICES on host (preserving ShardedSampler's exact
DistributedSampler semantics — host numpy stays the permutation source of
truth), and run the entire epoch as ONE jitted `lax.scan` whose body gathers
the batch on device and applies the fused fwd/bwd/SGD step. Python touches
the device once per epoch instead of once per step.

Semantics are bit-compatible with the streaming loop (train/loop.py): the
same per-step `jax.random.split` chain drives dropout, the same wrap-padded
static batches come out of the same sampler indices, and per-step mean
losses are accumulated identically — `fit_cached` therefore prints the same
reference-format epoch line. The DP variant runs the scan inside
`shard_map`: batch indices are sharded over 'dp' (each device gathers only
its replica's rows from the replicated dataset — no collective), gradients
are `pmean`ed per step exactly like the streaming DP step.

Scale note: this mode replicates the dataset in HBM — raw uint8 pixels
(MNIST: ~47 MB; `resident_images`), normalized on device per gather — the
right call at the reference's scale; the streaming loaders remain the path
for datasets that don't fit.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ..compat import shard_map

from ..data.mnist import MNIST_MEAN, MNIST_STD
from ..models.mlp import mlp_apply
from ..ops.loss import cross_entropy
from ..ops.sgd import sgd_step
from ..parallel.ddp import _pvary
from ..parallel.mesh import DATA_AXIS
from ..pipeline.prefetch import prefetch as pipeline_prefetch
from ..telemetry.dispatch import NullProfiler
from ..telemetry.events import get_tracer
from .loop import (TrainState, _fire_step_hook, epoch_summary, evaluate,
                   make_ddp_comm_recorder, make_eval_step,
                   make_snapshot_eval_step, step_ckpt_positions, val_summary)


def _gathered_x(x_all, batch_idx, compute_dt):
    """Gather a batch from the resident dataset, normalizing on device when
    the dataset is uint8-resident.

    Storing raw uint8 pixels in HBM instead of normalized float32 cuts the
    dataset footprint and the per-step gather's HBM read 4x (the scan step is
    bandwidth/latency-bound, not MXU-bound — docs/PERF.md). The device
    normalize replays normalize_images' op chain in float32 — the gathered
    batch is mathematically identical to one from a host-normalized array;
    XLA may fuse/reorder the chain into neighbors, so downstream values can
    differ at float-rounding level (like any recompilation), never in
    distribution or algorithm.
    """
    x = jnp.take(x_all, batch_idx, axis=0)
    if x.dtype == jnp.uint8:
        x = device_normalize(x.reshape(x.shape[0], -1))
    return x.astype(compute_dt)


def device_normalize(x):
    """normalize_images' exact op chain on device, in f32 and in this op
    order (the bit-identity argument vs the host path depends on it) — the
    ONE jnp copy of the chain, shared by the scan gather and the eval
    bench. The Pallas epoch kernel keeps its own Mosaic variant (int32
    widening; ops/pallas_step.py) and pins it to this math by test."""
    x = x.astype(jnp.float32) / jnp.float32(255.0)
    return (x - jnp.float32(MNIST_MEAN)) / jnp.float32(MNIST_STD)


def resident_images(images: np.ndarray) -> np.ndarray:
    """Host-side prep of the HBM-resident dataset: raw uint8 stays uint8
    (flattened — normalization happens on device per gather); anything else
    is assumed pre-normalized float32."""
    arr = np.asarray(images)
    if arr.dtype == np.uint8:
        return np.ascontiguousarray(arr.reshape(arr.shape[0], -1))
    return np.asarray(arr, np.float32)


def epoch_batch_indices(sampler, batch_size: int) -> np.ndarray:
    """(nbatches, batch_size) int32 — this rank's epoch as static-shape
    batches, wrap-padding the final one (same math as the loaders)."""
    from ..data.loader import _batched_indices
    return np.stack(list(_batched_indices(sampler, batch_size))).astype(np.int32)


def resolve_kernel(dtype: str, on_tpu: bool) -> str:
    """The `--kernel auto` policy (bench.py and the trainer CLI): fused
    Pallas step on TPU (fastest measured PER-STEP variant — docs/PERF.md;
    bench additionally promotes single-chip runs to the whole-epoch kernel),
    XLA autodiff elsewhere (Pallas off-TPU is interpreter-only). bf16 keeps
    xla: the bf16-matmul Pallas kernels exist (explicit --kernel selects
    them) but auto only promotes to hardware-measured-fastest variants."""
    return "pallas" if on_tpu and dtype == "float32" else "xla"


def _check_kernel(kernel: str, dtype: str) -> None:
    """Kernel/dtype compatibility — the single source of truth (the CLI
    converts this ValueError to a SystemExit). Every kernel now composes
    with bfloat16: the Pallas kernels select bf16-matmul mode (bf16 MXU
    operands, f32 accumulation/master weights) when handed a bf16 batch."""
    if kernel not in ("xla", "pallas", "pallas_rng", "pallas_epoch"):
        raise ValueError(f"unknown kernel {kernel!r}")
    if dtype not in ("float32", "bfloat16"):
        raise ValueError(f"unknown dtype {dtype!r}")


def _check_superstep(superstep: int, kernel: str) -> None:
    """superstep composes only with the whole-epoch kernel (K sub-steps per
    grid iteration); reject it elsewhere by name rather than silently
    ignoring the flag (the unroll lesson, ADVICE r2)."""
    if superstep == 1:
        return
    if kernel != "pallas_epoch":
        raise ValueError(
            f"superstep={superstep} is a whole-epoch-kernel knob (K SGD "
            f"sub-steps per grid iteration); kernel={kernel!r} has a "
            f"per-step scan — use unroll there, or kernel='pallas_epoch'")
    if superstep not in (2, 4, 8):
        raise ValueError(
            f"superstep must be 1, 2, 4 or 8 (sub-step loss rows must stay "
            f"inside one 8-row loss tile); got {superstep}")


def _check_ring(ring: str, kernel: str, n_dev: int) -> None:
    """`ring` selects the DP epoch kernel's in-kernel allreduce strategy;
    reject it by name anywhere it would be a silent no-op (the unroll
    lesson, ADVICE r2) — a caller forcing 'reduce_scatter' on a kernel or
    mesh that never reaches the ring would otherwise silently measure the
    wrong program. epoch_fused_sgd re-validates on the path that uses it."""
    if ring not in ("auto", "allgather", "reduce_scatter"):
        raise ValueError(f"ring must be 'auto', 'allgather' or "
                         f"'reduce_scatter'; got {ring!r}")
    if ring == "auto":
        return
    if kernel != "pallas_epoch" or n_dev == 1:
        raise ValueError(
            f"ring={ring!r} selects the DP epoch kernel's in-kernel "
            f"allreduce strategy; it needs kernel='pallas_epoch' on a "
            f"multi-device mesh (got kernel={kernel!r}, {n_dev} device(s))")


def _loss_and_grads(params, x, y, dropout_key, kernel: str, interpret: bool,
                    apply_fn=None):
    """Per-step fwd+bwd: XLA autodiff or the fused Pallas kernel. 'pallas'
    draws the dropout mask from the same bernoulli stream as 'xla' for the
    same key (bitwise-matched schedule change); 'pallas_rng' draws it inside
    the kernel from the TPU core PRNG, seeded per step from the key — same
    keep distribution, its own stream (like threefry vs rbg). `apply_fn`
    (models/zoo.py) selects the model on the XLA path; the Pallas kernels
    hard-code the reference MLP and their callers reject other models by
    name."""
    if kernel == "pallas_rng":
        if interpret:
            raise ValueError("kernel 'pallas_rng' draws dropout bits with "
                             "the TPU core PRNG (no interpreter lowering); "
                             "use 'pallas' off-TPU")
        from ..ops.pallas_step import fused_loss_and_grads_rng
        seed = jax.lax.bitcast_convert_type(
            jax.random.key_data(dropout_key).ravel()[0], jnp.int32)
        return fused_loss_and_grads_rng(params, x, y, seed)
    if kernel == "pallas":
        from ..ops.pallas_step import dropout_mask, fused_loss_and_grads
        mask = dropout_mask(dropout_key, x.shape[0])
        return fused_loss_and_grads(params, x, y, mask, interpret=interpret)

    fwd = apply_fn or mlp_apply

    def loss_fn(p):
        return cross_entropy(
            fwd(p, x, train=True, dropout_key=dropout_key), y)

    return jax.value_and_grad(loss_fn)(params)


def make_epoch_fn(lr: float, *, dtype: str = "float32", kernel: str = "xla",
                  interpret: bool = False, model: str = "mlp",
                  param_scale: int = 1) -> Callable:
    """Serial epoch program: (params, key, x_all, y_all, idx) ->
    (params', key', losses) with idx (nbatches, B).

    One epoch is the one-element case of the fused multi-epoch program
    (mirrors make_dp_epoch_fn / make_dp_run_fn)."""
    run = make_run_fn(lr, dtype=dtype, kernel=kernel, interpret=interpret,
                      model=model, param_scale=param_scale)

    @partial(jax.jit, donate_argnums=(0, 1))
    def epoch(params, key, x_all, y_all, idx):
        params, key, losses = run(params, key, x_all, y_all, idx[None])
        return params, key, losses[0]

    return epoch


def _make_epochal_body(x_all, y_all, lr, *, interpret: bool, snapshots: bool,
                       pmean_axis: str | None = None,
                       axis_size: int = 1,
                       compute_bf16: bool = False,
                       steps_per_iter: int = 1,
                       ring: str = "auto") -> Callable:
    """The shared per-EPOCH scan body of the kernel='pallas_epoch' programs
    (serial make_run_fn and DP make_dp_run_fn): derive the epoch's dropout
    source from the key chain, gather the epoch rows (uint8 pass-through —
    the kernel normalizes in-VMEM), call the whole-epoch kernel, optionally
    pmean the shard-local losses (DP) and stack snapshots.

    `interpret` (CPU CI): the seeds->mask mapping is abstracted out — masks
    come from the jax.random stream of the same per-epoch subkey (its own
    dropout stream, like threefry vs the TPU core PRNG) and stream into the
    interpretable masked kernel. `axis_size > 1` enables the in-kernel ICI
    ring (see ops.pallas_step.epoch_fused_sgd)."""
    from ..ops.pallas_step import dropout_mask, epoch_fused_sgd

    def epoch(carry, idx_e):
        params, key = carry
        key, sub = jax.random.split(key)
        batch = idx_e.shape[1]               # per-replica rows per step
        nsteps = idx_e.shape[0]              # real steps this epoch
        rows = idx_e.reshape(-1)
        # A ragged step count (nsteps % K != 0) is padded HERE, at the
        # index level — a few extra gathered blocks — so epoch_fused_sgd
        # never takes its whole-epoch zero-concat fallback on the hot path.
        # The kernel masks the padded tail sub-steps by global step
        # (valid_steps), so the pad rows' content is irrelevant (index 0 =
        # real, finite data).
        pad_steps = (-nsteps) % steps_per_iter
        if pad_steps:
            rows = jnp.concatenate(
                [rows, jnp.zeros(pad_steps * batch, rows.dtype)])
        if x_all.dtype == jnp.uint8:
            # raw uint8 rows stream straight into the kernel — no f32 epoch
            # image array (~4x the bytes) is ever materialized in HBM.
            xp = jnp.take(x_all, rows, axis=0)
        else:
            xp = _gathered_x(x_all, rows, jnp.float32)
        yp = jnp.take(y_all, rows, axis=0)
        # interpret=True -> the PLAIN interpreter (masks streamed; the
        # seeds->mask mapping abstracted out). An InterpretParams instance
        # instead runs the REAL kernel under the TPU-semantics simulator
        # and falls through to the in-kernel RNG branches below.
        if interpret is True:
            subs = jax.random.split(sub, nsteps)
            masks = jax.vmap(lambda k: dropout_mask(k, batch))(subs)
            masks = masks.reshape(nsteps * batch, -1)
            if pad_steps:
                masks = jnp.concatenate(
                    [masks,
                     jnp.zeros((pad_steps * batch, masks.shape[1]),
                               masks.dtype)])
            params, losses = epoch_fused_sgd(
                params, xp, yp, None, lr, batch,
                masks=masks, interpret=True,
                compute_bf16=compute_bf16, steps_per_iter=steps_per_iter,
                valid_steps=nsteps)
        elif jax.random.key_data(sub).shape[-1] == 2:
            # A 2-word key IS the threefry engine (--impl threefry2x32, the
            # reference RNG): draw the exact models/mlp.py bernoulli stream
            # IN-kernel (ops/pallas_step.py threefry2x32 on the VPU) from
            # per-step subkeys of the same split chain the interpreted path
            # uses — reference dropout semantics at epoch-kernel speed
            # (VERDICT r3 #4; the dropout of ddp_tutorial_cpu.py:47). DP
            # replicas fold the axis index into the epoch key first, so
            # each rank draws an independent stream (SURVEY.md §7 item 4).
            if not jax.config.jax_threefry_partitionable:
                # the in-kernel cipher replays the PARTITIONABLE counter
                # layout (the jax default); under the legacy layout
                # dropout_mask's stream differs and the bitwise-parity
                # contract would break SILENTLY — refuse instead.
                raise ValueError(
                    "in-kernel threefry dropout reproduces jax's "
                    "partitionable threefry stream; this process disabled "
                    "jax_threefry_partitionable — re-enable it (the "
                    "default) or use --impl rbg / --kernel pallas")
            skey = sub
            if axis_size > 1:
                skey = jax.random.fold_in(
                    sub, jax.lax.axis_index(pmean_axis))
            subs = jax.random.split(skey, nsteps)
            keys = jax.random.key_data(subs).astype(jnp.int32)
            if pad_steps:
                keys = jnp.concatenate(
                    [keys, jnp.zeros((pad_steps, 2), jnp.int32)])
            params, losses = epoch_fused_sgd(
                params, xp, yp, keys, lr, batch, rng_impl="threefry",
                interpret=interpret,   # False, or an InterpretParams
                axis_name=pmean_axis if axis_size > 1 else None,
                axis_size=axis_size, compute_bf16=compute_bf16,
                steps_per_iter=steps_per_iter, valid_steps=nsteps,
                ring=ring)
        else:
            # 4-word (rbg) key: the TPU hardware generator seeds the
            # in-kernel core PRNG — its own stream, the bench default.
            seed = jax.lax.bitcast_convert_type(
                jax.random.key_data(sub).ravel()[0], jnp.int32)
            params, losses = epoch_fused_sgd(
                params, xp, yp, seed, lr, batch,
                interpret=interpret,   # False, or an InterpretParams
                axis_name=pmean_axis if axis_size > 1 else None,
                axis_size=axis_size, compute_bf16=compute_bf16,
                steps_per_iter=steps_per_iter, valid_steps=nsteps,
                ring=ring)
        if pmean_axis is not None:
            # the DDP-reported loss: mean over replicas of the shard-local
            # per-step means (params are already lockstep-identical)
            losses = jax.lax.pmean(losses, pmean_axis)
        out = ((losses, (params, key)) if snapshots else losses)
        return (params, key), out

    return epoch


def make_run_fn(lr: float, *, dtype: str = "float32", kernel: str = "xla",
                interpret: bool = False, snapshots: bool = False,
                unroll: int = 1, superstep: int = 1, model: str = "mlp",
                param_scale: int = 1) -> Callable:
    """Serial analog of make_dp_run_fn: the whole E-epoch run as ONE jitted
    nested-scan program, optionally with per-epoch params snapshots.

    `unroll` unrolls the inner (per-step) scan body: the steps stay strictly
    sequential (each SGD update feeds the next); XLA emits `unroll` step
    bodies per loop iteration. Measured on hardware this is a NEGATIVE
    result — 10-27% slower than unroll=1 on both kernels (docs/PERF.md:
    loop bookkeeping is not the bottleneck, and the longer body schedules
    worse). The knob exists to reproduce that measurement.

    `superstep` (kernel='pallas_epoch' only; K in {1,2,4,8}): K SGD steps
    per epoch-kernel grid iteration — identical math, amortized
    per-iteration cost (ops.pallas_step.epoch_fused_sgd).

    `model`/`param_scale` (models/zoo.py) select the workload; non-default
    models need kernel='xla' (the Pallas kernels hard-code the reference
    MLP) and are rejected by name."""
    from ..models.zoo import is_default_model, resolve_model
    _check_kernel(kernel, dtype)
    _check_superstep(superstep, kernel)
    apply_fn = resolve_model(model, param_scale).apply
    if not is_default_model(model, param_scale) and kernel != "xla":
        raise ValueError(
            f"model={model!r} param_scale={param_scale} needs the XLA scan "
            f"body; kernel={kernel!r} hard-codes the reference MLP's VMEM "
            f"block shapes — use kernel='xla'")
    compute_dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32

    def body(carry, batch_idx, x_all, y_all):
        params, key = carry
        key, sub = jax.random.split(key)
        x = _gathered_x(x_all, batch_idx, compute_dt)
        y = jnp.take(y_all, batch_idx, axis=0)
        loss, grads = _loss_and_grads(params, x, y, sub, kernel, interpret,
                                      apply_fn=apply_fn)
        return (sgd_step(params, grads, lr), key), loss

    if kernel == "pallas_epoch":
        if unroll != 1:
            raise ValueError(
                "kernel 'pallas_epoch' has no per-step scan to unroll (the "
                "whole epoch is one kernel); unroll is only meaningful for "
                "the per-step kernels — drop unroll or use kernel='pallas'")
        @partial(jax.jit, donate_argnums=(0, 1))
        def run_epochal(params, key, x_all, y_all, idxs):
            epoch = _make_epochal_body(x_all, y_all, lr, interpret=interpret,
                                       snapshots=snapshots,
                                       compute_bf16=dtype == "bfloat16",
                                       steps_per_iter=superstep)
            (params, key), out = jax.lax.scan(epoch, (params, key), idxs)
            if snapshots:
                losses, (p_snaps, k_snaps) = out
                return params, key, losses, (p_snaps, k_snaps)
            return params, key, out

        return run_epochal

    @partial(jax.jit, donate_argnums=(0, 1))
    def run(params, key, x_all, y_all, idxs):
        step = partial(body, x_all=x_all, y_all=y_all)

        def epoch(carry, idx_e):
            carry, losses = jax.lax.scan(step, carry, idx_e, unroll=unroll)
            return carry, ((losses, carry) if snapshots else losses)

        (params, key), out = jax.lax.scan(epoch, (params, key), idxs)
        if snapshots:
            losses, (p_snaps, k_snaps) = out
            return params, key, losses, (p_snaps, k_snaps)
        return params, key, out

    return run


def _dp_step_body(x_all, y_all, me, lr, compute_dt, kernel="xla",
                  interpret=False, comm="pmean", n_dev=1,
                  bf16_rounding="nearest", overlap=False,
                  quant_block=None, error_feedback=True,
                  bucket_elems=None, apply_fn=None):
    """The shared per-step scan body of the DP programs: gather this
    replica's rows, fwd/bwd with a replica-distinct dropout key, then the
    selected gradient-communication strategy (`comm`,
    parallel/collectives.py) — pmean + replicated SGD (the DDP baseline),
    reduce-scatter + sharded update + all-gather, bf16-compressed
    allreduce, or the int8 error-feedback quantized allreduce (whose
    residual rides the scan carry as a third element, device-varying).
    `overlap=True` bucket-pipelines the pmean/bf16 collectives."""
    from ..parallel import collectives
    qb = collectives.QUANT_BLOCK if quant_block is None else quant_block
    be = (collectives.DEFAULT_BUCKET_ELEMS if bucket_elems is None
          else bucket_elems)
    stateful = collectives.carries_state(comm, error_feedback)

    def body(carry, batch_idx):
        if stateful:
            params, key, resid = carry
        else:
            params, key = carry
        key, sub = jax.random.split(key)
        rkey = jax.random.fold_in(sub, me)
        x = _gathered_x(x_all, batch_idx, compute_dt)
        y = jnp.take(y_all, batch_idx, axis=0)
        loss, grads = _loss_and_grads(params, x, y, rkey, kernel, interpret,
                                      apply_fn=apply_fn)
        loss = jax.lax.pmean(loss, DATA_AXIS)
        if comm == "pmean" and not overlap:
            grads = jax.lax.pmean(grads, DATA_AXIS)  # the DDP allreduce-mean
            params = sgd_step(params, grads, lr)
        elif comm == "int8":
            params, new_r = collectives.int8_apply_gradients(
                params, grads, lr, DATA_AXIS, n_dev,
                resid=resid.reshape(-1) if stateful else None,
                bucket_elems=be, quant_block=qb)
            if stateful:
                resid = new_r.reshape(resid.shape)
        else:
            rnd = (jax.random.fold_in(rkey, 7)
                   if bf16_rounding == "stochastic" else None)
            params = collectives.apply_gradients(
                params, grads, lr, DATA_AXIS, comm, n_dev,
                rounding_key=rnd, bucket_elems=be, overlap=overlap)
        return ((params, key, resid) if stateful else (params, key)), loss

    return body


def make_dp_epoch_fn(mesh: Mesh, lr: float, *, dtype: str = "float32",
                     kernel: str = "xla", interpret: bool = False,
                     comm: str = "pmean",
                     bf16_rounding: str = "nearest",
                     overlap: bool = False, quant_block: int | None = None,
                     error_feedback: bool = True,
                     bucket_elems: int | None = None,
                     model: str = "mlp", param_scale: int = 1) -> Callable:
    """SPMD epoch program over the 'dp' mesh.

    x_all/y_all replicated (each device holds the dataset and gathers its own
    rows — no data-movement collective); idx (nbatches, global_B) sharded on
    dim 1 over 'dp'; per-step gradient communication follows `comm` exactly
    like parallel.ddp.make_dp_train_step. Dropout keys fold in the replica
    index (independent masks per replica, SURVEY.md §7 item 4).

    Comm-state strategies (int8 with error feedback) make the epoch
    (params, key, x_all, y_all, idx, resid) -> (params', key', losses,
    resid'); `.comm_state` on the returned fn says which arity applies.

    One epoch is the one-element case of the fused multi-epoch program
    (tests prove the equivalence), so this just wraps make_dp_run_fn.
    """
    from ..parallel import collectives
    run = make_dp_run_fn(mesh, lr, dtype=dtype, kernel=kernel,
                         interpret=interpret, comm=comm,
                         bf16_rounding=bf16_rounding, overlap=overlap,
                         quant_block=quant_block,
                         error_feedback=error_feedback,
                         bucket_elems=bucket_elems,
                         model=model, param_scale=param_scale)
    if collectives.carries_state(comm, error_feedback):
        jitted_ef = jax.jit(
            lambda params, key, x_all, y_all, idx, resid:
                run(params, key, x_all, y_all, idx[None], resid),
            donate_argnums=(0, 1, 5))

        def epoch_ef(params, key, x_all, y_all, idx, resid):
            params, key, losses, resid = jitted_ef(params, key, x_all,
                                                   y_all, idx, resid)
            return params, key, losses[0], resid

        epoch_ef.comm_state = True
        return epoch_ef

    jitted = jax.jit(
        lambda params, key, x_all, y_all, idx:
            run(params, key, x_all, y_all, idx[None]),
        donate_argnums=(0, 1))

    def epoch(params, key, x_all, y_all, idx):
        params, key, losses = jitted(params, key, x_all, y_all, idx)
        return params, key, losses[0]

    epoch.comm_state = False
    return epoch


def make_dp_run_fn(mesh: Mesh, lr: float, *, dtype: str = "float32",
                   kernel: str = "xla", interpret: bool = False,
                   snapshots: bool = False, unroll: int = 1,
                   superstep: int = 1, ring: str = "auto",
                   comm: str = "pmean",
                   bf16_rounding: str = "nearest",
                   overlap: bool = False, quant_block: int | None = None,
                   error_feedback: bool = True,
                   bucket_elems: int | None = None,
                   model: str = "mlp", param_scale: int = 1) -> Callable:
    """Multi-epoch fused DP program: (params, key, x_all, y_all, idxs) ->
    (params', key', losses (E, nbatches)) with idxs (E, nbatches, global_B)
    sharded on the batch dim.

    A nested lax.scan (epochs over steps) turns an E-epoch training run into
    ONE device program — zero host round-trips inside, which is what a
    remote/tunneled TPU needs (a per-epoch sync costs a full RTT) and what
    lets XLA keep the whole run in its pipeline. Epoch reshuffles stay exact:
    the host precomputes each epoch's sampler indices into idxs.

    `snapshots=True` adds a 4th output `(params_snaps, key_snaps)`: the
    params pytree AND the RNG key stacked per epoch end (E leading dim) —
    what `fit_cached(fused=True)` evaluates afterwards to print the
    reference's per-epoch val_loss (and hand epoch hooks a faithful
    TrainState) without breaking the fused program (118k params ->
    ~0.5 MB/epoch, trivial).

    `ring` (kernel='pallas_epoch', multi-device only) picks the in-kernel
    allreduce strategy — 'allgather' / 'reduce_scatter' / 'auto' (slot-
    budget switch); see ops.pallas_step.epoch_fused_sgd.

    `comm` selects the per-step gradient communication
    (parallel/collectives.py: 'pmean' / 'sharded' / 'bf16' / 'int8') for
    the scan-body kernels, `overlap` the bucket-pipelined scheduling;
    kernel='pallas_epoch' owns its comms in-kernel (the ICI ring) and
    rejects a non-default comm (and overlap) by name.

    Comm-state strategies (int8 with error feedback) change the
    signature: (params, key, x_all, y_all, idxs, resid) -> (params', key',
    losses, resid'[, snaps]) — losses stay at index 2, the residual rides
    right behind them, snapshots (which do NOT include per-epoch residual
    copies — a fused-run epoch checkpoint resumes with a zero residual,
    bounded drift) stay last. `.comm_state` on the returned fn says which
    arity applies. `model`/`param_scale` select the workload
    (models/zoo.py); non-default models need the XLA scan body (the
    Pallas kernels hard-code the reference MLP) and are rejected by name
    elsewhere.
    """
    from ..models.zoo import is_default_model, resolve_model
    from ..parallel import collectives
    from ..parallel.ddp import _mesh_axis_size
    _check_kernel(kernel, dtype)
    _check_superstep(superstep, kernel)
    n_dev = _mesh_axis_size(mesh)  # Mesh or AbstractMesh (export lowering)
    _check_ring(ring, kernel, n_dev)
    collectives.validate_comm(comm)
    collectives.validate_bf16_rounding(bf16_rounding, comm)
    collectives.validate_int8_options(
        collectives.QUANT_BLOCK if quant_block is None else quant_block,
        error_feedback, comm)
    apply_fn = resolve_model(model, param_scale).apply
    if not is_default_model(model, param_scale) and kernel != "xla":
        raise ValueError(
            f"model={model!r} param_scale={param_scale} needs the XLA scan "
            f"body; kernel={kernel!r} hard-codes the reference MLP's VMEM "
            f"block shapes — use kernel='xla'")
    if comm != "pmean" and kernel == "pallas_epoch":
        raise ValueError(
            f"comm={comm!r} selects the per-step XLA gradient collective; "
            f"kernel 'pallas_epoch' performs its allreduce IN-kernel (the "
            f"ICI ring — pick it with ring=) and never reads comm")
    if overlap and kernel == "pallas_epoch":
        raise ValueError(
            "overlap=True bucket-pipelines the per-step XLA gradient "
            "collectives; kernel 'pallas_epoch' owns its comms IN-kernel "
            "and never reads it")
    stateful = collectives.carries_state(comm, error_feedback)
    compute_dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    use_pallas = kernel.startswith("pallas")

    if kernel == "pallas_epoch":
        # The DDP epoch kernel: whole epoch per replica as one kernel,
        # per-step mean gradients via the IN-KERNEL ICI ring allreduce
        # (ops/pallas_step.py _make_epoch_kernel's dp path). A 1-device mesh
        # degenerates to the serial kernel (no ring). EXPERIMENTAL at n>1:
        # compiles and is semantically pinned by the n=1 tests + the pure-JAX
        # oracle, but no multi-chip hardware existed this session to execute
        # the ring (docs/PERF.md).
        if unroll != 1:
            raise ValueError(
                "kernel 'pallas_epoch' has no per-step scan to unroll; drop "
                "unroll or use kernel='pallas'")
        if interpret is True and n_dev > 1:
            # An InterpretParams instance passes: the TPU-semantics
            # simulator models the ring's remote DMAs + semaphores, and CI
            # executes the real DP kernel under it (test_pallas_step.py).
            raise ValueError(
                "kernel 'pallas_epoch' on a multi-device mesh uses ICI "
                "remote DMAs with no plain-interpreter lowering; pass "
                "interpret=pltpu.InterpretParams() (TPU-semantics "
                "simulator) or use kernel='pallas' for interpreted DP")
        # No mesh-size cap: epoch_fused_sgd's ring='auto' picks the
        # all-gather ring up to EPOCH_KERNEL_MAX_DEVICES replicas and the
        # near-constant-VMEM reduce-scatter ring beyond it.
        if superstep != 1 and n_dev > 1:
            raise ValueError(
                f"superstep={superstep} is single-replica only (the DP "
                f"ring's per-iteration handshake); use superstep=1 on the "
                f"{n_dev}-device mesh")

        def epoch_shard_fn(params, key, x_all, y_all, idxs):
            epoch = _make_epochal_body(x_all, y_all, lr, interpret=interpret,
                                       snapshots=snapshots,
                                       pmean_axis=DATA_AXIS,
                                       axis_size=n_dev,
                                       compute_bf16=dtype == "bfloat16",
                                       steps_per_iter=superstep, ring=ring)
            (params, key), out = jax.lax.scan(epoch, (params, key), idxs)
            if snapshots:
                losses, (p_snaps, k_snaps) = out
                return params, key, losses, (p_snaps, k_snaps)
            return params, key, out

        nout = 4 if snapshots else 3
        sharded_epochal = shard_map(
            epoch_shard_fn, mesh=mesh,
            in_specs=(P(), P(), P(), P(), P(None, None, DATA_AXIS)),
            out_specs=(P(),) * nout, check_vma=False)

        @partial(jax.jit, donate_argnums=(0, 1))
        def run_ep(params, key, x_all, y_all, idxs):
            return sharded_epochal(params, key, x_all, y_all, idxs)

        return run_ep

    def shard_fn(params, key, x_all, y_all, idxs, resid=None):
        if not use_pallas:
            # Differentiate per-replica copies so the allreduce in the body
            # is the only grad reduction (see parallel/ddp.py). The pallas
            # body's grads come from the kernel, not an autodiff transpose,
            # so there is nothing to protect (and check_vma is off below).
            params = _pvary(params, DATA_AXIS)
        me = jax.lax.axis_index(DATA_AXIS)
        body = _dp_step_body(x_all, y_all, me, lr, compute_dt,
                             kernel=kernel, interpret=interpret,
                             comm=comm, n_dev=n_dev,
                             bf16_rounding=bf16_rounding, overlap=overlap,
                             quant_block=quant_block,
                             error_feedback=error_feedback,
                             bucket_elems=bucket_elems, apply_fn=apply_fn)

        def epoch(carry, idx_e):
            carry, losses = jax.lax.scan(body, carry, idx_e, unroll=unroll)
            if snapshots:
                # snapshots stay (params, key) pairs in BOTH arities: the
                # residual is comm state, not trajectory state (docstring)
                out = (losses, carry[:2])
            else:
                out = losses
            return carry, out

        carry0 = (params, key, resid) if stateful else (params, key)
        carry, out = jax.lax.scan(epoch, carry0, idxs)
        params, key = carry[:2]
        if comm == "pmean" and not overlap:
            # per-replica lockstep copies: pmean re-replicates for output.
            # The other strategies end each step in an all-gather/psum
            # whose outputs are already value-identical on every device —
            # a further pmean would only add a run-final collective for
            # nothing.
            params = jax.tree_util.tree_map(
                lambda a: jax.lax.pmean(a, DATA_AXIS), params)
        tail = (carry[2],) if stateful else ()
        if snapshots:
            losses, (p_snaps, k_snaps) = out
            # params snapshots are per-replica copies kept in lockstep by the
            # in-body allreduce: pmean re-replicates them for output. The key
            # evolves identically on every replica (pure split chain) and is
            # not a float — no reduction, it is already replicated.
            if comm == "pmean" and not overlap:
                p_snaps = jax.tree_util.tree_map(
                    lambda a: jax.lax.pmean(a, DATA_AXIS), p_snaps)
            return (params, key, losses) + tail + ((p_snaps, k_snaps),)
        return (params, key, out) + tail

    nout = 3 + (1 if snapshots else 0) + (1 if stateful else 0)
    in_specs = [P(), P(), P(), P(), P(None, None, DATA_AXIS)]
    out_specs = [P()] * nout
    if stateful:
        in_specs.append(P(DATA_AXIS))       # resid: per-device local state
        out_specs[3] = P(DATA_AXIS)         # (params, key, losses, resid..)
    sharded = shard_map(
        shard_fn, mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=tuple(out_specs),
        check_vma=not use_pallas and comm == "pmean" and not overlap)

    if stateful:
        jitted_ef = jax.jit(sharded, donate_argnums=(0, 1, 5))

        def run_ef(params, key, x_all, y_all, idxs, resid):
            return jitted_ef(params, key, x_all, y_all, idxs, resid)

        run_ef.comm_state = True
        # declared donation contract, cross-checked against the traced
        # program by statics/jaxpr_audit.py's donation-aliasing contract
        run_ef.donates = ("params", "key", "resid")
        return run_ef

    jitted = jax.jit(sharded, donate_argnums=(0, 1))

    def run(params, key, x_all, y_all, idxs):
        return jitted(params, key, x_all, y_all, idxs)

    run.comm_state = False
    run.donates = ("params", "key")
    return run


def fit_cached(state: TrainState, x_train, y_train, sampler, x_test, y_test, *,
               epochs: int, batch_size: int, lr: float,
               mesh: Optional[Mesh] = None, dtype: str = "float32",
               kernel: str = "xla", interpret: bool = False,
               fused: bool = False, comm: str = "pmean",
               bf16_rounding: str = "nearest",
               overlap: bool = False, quant_block: int | None = None,
               error_feedback: bool = True,
               model: str = "mlp", param_scale: int = 1,
               log: Callable[[str], None] = print,
               epoch_hook: Callable | None = None,
               start_epoch: int = 0, start_offset: int = 0,
               ckpt_every_steps: int = 0,
               step_hook: Callable | None = None,
               eval_perm: Callable | None = None,
               watchdog=None, prefetch_depth: int = 1,
               dispatch_profiler=None) -> TrainState:
    """The `fit` loop with the dataset cached in HBM and epochs scanned.

    `batch_size` is the GLOBAL batch (sampler shards rows per process; with a
    mesh the index array is device-sharded on the batch dim). Prints the same
    reference-format epoch line as `fit`.

    `fused=True` runs ALL epochs as ONE device program (the bench.py path):
    per-epoch params snapshots come back with the losses, so the per-epoch
    val_loss/accuracy lines and epoch hooks still happen — just after the
    device is done rather than interleaved. Throughput in the epoch line is
    then the run average (one wall measurement / E).

    `start_epoch` resumes at a GLOBAL epoch index: epochs
    [start_epoch, epochs) run with their uninterrupted sampler reshuffles
    (set_epoch uses global numbers) and epoch-line numbering — the
    outage-resume path (cli.train --start_epoch); with epoch k-1's params
    and key in `state`, the resumed trajectory is bitwise the unbroken one.

    Step granularity (`train/ckpt_manager.py`): `ckpt_every_steps=N` CHUNKS
    each epoch's scan at every N steps — the host regains control at each
    boundary to run `step_hook(epoch, offset, global_step, state)` (same
    contract and cadence as the streaming `fit`) and the `kill`/`step`
    fault point. Per-step math is untouched: the chunks are consecutive
    slices of the same sequential scan, and the per-STEP key-split chain
    crosses chunk boundaries unchanged, so a chunked run — and a
    `start_offset` mid-epoch resume, which skips the first `offset` index
    rows of the first run epoch — stays bitwise on the unchunked
    trajectory. `kernel='pallas_epoch'` splits its key once per EPOCH, so
    chunking would fork its dropout stream: rejected by name. `fused=True`
    has no mid-run host control at all: likewise rejected.

    `watchdog` (telemetry.health.Watchdog) observes at every chunk
    boundary — the granularity at which this trainer already fetches its
    per-step losses, so live health detection costs no extra host syncs
    (with `ckpt_every_steps=N` the detection window is N steps; unchunked,
    one epoch). The scan programs carry no per-step health aux (the aux
    fold lives in the streaming steps), so detection here is loss- and
    timing-based. Each fetched chunk is also the `nan` value-fault point
    (`faultpoints.poison_array`). `fused=True` rejects a watchdog by name:
    one whole-run device program has no live host to watch from.

    `prefetch_depth` (the input pipeline's H2D stage, pipeline/prefetch.py)
    keeps that many chunk INDEX arrays' device placements in flight: chunk
    k+1's sharded `device_put` dispatches while chunk k's program computes,
    so the host-synchronous placement cost leaves the critical path. The
    placed values are identical at any depth — chunking math, per-step RNG
    chain, and the epoch-granular fetch budget are all untouched (bitwise,
    pinned by tests/test_pipeline.py).

    `dispatch_profiler` (telemetry.dispatch.DispatchProfiler) attributes
    the CHUNK boundary here — prestep is the chunk bookkeeping, dispatch
    the epoch/chunk program call, sync_wait the per-chunk loss fetch;
    `fused=True` rejects it by name (one whole-run device program has no
    per-step host boundary to decompose). NullProfiler default adds zero
    syncs (docs/OBSERVABILITY.md §Dispatch forensics).
    """
    import time

    from ..models.zoo import resolve_model
    from ..parallel import collectives
    from ..utils import faultpoints

    model_apply = resolve_model(model, param_scale).apply
    # int8-with-error-feedback threads the residual state through every
    # program call (and into the TrainState the hooks/watchdog see, so
    # step checkpoints round-trip it)
    stateful = (mesh is not None
                and collectives.carries_state(comm, error_feedback))
    if not 0 <= start_epoch <= epochs:
        raise ValueError(f"start_epoch={start_epoch} outside [0, {epochs}]")
    if start_offset < 0:
        raise ValueError(f"start_offset={start_offset} must be >= 0")
    if fused and (ckpt_every_steps or step_hook is not None or start_offset):
        raise ValueError(
            "step-granular checkpointing (ckpt_every_steps/step_hook/"
            "start_offset) needs per-chunk host control; fused=True runs "
            "all epochs as ONE device program — use plain cached mode")
    if fused and watchdog is not None:
        raise ValueError(
            "live health monitoring (watchdog) observes at chunk/epoch "
            "boundaries the host controls; fused=True runs all epochs as "
            "ONE device program with no live host — use plain cached mode")
    if fused and getattr(dispatch_profiler, "armed", False):
        raise ValueError(
            "dispatch profiling decomposes the per-step/per-chunk host "
            "boundary; fused=True runs all epochs as ONE device program "
            "with no such boundary — use plain cached or streaming mode")
    if kernel == "pallas_epoch" and (ckpt_every_steps or start_offset):
        raise ValueError(
            "step-granular checkpointing chunks the epoch scan, but kernel "
            "'pallas_epoch' derives its whole epoch's dropout stream from "
            "ONE per-epoch key split — chunking would fork the RNG chain; "
            "use kernel='xla'/'pallas' for step-granular checkpoints")

    if mesh is not None:
        # replicate_state / make_array_from_callback build GLOBAL arrays, so
        # this path works when `mesh` spans multiple processes too: every
        # process holds the (tiny) dataset and the same host-side sampler
        # state, and contributes its devices' shards.
        from ..parallel.ddp import replicate_state
        x_all = replicate_state(mesh, resident_images(x_train))
        y_all = replicate_state(mesh, np.asarray(y_train, np.int32))
        epoch_fn = None if fused else make_dp_epoch_fn(
            mesh, lr, dtype=dtype, kernel=kernel, interpret=interpret,
            comm=comm, bf16_rounding=bf16_rounding, overlap=overlap,
            quant_block=quant_block, error_feedback=error_feedback,
            model=model, param_scale=param_scale)
        idx_sharding = NamedSharding(mesh, P(None, DATA_AXIS))
    else:
        x_all = jax.device_put(resident_images(x_train))
        y_all = jax.device_put(np.asarray(y_train, np.int32))
        epoch_fn = None if fused else make_epoch_fn(
            lr, dtype=dtype, kernel=kernel, interpret=interpret,
            model=model, param_scale=param_scale)
        idx_sharding = None

    # Test set to device once, not per epoch (mirrors loop.fit's hoist).
    x_test_dev, y_test_dev = jnp.asarray(x_test), jnp.asarray(y_test)
    params, key = state.params, state.key
    resid = (collectives.place_comm_state(
                 mesh, params,
                 host=(np.asarray(state.resid)
                       if state.resid is not None else None),
                 quant_block=(collectives.QUANT_BLOCK if quant_block is None
                              else quant_block))
             if stateful else None)
    # DP runs publish the ddp.* comm metrics (same recorder as loop.fit) —
    # except kernel='pallas_epoch', whose allreduce happens IN-kernel via
    # its own ring strategy: the recorder's ring-model bytes and XLA-pmean
    # probe would attribute a collective that program never runs.
    ddp_record = (make_ddp_comm_recorder(mesh, comm,
                                         int(mesh.devices.size), params,
                                         quant_block=quant_block)
                  if mesh is not None and kernel != "pallas_epoch"
                  else None)

    if fused:
        if epochs <= start_epoch:  # match the per-epoch loop's no-op
            return TrainState(params, key, resid)
        # ONE program for the whole run (zero host round-trips inside),
        # then replay the per-epoch reporting from the snapshots.
        run_epochs = list(range(start_epoch, epochs))
        idxs = []
        for epoch in run_epochs:
            sampler.set_epoch(epoch)
            idxs.append(epoch_batch_indices(sampler, batch_size))
        idxs = np.stack(idxs)
        if mesh is not None:
            run = make_dp_run_fn(mesh, lr, dtype=dtype, kernel=kernel,
                                 interpret=interpret, snapshots=True,
                                 comm=comm, bf16_rounding=bf16_rounding,
                                 overlap=overlap, quant_block=quant_block,
                                 error_feedback=error_feedback,
                                 model=model, param_scale=param_scale)
            sh3 = NamedSharding(mesh, P(None, None, DATA_AXIS))
            idxs = jax.make_array_from_callback(
                idxs.shape, sh3, lambda s, _i=idxs: _i[s])
        else:
            run = make_run_fn(lr, dtype=dtype, kernel=kernel,
                              interpret=interpret, snapshots=True,
                              model=model, param_scale=param_scale)
        t0 = time.perf_counter()
        if stateful:
            params, key, losses, resid, (p_snaps, k_snaps) = run(
                params, key, x_all, y_all, idxs, resid)
        else:
            params, key, losses, (p_snaps, k_snaps) = run(
                params, key, x_all, y_all, idxs)
        losses = np.asarray(losses)                      # sync: run finished
        per_epoch_dt = (time.perf_counter() - t0) / len(run_epochs)
        # one span for the whole fused program — there is no per-epoch
        # phase split inside a single device program to report
        get_tracer().complete_span("fused_run", time.perf_counter() - t0,
                                   epochs=len(run_epochs),
                                   steps=int(losses.size))
        if ddp_record is not None:
            ddp_record(int(losses.size), params)
        # Replay ALL epochs' val lines from one vmapped eval program + one
        # fetch — per-epoch evaluate() calls here would cost E dispatch
        # round-trips (a full tunnel RTT each on a remote TPU).
        ps_all, corr_all = make_snapshot_eval_step(model_apply)(
            p_snaps, x_test_dev, y_test_dev)
        ps_all, corr_all = np.asarray(ps_all), np.asarray(corr_all)
        for i, epoch in enumerate(run_epochs):
            p_e = jax.tree_util.tree_map(lambda a, _i=i: a[_i], p_snaps)
            val = val_summary(ps_all[i], corr_all[i], batch_size,
                              perm=eval_perm(epoch) if eval_perm else None)
            log(epoch_summary(epoch, losses[i], batch_size, val,
                              per_epoch_dt))
            if epoch_hook is not None:
                # faithful TrainState: this epoch's params AND RNG key, so a
                # hook that checkpoints state resumes the same trajectory as
                # a non-fused run would. (No per-epoch residual snapshots:
                # an int8 run resumed from such a checkpoint reseeds a zero
                # residual — bounded drift, documented on make_dp_run_fn.)
                epoch_hook(epoch, TrainState(p_e, k_snaps[i]))
        return TrainState(params, key, resid)

    tracer = get_tracer()
    # NullProfiler unless --profile_dispatch armed one (zero-sync default)
    prof = (dispatch_profiler if dispatch_profiler is not None
            else NullProfiler())
    eval_step = make_eval_step(model_apply)
    for epoch in range(start_epoch, epochs):
        with tracer.span("epoch", epoch=epoch):
            t0 = time.perf_counter()
            sampler.set_epoch(epoch)
            idx = epoch_batch_indices(sampler, batch_size)
            nb = idx.shape[0]
            offset = start_offset if epoch == start_epoch else 0
            if offset >= nb:
                raise ValueError(
                    f"start_offset={offset} >= the epoch's {nb} steps (a "
                    f"committed step checkpoint never records a full-epoch "
                    f"offset)")
            # Chunk boundaries at epoch-local multiples of ckpt_every_steps
            # (0 = the whole remaining epoch as one program, today's
            # behavior). A resumed run's boundaries therefore coincide with
            # the unbroken run's past the resume point; the chunks are
            # consecutive slices of the same sequential scan either way, so
            # the math is chunking-invariant.
            bounds = []
            c0 = offset
            while c0 < nb:
                c1 = (min(nb, (c0 // ckpt_every_steps + 1) * ckpt_every_steps)
                      if ckpt_every_steps else nb)
                bounds.append((c0, c1))
                c0 = c1

            def _place(part):
                # sharding-aware device placement of one chunk's index
                # rows; prefetched below so chunk k+1's H2D dispatches
                # while chunk k's program computes (pipeline/prefetch.py)
                if idx_sharding is not None:
                    return jax.make_array_from_callback(
                        part.shape, idx_sharding, lambda s, _i=part: _i[s])
                return jax.device_put(part)

            placed = pipeline_prefetch(
                (idx[b0:b1] for b0, b1 in bounds),
                depth=prefetch_depth, put=_place)
            loss_parts = []
            for (c0, c1), part in zip(bounds, placed):
                # the chunk boundary IS this trainer's step boundary:
                # prestep opens with the placed chunk in hand
                prof.mark_prestep()
                t_chunk = time.perf_counter()
                # sampled device-idle bracket drains the previous
                # chunk's live params output (same contract as loop.fit)
                prof.begin_dispatch(params)
                if stateful:
                    params, key, part_losses, resid = epoch_fn(
                        params, key, x_all, y_all, part, resid)
                else:
                    params, key, part_losses = epoch_fn(params, key,
                                                        x_all, y_all, part)
                prof.end_dispatch(epoch * nb + c0)
                t_sync = time.perf_counter()
                part_np = np.asarray(part_losses)           # chunk sync
                prof.note_sync_wait(time.perf_counter() - t_sync)
                # the nan value-fault point, chunk form: poisons only the
                # fetched loss curve (params untouched) — the watchdog's
                # deterministic chaos input
                part_np = faultpoints.poison_array(
                    "loss", part_np, first_step=epoch * nb + c0 + 1,
                    epoch=epoch)
                loss_parts.append(part_np)
                _fire_step_hook(step_hook, ckpt_every_steps, nb, epoch,
                                c1 - 1, params, key, resid=resid)
                # hook BEFORE the kill point: an injected kill at step K
                # must never race the step-K checkpoint it tests
                faultpoints.fire("step", step=epoch * nb + c1, epoch=epoch)
                if watchdog is not None:
                    # chunk-granular live health: the losses are already on
                    # host (the chunk sync above); positions follow
                    # step_ckpt_positions so a checkpoint-and-warn rescue
                    # records exactly what a step checkpoint would. May
                    # raise TrainingHealthError under the abort policy.
                    ck_ep, ck_off = step_ckpt_positions(nb, epoch, c1 - 1)
                    watchdog.observe(
                        part_np, state=TrainState(params, key, resid),
                        epoch=epoch,
                        step=epoch * nb + c1,
                        ckpt_epoch=ck_ep, ckpt_offset=ck_off,
                        dt_s=time.perf_counter() - t_chunk,
                        imgs=part_np.size * batch_size)
            losses = np.concatenate(loss_parts)
            # the per-chunk loss fetches block until each chunk's program
            # finished (ONE fetch per epoch when unchunked), so this is
            # the whole device phase — the cached path has no separate
            # data wait (the dataset lives in HBM)
            tracer.complete_span("step_compute", time.perf_counter() - t0,
                                 steps=int(losses.size))
            # no independent per-call timer here (the chunk sync is part
            # of the same host interval) — the window defaults to the
            # profiler's own dispatch total
            prof.flush_epoch(epoch, steps=len(bounds))
            t_eval = time.perf_counter()
            val = evaluate(eval_step, params, x_test_dev, y_test_dev,
                           batch_size,
                           perm=eval_perm(epoch) if eval_perm else None)
            tracer.complete_span("eval", time.perf_counter() - t_eval)
            if ddp_record is not None:
                ddp_record(int(losses.size), params)
            log(epoch_summary(epoch, losses, batch_size, val,
                              time.perf_counter() - t0))
            state = TrainState(params, key, resid)
            if epoch_hook is not None:
                epoch_hook(epoch, state)
    return state
