"""Crash-consistent step-granular checkpointing.

The reference does ONE final params-only `torch.save` with no load path
(SURVEY.md §5.4); this framework's prior resume was epoch-granular only —
a preemption mid-epoch lost the whole epoch, and a torn file surfaced as a
raw msgpack error. This manager closes both gaps: a run killed at ANY step
resumes bitwise on the unbroken trajectory, and a corrupted checkpoint
degrades to the previous intact one instead of crashing the relaunch.

One checkpoint = two files in the manager directory:

    step_00000012.msgpack   payload — flax msgpack of the params pytree,
                            the SAME bytes `save_checkpoint` writes (so
                            `load_checkpoint` reads a payload directly)
    step_00000012.json      manifest — the COMMIT record:
        {"v": 1, "step": 12,         global steps completed
         "epoch": 1, "offset": 4,    sampler position: epoch in progress +
                                     batches already consumed in it (the
                                     ShardedSampler permutation is a pure
                                     function of seed+epoch, so this pair
                                     IS the full sampler state)
         "key": [...], "impl": "threefry2x32",   RNG key chain (key_data
                                     words; tiny, so it lives here, not in
                                     the payload)
         "payload": "step_00000012.msgpack",
         "bytes": N, "crc32": C,     payload size + CRC32 stamp
         "t_wall": ...}

Crash consistency:
  * write order is payload-tmp -> fsync -> rename, THEN manifest-tmp ->
    rename. The manifest is the commit: a crash at any instant leaves
    either a fully committed checkpoint or an uncommitted one (payload
    without manifest / stray .tmp), never a half-truth;
  * `restore_latest` walks manifests newest-first and takes the first
    INTACT one — manifest parses, payload exists, size matches, CRC32
    matches, msgpack decodes. Every rejected candidate is recorded to the
    telemetry flight recorder (`checkpoint_fallback`) so a relaunch that
    skipped a torn file leaves evidence of it;
  * rotation deletes beyond keep-last-N, manifest FIRST (uncommit) then
    payload — interruption mid-rotation again leaves only committed or
    uncommitted states.

Telemetry: every save records `checkpoint.save_s` (histogram) and
`checkpoint.bytes` (counter) into the unified registry, so `--telemetry`
runs stamp checkpoint cost into the end-of-run snapshot
(`scripts/check_telemetry.py --require checkpoint.` gates on it).

Fault points: `utils/faultpoints.fire("ckpt_save", step=...)` runs just
before the payload rename — `PDMT_FAULT=ckpt_save_io:step=K` makes save K
fail with an OSError while the directory stays consistent (pinned by
tests/test_ckpt_manager.py).
"""

from __future__ import annotations

import json
import os
import re
import time
import zlib
from dataclasses import dataclass
from typing import Any, List

import numpy as np

from .checkpoint import CheckpointError

_SCHEMA = 1
_NAME_RE = re.compile(r"^step_(\d{8})\.json$")
_PAYLOAD_RE = re.compile(r"^step_(\d{8})\.msgpack$")
_RESID_RE = re.compile(r"^step_(\d{8})\.resid\.msgpack$")


def _resid_name(step: int) -> str:
    return f"step_{step:08d}.resid.msgpack"


def _nonfinite_leaves(tree, prefix: str = "") -> List[str]:
    """Paths of float leaves holding NaN/Inf — the restore-time divergence
    check (params are plain nested dicts of numpy arrays here; integer
    leaves are exempt by dtype)."""
    if isinstance(tree, dict):
        out: List[str] = []
        for k, v in tree.items():
            out.extend(_nonfinite_leaves(v, f"{prefix}/{k}"))
        return out
    arr = np.asarray(tree)
    if (np.issubdtype(arr.dtype, np.floating)
            and not np.isfinite(arr).all()):
        return [prefix or "/"]
    return []


def _manifest_name(step: int) -> str:
    return f"step_{step:08d}.json"


def _payload_name(step: int) -> str:
    return f"step_{step:08d}.msgpack"


@dataclass
class StepCheckpoint:
    """One restored checkpoint: everything a resume needs to replay the
    remaining steps of the unbroken trajectory bitwise."""
    params: Any
    key_data: np.ndarray     # jax.random.key_data words (uint32)
    impl: str                # PRNG engine the key words belong to
    step: int                # global steps completed
    epoch: int               # epoch in progress at save time
    offset: int              # batches already consumed in that epoch
    path: str                # manifest path it came from
    resid: Any = None        # the int8 comm strategy's error-feedback
                             # residual ((n_devices, elems) f32), when the
                             # save carried one — None otherwise (every
                             # pre-int8 manifest restores as None)
    meta: dict = None        # caller-stamped run geometry (may be empty):
                             # the fields whose change would silently
                             # re-interpret (epoch, offset) — the CLI
                             # stamps global_batch/limit/sampler_rng and
                             # refuses a resume that contradicts them


@dataclass
class RestoreScan:
    """Outcome of one newest-first restorability walk (`scan_restorable`):
    the shared verdict on which checkpoint is promotable. `best` is the
    newest intact AND finite candidate (None when none qualifies);
    `newest_nonfinite` the newest intact-but-diverged one (the resume
    path's last resort, the reload watcher's named refusal); `tried` the
    named defect of every candidate rejected before `best`."""
    best: "StepCheckpoint | None"
    newest_nonfinite: "StepCheckpoint | None"
    tried: List[str]


def geometry_mismatch_message(manifest_meta: dict,
                              requested: dict) -> "str | None":
    """The run-geometry refusal, or None when every stamped field matches.

    Names BOTH complete geometries — the manifest's and the requested
    run's — not just the differing fields: a multi-knob drift (say batch
    AND limit changed by a copy-pasted launch line) is diagnosable from
    the error alone, without re-opening the manifest. Ends by pointing at
    `--reshape` because ONE class of mismatch is now deliberate: an
    elastic shrink/grow changes global_batch by construction, and
    elastic/reshape.py re-maps it instead of refusing (the other fields —
    limit/sampler_rng/model/param_scale — stay hard refusals; reshape
    re-splits a world, it does not reinterpret a dataset or a model)."""
    mismatch = {k: (v, requested[k]) for k, v in manifest_meta.items()
                if k in requested and requested[k] != v}
    if not mismatch:
        return None

    def _fmt(src: dict) -> str:
        return ", ".join(f"{k}={src[k]!r}" for k in sorted(requested)
                         if k in src)

    return ("checkpoint was written under different run geometry; its "
            "(epoch, offset) would address different batches.\n"
            f"  checkpoint geometry: {_fmt(manifest_meta)}\n"
            f"  requested geometry:  {_fmt(requested)}\n"
            "  differing: " + ", ".join(sorted(mismatch)) + "\n"
            "(a deliberate world-size change resumes with --elastic "
            "--reshape global_batch|per_rank — elastic/reshape.py re-maps "
            "the global batch, sampler offset, and int8 residual instead "
            "of refusing)")


def peek_latest_meta(directory: str) -> "dict | None":
    """The newest committed manifest's position + meta stamp — WITHOUT
    touching the payload (no template, no decode, no CRC walk).

    The elastic resume pre-pass (cli.train) needs the manifest's
    global_batch/devices BEFORE the data plane is built — the per-device
    micro-batch under `--reshape global_batch` is derived from it, and the
    data plane sizes its loader from that micro-batch. Falls back past
    unreadable/foreign manifests; returns None when nothing committed.
    Payload intactness is NOT checked here — restore_latest still owns
    that (this peek only shapes the run; the restore verifies it)."""
    try:
        names = os.listdir(directory)
    except OSError:
        return None
    steps = sorted((int(m.group(1)) for n in names
                    if (m := _NAME_RE.match(n))), reverse=True)
    for step in steps:
        try:
            with open(os.path.join(directory, _manifest_name(step))) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if rec.get("v") != _SCHEMA:
            continue
        return {"step": int(rec.get("step", step)),
                "epoch": int(rec.get("epoch", 0)),
                "offset": int(rec.get("offset", 0)),
                "meta": dict(rec.get("meta") or {})}
    return None


class CheckpointManager:
    """Atomic, CRC-stamped, keep-last-N step checkpoints in one directory.

    `save` is rank-agnostic — the CALLER gates on rank 0 (params are
    replicated in DP, identical bytes everywhere, same contract as
    `save_checkpoint`). `restore_latest` is safe from every rank: it only
    reads."""

    def __init__(self, directory: str, *, keep: int = 3):
        if keep < 1:
            raise ValueError(f"keep must be >= 1; got {keep}")
        self.directory = directory
        self.keep = int(keep)

    # -- write side ---------------------------------------------------------

    def save(self, params, key_data, impl: str, *, step: int, epoch: int,
             offset: int, meta: dict | None = None,
             pin: bool = False, resid=None) -> str:
        """Commit one step checkpoint; returns the manifest path.

        Fetches params to host (this is the one deliberate device sync of a
        checkpoint save). Raises CheckpointError on any I/O failure, with
        the temp file cleaned up and prior checkpoints untouched — a failed
        save never costs existing durability.

        `resid` (the int8 comm strategy's error-feedback residual — a
        (n_devices, elems) f32 array) rides as a SECOND payload file
        (`step_N.resid.msgpack`) with its own size/CRC stamp in the
        manifest, written BEFORE the manifest rename so the commit point
        covers both payloads: a resumed int8 run continues the unbroken
        quantization-error accounting instead of reseeding zeros.

        `pin=True` marks the checkpoint exempt from keep-last-N rotation
        (the health watchdog's rescue save uses it: a last-known-good
        pre-divergence checkpoint must not be rotated away by the routine
        saves of a run that keeps training — possibly on garbage — after
        the fatal signal). A pinned checkpoint persists until deleted by
        hand or overwritten by a save at the same step."""
        import jax
        from flax import serialization
        from ..telemetry import get_registry
        from ..utils import faultpoints

        t0 = time.perf_counter()
        os.makedirs(self.directory, exist_ok=True)
        host = jax.tree_util.tree_map(np.asarray, params)
        blob = serialization.to_bytes(host)
        rblob = (serialization.to_bytes(np.asarray(resid, np.float32))
                 if resid is not None else None)
        payload = os.path.join(self.directory, _payload_name(step))
        rpayload = os.path.join(self.directory, _resid_name(step))
        manifest = os.path.join(self.directory, _manifest_name(step))
        tmp = f"{payload}.tmp.{os.getpid()}"
        rtmp = f"{rpayload}.tmp.{os.getpid()}"
        try:
            faultpoints.fire("ckpt_save", step=step, epoch=epoch)
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, payload)
            if rblob is not None:
                with open(rtmp, "wb") as f:
                    f.write(rblob)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(rtmp, rpayload)
            record = {
                "v": _SCHEMA, "step": int(step), "epoch": int(epoch),
                "offset": int(offset),
                "key": [int(w) for w in np.asarray(key_data).ravel()],
                "impl": str(impl),
                "payload": os.path.basename(payload),
                "bytes": len(blob), "crc32": zlib.crc32(blob),
                "meta": dict(meta or {}),
                "t_wall": time.time(),
            }
            if rblob is not None:
                record.update(resid_payload=os.path.basename(rpayload),
                              resid_bytes=len(rblob),
                              resid_crc32=zlib.crc32(rblob))
            if pin:
                record["pinned"] = True
            mtmp = f"{manifest}.tmp.{os.getpid()}"
            with open(mtmp, "w") as f:
                json.dump(record, f)
                f.write("\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(mtmp, manifest)  # <- the commit point
            # The renames are page-cache metadata ops; rotation below
            # issues durable DELETES of older checkpoints. fsync the
            # directory first, or a power loss could persist the deletes
            # while losing this commit — exactly the zero-intact-left
            # state crash consistency promises away.
            try:
                dfd = os.open(self.directory, os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
            except OSError:
                pass  # best effort (non-POSIX dir fsync)
        except OSError as e:
            for stray in (tmp, rtmp, f"{manifest}.tmp.{os.getpid()}"):
                try:
                    os.unlink(stray)
                except OSError:
                    pass
            raise CheckpointError(
                f"step checkpoint save failed at step {step} "
                f"({payload}): {e}") from e
        self._rotate()
        reg = get_registry()
        reg.histogram("checkpoint.save_s").record(time.perf_counter() - t0)
        reg.counter("checkpoint.bytes").inc(len(blob)
                                            + (len(rblob) if rblob else 0))
        return manifest

    def _pinned(self, steps: List[int]) -> set:
        """Which of `steps` carry a pinned manifest. Only rotation
        CANDIDATES are checked (one small JSON read each), so the common
        no-pin rotation stays the same few unlinks it always was; an
        unreadable manifest reads as unpinned (it is torn anyway)."""
        out = set()
        for step in steps:
            try:
                with open(os.path.join(self.directory,
                                       _manifest_name(step))) as f:
                    if json.load(f).get("pinned"):
                        out.add(step)
            except (OSError, ValueError):
                pass
        return out

    def _rotate(self) -> None:
        """Drop committed checkpoints beyond keep-last-N — manifest first
        (uncommit), then payload, so a crash mid-rotation can only leave an
        uncommitted orphan, never a manifest pointing at nothing. Pinned
        checkpoints (the watchdog's rescue saves) sit OUTSIDE the keep-N
        budget: never deleted here, and their payloads are never swept as
        strays. Then sweep crash debris: `.tmp.<pid>` files from DEAD
        writers (a SIGKILL mid-save never reaches save's cleanup) and
        payloads whose manifest never committed — both invisible to
        restore, but each kill/resume cycle would otherwise leave one
        full-size orphan behind forever."""
        committed = self.steps()
        doomed = committed[:-self.keep]
        pinned = self._pinned(doomed)
        for step in doomed:
            if step in pinned:
                continue
            for name in (_manifest_name(step), _payload_name(step),
                         _resid_name(step)):
                try:
                    os.unlink(os.path.join(self.directory, name))
                except OSError:
                    pass
        live = set(committed[-self.keep:]) | pinned
        my_suffix = f".{os.getpid()}"
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        for name in names:
            if ".tmp." in name:
                stray = not name.endswith(my_suffix)  # ours may be in flight
            else:
                m = _PAYLOAD_RE.match(name) or _RESID_RE.match(name)
                stray = bool(m) and int(m.group(1)) not in live
            if stray:
                try:
                    os.unlink(os.path.join(self.directory, name))
                except OSError:
                    pass

    # -- read side ----------------------------------------------------------

    def steps(self) -> List[int]:
        """Committed (manifest-bearing) step numbers, ascending."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        return sorted(int(m.group(1)) for n in names
                      if (m := _NAME_RE.match(n)))

    def _load_intact(self, step: int, template) -> StepCheckpoint:
        """Load + verify one committed checkpoint; CheckpointError names
        exactly what is wrong (missing/short/CRC-mismatched payload, bad
        manifest, undecodable msgpack)."""
        from flax import serialization

        manifest = os.path.join(self.directory, _manifest_name(step))
        try:
            with open(manifest) as f:
                rec = json.load(f)
        except (OSError, ValueError) as e:
            raise CheckpointError(f"{manifest}: unreadable manifest: {e}") from e
        if rec.get("v") != _SCHEMA:
            raise CheckpointError(
                f"{manifest}: unknown manifest schema {rec.get('v')!r}")
        missing = [k for k in ("step", "epoch", "offset", "key", "impl",
                               "payload", "bytes", "crc32") if k not in rec]
        if missing:
            # must stay a CheckpointError: restore_latest's fallback walk
            # catches exactly that class — a KeyError here would crash the
            # relaunch this path exists to survive
            raise CheckpointError(
                f"{manifest}: manifest missing fields {missing}")
        payload = os.path.join(self.directory, rec["payload"])
        try:
            with open(payload, "rb") as f:
                blob = f.read()
        except OSError as e:
            raise CheckpointError(f"{payload}: unreadable payload: {e}") from e
        if len(blob) != rec["bytes"]:
            raise CheckpointError(
                f"{payload}: truncated payload ({len(blob)} bytes, manifest "
                f"says {rec['bytes']})")
        if zlib.crc32(blob) != rec["crc32"]:
            raise CheckpointError(
                f"{payload}: CRC32 mismatch ({zlib.crc32(blob):#010x}, "
                f"manifest says {rec['crc32']:#010x}) — corrupt payload of "
                f"{len(blob)} bytes")
        try:
            params = serialization.from_bytes(template, blob)
        except Exception as e:
            raise CheckpointError(
                f"{payload}: cannot decode checkpoint ({len(blob)} bytes): "
                f"{type(e).__name__}: {e}") from e
        resid = None
        if rec.get("resid_payload"):
            # the int8 error-feedback residual: a second payload under the
            # same intactness contract (size + CRC + decode) — a torn
            # residual makes the whole checkpoint torn (resuming the
            # quantization-error accounting from garbage would silently
            # corrupt gradients, worse than falling back one checkpoint)
            rpath = os.path.join(self.directory, rec["resid_payload"])
            try:
                with open(rpath, "rb") as f:
                    rblob = f.read()
            except OSError as e:
                raise CheckpointError(
                    f"{rpath}: unreadable residual payload: {e}") from e
            if len(rblob) != rec.get("resid_bytes"):
                raise CheckpointError(
                    f"{rpath}: truncated residual payload ({len(rblob)} "
                    f"bytes, manifest says {rec.get('resid_bytes')})")
            if zlib.crc32(rblob) != rec.get("resid_crc32"):
                raise CheckpointError(
                    f"{rpath}: residual CRC32 mismatch "
                    f"({zlib.crc32(rblob):#010x}, manifest says "
                    f"{rec.get('resid_crc32'):#010x})")
            try:
                resid = np.asarray(serialization.msgpack_restore(rblob),
                                   np.float32)
            except Exception as e:
                raise CheckpointError(
                    f"{rpath}: cannot decode residual payload: "
                    f"{type(e).__name__}: {e}") from e
        return StepCheckpoint(
            params=params,
            key_data=np.asarray(rec["key"], np.uint32),
            impl=str(rec["impl"]), step=int(rec["step"]),
            epoch=int(rec["epoch"]), offset=int(rec["offset"]),
            path=manifest, resid=resid, meta=dict(rec.get("meta") or {}))

    def scan_restorable(self, template,
                        newer_than: "int | None" = None) -> "RestoreScan":
        """The newest-intact-AND-finite preference itself, shared by
        `restore_latest` (the trainer's `--resume`) and the serve
        hot-reload watcher (`serve/reload.py`) — ONE walk, so the two
        consumers can never drift on what "promotable" means.

        Walks committed manifests newest-first and stops at the first
        candidate that is both intact (`_load_intact`'s CRC/size/decode
        contract) and finite, returning a `RestoreScan` with that
        candidate (`best`), the newest intact-but-non-finite one seen
        (`newest_nonfinite` — `restore_latest`'s last-resort fallback,
        which a reload watcher must instead refuse), and the named defect
        of every candidate rejected on the way (`tried`). Every rejection
        lands in the flight recorder (kind `checkpoint_fallback`) and on
        stderr exactly as the resume path always did.

        `newer_than` bounds the walk to steps strictly beyond it — the
        reload watcher only considers checkpoints newer than what the
        fleet already serves."""
        import sys
        from ..telemetry import flight

        tried: List[str] = []
        nonfinite_newest: StepCheckpoint | None = None
        for step in reversed(self.steps()):
            if newer_than is not None and step <= newer_than:
                break
            try:
                ckpt = self._load_intact(step, template)
            except CheckpointError as e:
                tried.append(str(e))
                flight.record("checkpoint_fallback", step=step,
                              error=str(e)[:500])
                print(f"[ckpt] skipping torn checkpoint at step {step}: {e}",
                      file=sys.stderr, flush=True)
                continue
            bad = _nonfinite_leaves(ckpt.params)
            if bad:
                msg = (f"{ckpt.path}: params contain non-finite values "
                       f"(e.g. {bad[0]}) — a diverged run's checkpoint")
                tried.append(msg)
                flight.record("checkpoint_fallback", step=step,
                              error=msg[:500])
                print(f"[ckpt] skipping non-finite checkpoint at step "
                      f"{step} (looking for the newest finite one)",
                      file=sys.stderr, flush=True)
                if nonfinite_newest is None:
                    nonfinite_newest = ckpt
                continue
            return RestoreScan(best=ckpt, newest_nonfinite=nonfinite_newest,
                               tried=tried)
        return RestoreScan(best=None, newest_nonfinite=nonfinite_newest,
                           tried=tried)

    def restore_latest(self, template) -> StepCheckpoint:
        """Newest INTACT + FINITE checkpoint, falling back past torn,
        corrupt, and non-finite ones.

        The finiteness walk is new with the health watchdog: a run whose
        params truly diverged keeps committing intact-by-CRC checkpoints
        full of NaN — resuming from one trains garbage forever, so restore
        prefers the newest checkpoint whose float leaves are all finite
        (the watchdog's pinned rescue save, typically). When NO finite
        candidate exists, the newest intact one is returned anyway with a
        loud warning (behavior-preserving: refusing outright would strand
        resumes that predate the watchdog).

        Every rejected candidate lands in the flight recorder (kind
        `checkpoint_fallback`, with the path and the named defect) and on
        stderr; the restore that finally succeeds records
        `checkpoint_restore`. Raises CheckpointError naming every tried
        path when nothing intact remains. The walk itself lives in
        `scan_restorable` — shared with the serve hot-reload watcher."""
        import sys
        from ..telemetry import flight

        steps = self.steps()
        if not steps:
            raise CheckpointError(
                f"{self.directory}: no committed step checkpoints "
                f"(no step_*.json manifests)")
        scan = self.scan_restorable(template)
        tried = scan.tried
        if scan.best is not None:
            ckpt = scan.best
            flight.record("checkpoint_restore", step=ckpt.step,
                          epoch=ckpt.epoch, offset=ckpt.offset,
                          fallbacks=len(tried))
            return ckpt
        nonfinite_newest = scan.newest_nonfinite
        if nonfinite_newest is not None:
            print(f"[ckpt] WARNING: every intact checkpoint holds "
                  f"non-finite params; restoring the newest anyway "
                  f"(step {nonfinite_newest.step}) — expect the resumed "
                  f"run to stay diverged", file=sys.stderr, flush=True)
            flight.record("checkpoint_restore", step=nonfinite_newest.step,
                          epoch=nonfinite_newest.epoch,
                          offset=nonfinite_newest.offset,
                          fallbacks=len(tried), nonfinite=True)
            return nonfinite_newest
        raise CheckpointError(
            f"{self.directory}: no intact step checkpoint; tried "
            f"{len(tried)}:\n" + "\n".join(f"  {t}" for t in tried))
