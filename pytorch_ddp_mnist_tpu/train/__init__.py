from .loop import TrainState, make_train_step, make_eval_step, fit, evaluate
from .checkpoint import CheckpointError, save_checkpoint, load_checkpoint
from .ckpt_manager import CheckpointManager, StepCheckpoint

__all__ = [
    "TrainState", "make_train_step", "make_eval_step", "fit", "evaluate",
    "CheckpointError", "save_checkpoint", "load_checkpoint",
    "CheckpointManager", "StepCheckpoint",
]
