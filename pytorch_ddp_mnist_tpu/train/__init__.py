from .loop import TrainState, make_train_step, make_eval_step, fit, evaluate
from .checkpoint import save_checkpoint, load_checkpoint

__all__ = [
    "TrainState", "make_train_step", "make_eval_step", "fit", "evaluate",
    "save_checkpoint", "load_checkpoint",
]
