"""`python -m pytorch_ddp_mnist_tpu <command>` — one front door to the
framework's executables (each also runs standalone as its own module):

    train      the unified trainer CLI (cli/train.py; the reference's five
               entry scripts behind one config surface)
    serve      micro-batching inference service from a checkpoint
               (cli/serve.py; TCP JSON-lines server or --selftest)
    trace      analyze / regression-gate / Perfetto-export the JSONL
               telemetry traces a --telemetry run emits (cli/trace.py)
    ledger     the performance ledger: ingest every committed artifact
               generation into one direction-aware metric history, render
               the trajectory report, trend-gate the newest run
               (cli/ledger.py; exit 3 names the regressed series)
    convert    IDX -> NetCDF converter (data/convert.py; the
               mnist_to_netcdf.ipynb workflow)
    download   mirrored, checksum-verified MNIST IDX fetch (data/download.py)
    lint       JAX-aware source lint + concurrency auditor — host syncs in
               traced code, wire dtypes, overbroad excepts, unlocked
               globals, blocking calls on the serve event loop, lock-order
               cycles... with a committed baseline (statics/lint.py +
               statics/concurrency.py; docs/STATIC_ANALYSIS.md)
    audit-program
               lower the comm x overlap step-program matrix and assert the
               collective/dtype/wire-byte contracts per strategy
               (statics/jaxpr_audit.py; exit 3 names the broken contract)
"""

from __future__ import annotations

import sys

_COMMANDS = {
    "train": ("pytorch_ddp_mnist_tpu.cli.train", "the unified trainer"),
    "serve": ("pytorch_ddp_mnist_tpu.cli.serve",
              "micro-batching inference service"),
    "trace": ("pytorch_ddp_mnist_tpu.cli.trace",
              "telemetry trace report / regression gate / Perfetto export"),
    "ledger": ("pytorch_ddp_mnist_tpu.cli.ledger",
               "performance ledger: artifact history, trajectory report, "
               "trend gate"),
    "convert": ("pytorch_ddp_mnist_tpu.data.convert",
                "IDX -> NetCDF converter"),
    "download": ("pytorch_ddp_mnist_tpu.data.download", "MNIST IDX fetch"),
    "lint": ("pytorch_ddp_mnist_tpu.statics.lint",
             "JAX-aware source lint (baseline-gated)"),
    "audit-program": ("pytorch_ddp_mnist_tpu.statics.jaxpr_audit",
                      "step-program collective/dtype/wire contract audit"),
}


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv or argv[0] in ("-h", "--help"):
        lines = [f"  {name:<10} {desc}  (python -m {mod})"
                 for name, (mod, desc) in _COMMANDS.items()]
        usage = ("usage: python -m pytorch_ddp_mnist_tpu <command> [args]\n\n"
                 "commands:\n" + "\n".join(lines))
        # --help goes to stdout (success); the no-command error to stderr
        print(usage, file=sys.stdout if argv else sys.stderr)
        return 0 if argv else 2
    if argv[0] not in _COMMANDS:
        print(f"unknown command {argv[0]!r}; expected one of "
              f"{', '.join(_COMMANDS)}", file=sys.stderr)
        return 2
    import importlib
    mod = importlib.import_module(_COMMANDS[argv[0]][0])
    # argparse derives `prog` from sys.argv[0]; name the subcommand so its
    # usage/error text says how to re-invoke it through the front door.
    sys.argv[0] = f"python -m pytorch_ddp_mnist_tpu {argv[0]}"
    return mod.main(argv[1:]) or 0


if __name__ == "__main__":
    sys.exit(main())
