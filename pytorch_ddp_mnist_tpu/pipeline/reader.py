"""Rank-sharded batch-read plans — the pipeline's source stage.

The source paper's distinctive systems idea is parallel collective IO:
every rank of `mnist_pnetcdf_cpu_mp.py` reads its own shard of ONE shared
.nc file (rows 32,46), so no rank ever materializes the epoch. This module
re-states that contract for the staged input pipeline: a *reader* separates
the epoch's index PLAN (a lazy stream of `(batch_index, rows)` — the
sampler shard sliced into wrap-padded static batches, exactly
`data.loader._batched_indices`) from the row LOAD (`read_batch(rows)`: a
memory gather, a sharded .nc pread, or a synthetic generator), so the
background workers (`pipeline/workers.py`) can execute loads concurrently
while batch ORDER stays a pure function of the plan — the property the
legacy-loader bitwise-parity pin rests on.

A source is *pipeline-capable* when it exposes the protocol the package
loaders (`data.loader.BatchLoader` / `NetCDFShardLoader`) and
`pipeline.synthetic.SyntheticSource` all implement:

    source.sampler          ShardedSampler-shaped (set_epoch / indices)
    source.batch_size       static batch row count
    source.read_batch(rows) -> (x, y) for one index batch

Duck-typed plain iterables stay supported through the sequential fallback
(`sequential_iter`): no parallel reads — order-preserving parallelism over
an opaque iterator would have to materialize it — but the same front door
and the same `start` (mid-epoch resume) semantics.
"""

from __future__ import annotations

from typing import Iterator, Tuple

import numpy as np


def pipeline_capable(source) -> bool:
    """True when `source` carries the plan/load split the worker stage
    needs (see module docstring for the protocol)."""
    return (hasattr(source, "read_batch") and hasattr(source, "sampler")
            and hasattr(source, "batch_size"))


class ShardReader:
    """The plan/load split over one pipeline-capable source.

    `plan(start)` yields `(batch_index, rows)` LAZILY from the sampler's
    current epoch state — chunked at batch granularity, so neither this
    rank's plan nor its loads ever hold the epoch (the PnetCDF
    independent-read contract); `start` drops the first `start` batches at
    the INDEX level, before any gather (the `iter_from` mid-epoch-resume
    rule: skipped rows are never read). `load(rows)` is the source's
    `read_batch` — stateless per batch, safe to run from worker threads
    concurrently (numpy gathers and positional preads share no cursor).
    """

    def __init__(self, source):
        if not pipeline_capable(source):
            raise ValueError(
                f"{type(source).__name__} is not pipeline-capable: the "
                f"worker stage needs sampler/batch_size/read_batch(rows) "
                f"(see pipeline/reader.py) — use workers=0 for plain "
                f"sequential iteration")
        self.source = source

    def __len__(self) -> int:
        return len(self.source)

    def plan(self, start: int = 0) -> Iterator[Tuple[int, np.ndarray]]:
        from ..data.loader import _batched_indices
        for i, rows in enumerate(_batched_indices(self.source.sampler,
                                                  self.source.batch_size)):
            if i >= start:
                yield i, rows

    def load(self, rows: np.ndarray):
        return self.source.read_batch(rows)


def reshard_source(source, num_replicas: int, rank: int):
    """Re-point a pipeline-capable source at a NEW rank geometry in place
    (the elastic shrink/grow path, docs/ROBUSTNESS.md §Elastic training).

    The plan/load split makes this a one-field swap: batch ORDER is a pure
    function of the sampler, so replacing `source.sampler` with its
    `reshard(num_replicas, rank)` twin (same permutation source and seed,
    new shard slice, epoch carried over) re-maps every future `plan()` to
    the survivor geometry without touching the load side — the .nc pread /
    memory gather is row-addressed and geometry-blind. Returns `source`."""
    if not pipeline_capable(source):
        raise ValueError(
            f"{type(source).__name__} is not pipeline-capable: elastic "
            f"re-sharding swaps source.sampler (see pipeline/reader.py)")
    sampler = source.sampler
    if not hasattr(sampler, "reshard"):
        raise ValueError(
            f"{type(sampler).__name__} has no reshard(); elastic "
            f"re-sharding needs parallel.sampler.ShardedSampler")
    source.sampler = sampler.reshard(num_replicas=num_replicas, rank=rank)
    return source


def sequential_iter(source, start: int = 0):
    """The workers=0 path: plain in-thread iteration with the same `start`
    semantics as the worker stage — index-level skip through `iter_from`
    when the source supports it (skipped batches' CONTENT is irrelevant:
    the restored RNG key already encodes every step through them, and the
    sampler permutation is position-addressed), a discard fallback for
    duck-typed iterables that only support iteration."""
    if start == 0:
        return iter(source)
    if hasattr(source, "iter_from"):
        return source.iter_from(start)

    def dropped():
        it = iter(source)
        for _ in range(start):
            next(it, None)
        yield from it

    return dropped()
