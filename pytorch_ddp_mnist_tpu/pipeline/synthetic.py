"""Synthetic pipeline source — size/latency knobs that stress the input
stack without touching disk.

`bench.py --mode input` and `scripts/input_smoke.py` need a source whose
cost profile is a KNOB, not an accident of the host's page cache: this one
generates batches deterministically from (seed, row index) and charges a
configurable per-batch `latency_s` at read time — crank it until the
legacy synchronous loader is input-bound, then measure how much of that
wait the worker/prefetch stages hide. Rows come from a small base table
indexed modulo a prime, so memory stays O(features), independent of
`n_batches * batch_size` (a million-batch epoch costs nothing to hold).

Implements the full pipeline-capable protocol (pipeline/reader.py):
`sampler` (a real single-shard `parallel.sampler.ShardedSampler` — the
SAME epoch-reseed/permutation semantics as the package loaders, so the
"reshuffles like the real loaders" claim is shared code, not a parallel
implementation), `batch_size`, `read_batch(rows)`, plus the sequential
loader surface (`__len__` / `__iter__` / `iter_from`) with the same
`loader_next` chaos hook as `data.loader.BatchLoader`, so it drops into
`fit` wherever a loader goes — piped or not, bitwise either way.
"""

from __future__ import annotations

import math
import time
from typing import Iterator, Tuple

import numpy as np

_TABLE_ROWS = 251   # prime: rows % 251 decorrelates from batch_size


class SyntheticSource:
    """A loader-shaped batch source with synthetic rows and a read-latency
    knob. `latency_s` sleeps per `read_batch` — charged in the WORKER when
    piped (hidden behind compute) and in the consumer when not (the
    input-bound legacy geometry the bench measures)."""

    def __init__(self, n_batches: int = 64, batch_size: int = 128, *,
                 features: int = 784, classes: int = 10,
                 latency_s: float = 0.0, seed: int = 0):
        if n_batches < 1 or batch_size < 1:
            raise ValueError(f"n_batches/batch_size must be >= 1; got "
                             f"{n_batches}/{batch_size}")
        # lazy: keeps `import pytorch_ddp_mnist_tpu.pipeline` clear of the
        # parallel package's jax-importing __init__
        from ..parallel.sampler import ShardedSampler
        self.batch_size = int(batch_size)
        self.features = int(features)
        self.classes = int(classes)
        self.latency_s = float(latency_s)
        n_rows = int(n_batches) * self.batch_size
        self.sampler = ShardedSampler(n_rows, num_replicas=1, rank=0,
                                      shuffle=True, seed=seed)
        rng = np.random.default_rng(seed)
        # O(features) memory whatever the epoch size: batches gather from
        # this table by row index, values in the normalized-MNIST range
        self._table = rng.standard_normal(
            (_TABLE_ROWS, self.features)).astype(np.float32)

    def __len__(self) -> int:
        return math.ceil(len(self.sampler) / self.batch_size)

    def read_batch(self, rows: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        if self.latency_s > 0:
            time.sleep(self.latency_s)
        x = self._table[rows % _TABLE_ROWS]
        y = (rows % self.classes).astype(np.int32)
        return x, y

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        return self.iter_from(0)

    def iter_from(self, start: int) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Sequential iteration from batch `start` — the same chaos hook
        and index-level skip contract as the package loaders."""
        from ..data.loader import _batched_indices
        from ..utils import faultpoints
        for i, b in enumerate(_batched_indices(self.sampler,
                                               self.batch_size)):
            if i < start:
                continue
            faultpoints.fire("loader_next", batch=i)
            yield self.read_batch(b)
