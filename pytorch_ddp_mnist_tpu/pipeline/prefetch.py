"""Depth-K double-buffered device prefetch — the pipeline's H2D stage.

Generalizes `data.loader.device_prefetch`'s one-slot lookahead: keep up to
`depth` batches' host->device transfers IN FLIGHT while the consumer steps
on the current batch. `jax.device_put` is async, so dispatching batch
k+depth's transfer before batch k's step is consumed lets XLA overlap
PCIe/HBM copies with compute — the bucket-pipelining playbook PR 7 applied
to gradient collectives (arXiv:1711.00705), applied unchanged to the input
side; the reference gets the same overlap from `non_blocking=True` + CUDA
streams (ddp_tutorial_multi_gpu.py:87-88). `sharding` is shorthand for
`jax.device_put` with that sharding (sharding-aware placement: a DP batch
lands pre-sharded over the mesh); `put` overrides placement entirely (e.g.
the DP global-batch assembler).

Teardown is DETERMINISTIC: when the producer (or a `put` dispatch) raises
mid-iteration, every already-dispatched transfer is drained
(`jax.block_until_ready`, secondary errors swallowed) before the ORIGINAL
exception re-raises — the legacy `device_prefetch` shape abandoned its
pending transfer on a producer error, so an async transfer's own failure
(surfacing only at consumption) was silently dropped with the array, and
device work could outlive the error that killed the loop. The drain
serializes: by the time the caller sees the exception, the device owes
nothing. `device_prefetch` survives as a thin alias over `depth=1`.
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Optional


def _drain(pending: deque) -> None:
    """Block on every dispatched transfer, swallowing secondary errors —
    the primary exception (already propagating) must never be masked by a
    transfer that failed for the same upstream reason."""
    import jax
    while pending:
        item = pending.popleft()
        try:
            jax.block_until_ready(item)
        except Exception:  # noqa: BLE001 — fault barrier: teardown only;
            pass           # the original error is re-raised by the caller


def prefetch(source, *, depth: int = 1, sharding=None,
             put: Optional[Callable] = None):
    """Iterate `source` with `depth` batches of device-transfer lookahead.

    Order-preserving (batch k yields before k+1 dispatches nothing new —
    the pipeline stays bitwise against unpiped iteration); `depth=1` is
    exactly the legacy one-slot double buffer. StopIteration before the
    window fills just shrinks the window. Validation is EAGER (this is a
    plain function returning the generator): a bad depth raises at the
    call site, not at the first batch."""
    if depth < 1:
        raise ValueError(f"depth must be >= 1; got {depth}")
    import jax

    if put is None:
        if sharding is not None:
            def put(b):
                return jax.device_put(b, sharding)
        else:
            def put(b):
                return jax.tree_util.tree_map(jax.device_put, b)
    return _prefetch_gen(source, depth, put)


def _prefetch_gen(source, depth: int, put: Callable):
    pending: deque = deque()
    it = iter(source)
    try:
        exhausted = False
        while len(pending) < depth and not exhausted:
            try:
                pending.append(put(next(it)))
            except StopIteration:
                exhausted = True
        if not exhausted:
            for batch in it:
                # append BEFORE yielding: the consumer can close (or throw
                # into) the generator at the yield point, and a transfer
                # not yet in `pending` would escape the teardown drain
                pending.append(put(batch))
                yield pending.popleft()
        while pending:
            yield pending.popleft()
    except BaseException:
        # deterministic teardown: the device must owe nothing by the time
        # the caller sees the error (see module docstring)
        _drain(pending)
        raise
