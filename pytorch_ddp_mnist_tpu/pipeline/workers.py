"""Background decode workers — the pipeline's parallel middle stage.

N threads execute a `ShardReader`'s loads concurrently while the consumer
sees batches in EXACT plan order: each worker pulls the next `(i, rows)`
work item from the shared lazy plan, loads it (gather + normalize — the
decode/augment stage), and posts the result into a bounded reorder buffer
keyed by batch index; the consumer pops index `start`, `start+1`, ... as
they complete. Compared to the fixed round-robin readahead this
generalizes (`data.loader.NetCDFShardLoader._iter_readahead`), the shared
plan load-balances — a slow batch stalls only the slot budget, not one
worker's whole stride — while order (and therefore the bitwise-parity pin
against unpiped iteration) is enforced at the buffer, not the schedule.

Contracts:

  * **Backpressure** — at most `num_workers * queue_depth` batches exist
    beyond the consumer at any moment (a counting semaphore: workers
    acquire a slot before pulling work, the consumer releases it when it
    pops the batch). No rank materializes the epoch.
  * **Exception propagation** — a load that raises posts the error into
    the batch's slot; the consumer re-raises the ORIGINAL exception when
    it reaches that index, after the batches before it (order holds even
    for failures). A broken plan iterator propagates the same way.
  * **Clean shutdown** — consumer exit (exhaustion, error, or an early
    `close()` of the generator) stops the workers and joins them; workers
    parked on the slot semaphore wake on a bounded timeout and observe
    the stop flag. Threads are daemonic as a last resort only.
  * **Chaos** — `utils.faultpoints.fire("loader_next", batch=i)` fires
    INSIDE the worker, before the load: a `loader_stall` spec stalls
    production, the bounded buffer drains, and the consumer's wait lands
    in the `data_wait` span / `data.batch_wait_s` histogram — the
    watchdog's throughput detector sees the pipeline degrade loudly
    (docs/ROBUSTNESS.md).
  * **Telemetry** — `data.batch_wait_s` (consumer wait per batch),
    `data.queue_depth` (reorder-buffer depth at each pop),
    `data.batches` / `data.workers` into the shared registry. All host
    clock reads: ZERO device syncs (the no_host_sync pin).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from .reader import ShardReader


class _WorkerFailure:
    """A load (or plan) error, parked in the reorder buffer at the batch
    index it belongs to so the consumer re-raises it in order."""

    __slots__ = ("error",)

    def __init__(self, error: BaseException):
        self.error = error


class WorkerPool:
    """One epoch's worth of parallel loads over `reader`, consumed by
    iterating the pool ONCE (fresh pool per epoch — the front door builds
    one per `feed()` call; a second iteration raises by name)."""

    def __init__(self, reader: ShardReader, num_workers: int, *,
                 start: int = 0, queue_depth: int = 2, registry=None):
        if num_workers < 1:
            raise ValueError(f"num_workers must be >= 1; got {num_workers}")
        if queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1; got {queue_depth}")
        self._reader = reader
        self._num_workers = int(num_workers)
        self._start = int(start)
        self._slots = threading.BoundedSemaphore(
            self._num_workers * int(queue_depth))
        self._plan_lock = threading.Lock()
        self._plan = reader.plan(self._start)
        self._plan_done = False
        self._issued = self._start        # next batch index the plan owes
        self._cv = threading.Condition()
        self._done: dict = {}             # batch index -> batch | failure
        self._end: Optional[int] = None   # one past the last issued index
        self._stop = threading.Event()
        self._threads: list = []
        self._iterated = False
        if registry is None:
            from ..telemetry import get_registry
            registry = get_registry()
        self._wait_hist = registry.histogram("data.batch_wait_s")
        self._depth_gauge = registry.gauge("data.queue_depth")
        self._batch_counter = registry.counter("data.batches")
        registry.gauge("data.workers").set(self._num_workers)

    # -- producer side -----------------------------------------------------

    def _work(self) -> None:
        from ..utils import faultpoints
        while not self._stop.is_set():
            # bounded wait so a stopped pool never strands a worker here
            if not self._slots.acquire(timeout=0.1):
                continue
            with self._plan_lock:
                if self._plan_done:
                    self._slots.release()
                    return
                try:
                    i, rows = next(self._plan)
                    self._issued = i + 1
                except StopIteration:
                    self._plan_done = True
                    self._slots.release()
                    with self._cv:
                        self._end = self._issued
                        self._cv.notify_all()
                    return
                except BaseException as e:  # broken plan: surfaces in order
                    self._plan_done = True
                    err_at = self._issued
                    with self._cv:
                        self._done[err_at] = _WorkerFailure(e)
                        self._end = err_at + 1
                        self._cv.notify_all()
                    return
            # the chaos hook fires in the WORKER: a loader_stall spec stalls
            # production and the consumer starves through the bounded
            # buffer — the failure mode the data_wait telemetry exists to
            # expose (no-op when no faults are installed)
            faultpoints.fire("loader_next", batch=i)
            try:
                item = self._reader.load(rows)
            except BaseException as e:  # noqa: BLE001 — fault barrier: the
                # error is parked in the reorder buffer and re-raised by
                # the CONSUMER at this batch's position (order preserved)
                item = _WorkerFailure(e)
            with self._cv:
                self._done[i] = item
                self._cv.notify_all()

    # -- consumer side -----------------------------------------------------

    def __iter__(self):
        if self._iterated:
            raise RuntimeError(
                "WorkerPool is one-shot: its plan iterator is consumed — "
                "build a fresh pool (pipeline.feed) per epoch")
        self._iterated = True
        return self._consume()

    def _consume(self):
        self._threads = [
            threading.Thread(target=self._work, daemon=True,
                             name=f"pdmt-input-worker-{w}")
            for w in range(self._num_workers)
        ]
        for t in self._threads:
            t.start()
        try:
            i = self._start
            while True:
                t0 = time.perf_counter()
                with self._cv:
                    while i not in self._done and (self._end is None
                                                   or i < self._end):
                        self._cv.wait(0.1)
                    if i not in self._done:
                        return              # plan exhausted, all yielded
                    item = self._done.pop(i)
                    depth_now = len(self._done)
                self._wait_hist.record(time.perf_counter() - t0)
                self._depth_gauge.set(depth_now)
                self._slots.release()
                if isinstance(item, _WorkerFailure):
                    raise item.error
                self._batch_counter.inc()
                yield item
                i += 1
        finally:
            self._shutdown()

    def _shutdown(self) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=5)
