"""Staged, backpressured input pipeline — the repo's JAX-native answer to
the source paper's parallel collective IO (PnetCDF sharded reads,
`mnist_pnetcdf_cpu_mp.py`), done as threads + async device transfers
instead of MPI ranks + CUDA streams.

    source  ->  plan (lazy index batches, rank-sharded)       reader.py
            ->  N decode workers, bounded reorder buffer      workers.py
            ->  depth-K double-buffered jax.device_put        prefetch.py
            ->  the train loop

`feed()` is the ONE front door: `train.loop.fit` iterates it instead of a
bare loader, `workers=0, depth=1` degenerates to exactly the legacy
synchronous path, and any configuration is BITWISE identical to unpiped
iteration over the same source (order-preserving by construction; pinned
by tests/test_pipeline.py for both trainers). Mid-epoch resume threads
through as `start` — batches are skipped at the INDEX level, never
gathered, so PR 5's crash-resume parity holds with workers live. The
consumer side adds ZERO host syncs: worker handoff and the `data.*`
telemetry are host clock reads only (the `sanitize.no_host_sync` pin).

See docs/DATA.md for the stage diagram, knob table, and the backpressure /
shutdown / failure semantics.
"""

from __future__ import annotations

import time

from .prefetch import prefetch
from .reader import ShardReader, pipeline_capable, sequential_iter
from .synthetic import SyntheticSource
from .workers import WorkerPool

__all__ = ["feed", "host_iter", "prefetch", "pipeline_capable",
           "ShardReader", "SyntheticSource", "WorkerPool"]


def _recorded(it, registry=None):
    """Wrap a sequential host iterator with the same `data.*` metrics the
    worker pool publishes (wait histogram + batch counter), so a piped and
    an unpiped run expose one telemetry surface — the Prometheus endpoint
    and `check_telemetry --require data.` see input health either way.
    Clock reads only: no device traffic."""
    if registry is None:
        from ..telemetry import get_registry
        registry = get_registry()
    hist = registry.histogram("data.batch_wait_s")
    batches = registry.counter("data.batches")

    def recorded():
        inner = iter(it)
        while True:
            t0 = time.perf_counter()
            try:
                item = next(inner)
            except StopIteration:
                return
            hist.record(time.perf_counter() - t0)
            batches.inc()
            yield item

    return recorded()


def host_iter(source, *, workers: int = 0, start: int = 0,
              queue_depth: int = 2, registry=None):
    """The host half of the pipeline: parallel loads behind a reorder
    buffer when `workers > 0`, plain (recorded) iteration otherwise.
    `start` is the mid-epoch resume offset — index-level skip in both
    paths. A `workers > 0` request against a source that cannot split
    plan from load is refused by name (a silently sequential "parallel"
    pipeline would mislabel every measurement)."""
    if workers < 0:
        raise ValueError(f"workers must be >= 0; got {workers}")
    if workers == 0:
        return _recorded(sequential_iter(source, start), registry)
    return iter(WorkerPool(ShardReader(source), workers, start=start,
                           queue_depth=queue_depth, registry=registry))


def feed(source, *, workers: int = 0, depth: int = 1, start: int = 0,
         queue_depth: int = 2, sharding=None, put=None, registry=None):
    """The pipeline front door: `source` -> device-ready batches.

    Replaces `device_prefetch(loader)` iteration in the trainers:
    `workers` background decode threads (0 = synchronous reads), `depth`
    batches of H2D transfer lookahead, `start` the mid-epoch resume
    offset. Returns an iterator of placed `(x, y)` batches in exact
    source order."""
    return prefetch(host_iter(source, workers=workers, start=start,
                              queue_depth=queue_depth, registry=registry),
                    depth=depth, sharding=sharding, put=put)
