"""Per-rank elastic reaction loop — shrink the world without losing the run.

PRs 14-15 built the SENSORY layer: the per-rank collective journal, the
hang watchdog, `looks_like_backend_loss`. This module is the REACTION: when
a peer dies mid-run, the surviving ranks (each independently — a dead rank
cannot coordinate anyone)

  1. DETECT   — the collective that wedged on the dead peer surfaces as a
     backend-loss RuntimeError (`classify_peer_loss` pairs it with the
     journal's open entry and the watchdog's hang flip as evidence);
  2. RESCUE   — the lowest SURVIVING rank commits the last stashed state as
     a PINNED step checkpoint (the PR 6 rescue path: exempt from
     keep-last-N rotation) so the resume point cannot rotate away;
  3. MEMBERSHIP — survivors agree on who is left through beacon files in
     the shared checkpoint directory (the same shared-fs contract step
     checkpoints already require): each alive rank writes
     `elastic.gen<G>.rank<R>`, waits a settle window, and reads the set
     back — dead ranks never write, so the beacon set IS the surviving
     membership, and dense re-ranking (sorted order) gives the new ranks;
  4. RE-WIRE  — survivors wait for the backend out-of-process (jittered
     exponential backoff, `parallel.wireup.backoff_schedule`, every probe
     flight-recorded) and then re-exec into a fresh CLI invocation with
     RANK/WORLD_SIZE/MASTER_PORT env for the surviving membership under
     the NEXT world generation. Process replacement is the teardown: a
     wedged jax client cannot be re-initialized in place (its bridge lock
     may be held forever — the same reason the outage path re-execs), and
     the fresh processes re-rendezvous through `parallel/wireup.py`
     cleanly;
  5. RESHAPE + CONTINUE — the re-exec'd run resumes from the rescue
     checkpoint with `--reshape` re-mapping the manifest geometry
     (elastic/reshape.py) instead of refusing it.

GROW is scheduler-initiated: a dead process cannot resurrect itself, so
when capacity returns the launcher relaunches the FULL world with
`--resume <steps dir> --elastic --reshape MODE`; the same reshape path
re-maps the shrunken-world manifest up (residual rows grow with zeros,
offset re-maps) under the next generation. `scripts/elastic_smoke.py`
drives the whole shrink-to-1/grow-back cycle.

World-generation rules (docs/ROBUSTNESS.md §Elastic training):
  * generation 0 is the original launch; `PDMT_ELASTIC_GEN` carries it
    across re-execs and every checkpoint stamps its generation in meta;
  * the counter increments on EVERY membership change (shrink or grow),
    never reuses a value (monotonic), and a resume at unchanged geometry
    keeps its generation;
  * MASTER_PORT for generation G's rendezvous is base_port + G — every
    survivor derives the same port without communicating, and the old
    coordinator's socket (possibly held by a dead or wedged process) is
    never reused.
"""

from __future__ import annotations

import os
import re
import sys
import time
from dataclasses import dataclass, field
from typing import Optional

GEN_ENV = "PDMT_ELASTIC_GEN"
_BEACON_RE = re.compile(r"^elastic\.gen(\d+)\.rank(\d+)$")

# How long membership collection waits after the LAST new beacon before
# trusting the set (every survivor hits the dead collective within one
# step of each other; the window only needs to cover scheduling skew).
SETTLE_S = float(os.environ.get("PDMT_ELASTIC_SETTLE_S", "5.0"))
# Total membership deadline: a survivor that never beacons (wedged before
# reaching the coordinator) is treated as dead — the run continues without
# it rather than waiting forever.
MEMBER_DEADLINE_S = float(os.environ.get("PDMT_ELASTIC_MEMBER_S", "60.0"))


def world_generation() -> int:
    """This process's world generation (0 = original launch)."""
    try:
        gen = int(os.environ.get(GEN_ENV, "0"))
    except ValueError:
        return 0
    return max(gen, 0)


def next_generation(current: int) -> int:
    """Monotonic: every membership change mints a fresh generation."""
    return int(current) + 1


def rendezvous_port(base_port: int, generation: int) -> int:
    """Generation G rendezvouses on base + G: derivable by every survivor
    with no communication, never reusing a port a dead world may hold."""
    return int(base_port) + int(generation)


def beacon_path(directory: str, generation: int, rank: int) -> str:
    return os.path.join(directory, f"elastic.gen{generation}.rank{rank}")


def write_beacon(directory: str, generation: int, rank: int) -> str:
    """Mark this rank alive for `generation`'s membership round. Atomic
    (O_CREAT on a final name — no rename needed for an empty marker)."""
    os.makedirs(directory, exist_ok=True)
    path = beacon_path(directory, generation, rank)
    with open(path, "w") as f:
        f.write(f"{time.time()}\n")
        f.flush()
        os.fsync(f.fileno())
    return path


def read_beacons(directory: str, generation: int) -> list:
    """Ranks with a beacon for `generation`, sorted ascending."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    out = []
    for name in names:
        m = _BEACON_RE.match(name)
        if m and int(m.group(1)) == generation:
            out.append(int(m.group(2)))
    return sorted(set(out))


def collect_membership(directory: str, generation: int, rank: int, *,
                       settle_s: float = None,
                       deadline_s: float = None,
                       poll_s: float = 0.25) -> list:
    """Beacon, then wait for the survivor set to go QUIET: the membership
    is accepted once no new beacon has appeared for `settle_s` (bounded by
    `deadline_s` total). Every survivor runs this independently and — the
    set being monotone-growing and the settle window shared — lands on the
    same answer, so the dense re-rank below is consistent without any
    collective (there is no working collective to use)."""
    settle_s = SETTLE_S if settle_s is None else settle_s
    deadline_s = MEMBER_DEADLINE_S if deadline_s is None else deadline_s
    write_beacon(directory, generation, rank)
    deadline = time.monotonic() + deadline_s
    seen = read_beacons(directory, generation)
    quiet_since = time.monotonic()
    while time.monotonic() < deadline:
        if time.monotonic() - quiet_since >= settle_s:
            break
        time.sleep(poll_s)
        now = read_beacons(directory, generation)
        if now != seen:
            seen = now
            quiet_since = time.monotonic()
    return seen


def clear_beacons(directory: str, generation: Optional[int] = None) -> None:
    """Drop beacon files (all, or one generation's) — the resumed run's
    startup hygiene so a later shrink round starts clean."""
    try:
        names = os.listdir(directory)
    except OSError:
        return
    for name in names:
        m = _BEACON_RE.match(name)
        if m and (generation is None or int(m.group(1)) == generation):
            try:
                os.unlink(os.path.join(directory, name))
            except OSError:
                pass


def classify_peer_loss(exc: BaseException, journal=None) -> dict:
    """The detection evidence bundle: does this error look like a peer
    died, and what does the sensory layer know about where? Consumes the
    PR 14-15 signals — `looks_like_backend_loss` (the gRPC signatures a
    dead peer's collective surfaces), the journal's OPEN entry (the exact
    collective the world wedged in), and the watchdog's health flip (the
    `health.worst_severity_level` gauge `report_hang` raises to 2)."""
    from ..parallel.wireup import looks_like_backend_loss
    evidence = {"backend_loss": looks_like_backend_loss(exc),
                "error": str(exc)[:500], "open_entry": None,
                "hang_flagged": False}
    if journal is not None:
        entry = journal.open_entry()
        if entry:
            evidence["open_entry"] = {k: entry.get(k)
                                      for k in ("seq", "kind", "axis")}
    try:
        from ..telemetry import get_registry
        worst = get_registry().snapshot()["gauges"].get(
            "health.worst_severity_level")
        evidence["hang_flagged"] = bool(worst is not None and worst >= 2)
    except Exception:  # noqa: BLE001 — evidence gathering must never mask
        pass           # the original failure
    return evidence


class ElasticHandoffError(RuntimeError):
    """The elastic reaction could not complete (no survivors agreed, no
    rescue state, backend never returned) — surfaced by name so the
    caller's outage machinery (or the user) takes over."""


@dataclass
class ElasticCoordinator:
    """One rank's reaction loop. Built by cli.train when --elastic is on;
    `react()` is called with the escaped collective error and either
    re-execs this process into the surviving world (never returns) or
    raises — it NEVER returns normally."""
    steps_dir: str            # the shared step-checkpoint directory
    telemetry_dir: str        # beacons + flight dumps live here
    rank: int
    world: int
    reshape_mode: str
    impl: str                 # PRNG engine for the rescue save
    geometry: dict            # _run_geometry stamp for the rescue save
    ckpt_keep: int = 3
    settle_s: float = None
    member_deadline_s: float = None
    argv_tail: list = field(default_factory=lambda: None)  # None = sys.argv[1:]

    def react(self, exc: BaseException, stash: dict, journal=None):
        """Detect -> rescue -> membership -> re-wire -> re-exec."""
        from ..telemetry import flight, get_registry
        from ..parallel.wireup import (_subprocess_backend_healthy,
                                       backend_wait_env, backoff_schedule)

        gen = world_generation()
        evidence = classify_peer_loss(exc, journal)
        if not evidence["backend_loss"]:
            raise exc  # a program error, not a peer loss — fail fast
        flight.record("elastic_peer_loss", generation=gen, world=self.world,
                      rank=self.rank, **{k: v for k, v in evidence.items()
                                         if k != "error"},
                      error=evidence["error"])
        get_registry().counter("elastic.peer_loss").inc()
        if journal is not None:
            # dirty close: the open entry STAYS open in the file — that is
            # the hang evidence `trace report --cluster` attributes
            from ..telemetry import cluster
            cluster.disable_journal(clean=False)

        # -- membership: who else is still alive? -------------------------
        new_gen = next_generation(gen)
        survivors = collect_membership(
            self.telemetry_dir, new_gen, self.rank,
            settle_s=self.settle_s, deadline_s=self.member_deadline_s)
        if self.rank not in survivors:  # (cannot happen: we beaconed)
            survivors = sorted(set(survivors) | {self.rank})
        if len(survivors) >= self.world:
            # every rank beaconed: nobody died — a transient backend blip,
            # not a membership change. Hand back to the outage machinery.
            clear_beacons(self.telemetry_dir, new_gen)
            flight.record("elastic_no_peer_lost", generation=gen,
                          survivors=survivors)
            raise exc
        new_rank = survivors.index(self.rank)
        new_world = len(survivors)
        lost = sorted(set(range(self.world)) - set(survivors))
        flight.record("elastic_membership", generation=new_gen,
                      survivors=survivors, lost=lost, new_rank=new_rank,
                      new_world=new_world)
        print(f"[elastic] peer loss at generation {gen}: rank(s) {lost} "
              f"gone; surviving {survivors} re-rank to 0..{new_world - 1} "
              f"under generation {new_gen}", file=sys.stderr, flush=True)

        # -- rescue: lowest survivor pins the stash -----------------------
        self._rescue(stash, new_gen, is_leader=new_rank == 0)

        # -- re-wire: wait for a healthy backend, jittered backoff --------
        budget = backend_wait_env(600.0)
        deadline = time.monotonic() + budget
        for attempt, delay in enumerate(
                backoff_schedule(1.0, 30.0, seed=self.rank)):
            healthy = _subprocess_backend_healthy(
                min(45.0, max(deadline - time.monotonic(), 1.0)))
            flight.record("elastic_rewire_probe", attempt=attempt,
                          healthy=healthy, next_wait_s=round(delay, 2),
                          generation=new_gen)
            if healthy:
                break
            if time.monotonic() + delay > deadline:
                flight.dump(reason="elastic: backend never recovered for "
                                   "the re-wire")
                raise ElasticHandoffError(
                    f"elastic re-wire: backend stayed unhealthy for "
                    f"{budget:.0f}s after the peer loss; cannot rebuild "
                    f"the surviving world")
            time.sleep(delay)

        get_registry().gauge("elastic.generation").set(new_gen)
        get_registry().gauge("elastic.world").set(new_world)
        get_registry().counter("elastic.rewires").inc()
        self._reexec(new_gen, new_rank, new_world)

    # -- pieces (separately testable) -------------------------------------

    def _rescue(self, stash: dict, new_gen: int, *, is_leader: bool):
        """Pin the last stashed state as a rescue checkpoint. Leader-only
        (lowest surviving rank): params are replicated, so one committed
        copy serves every survivor's resume — and the leader may well NOT
        be old rank 0 (the dead rank often is)."""
        from ..telemetry import flight, get_registry
        if not is_leader:
            return None
        if not stash or "params" not in stash:
            flight.record("elastic_rescue_skipped", generation=new_gen,
                          reason="no stashed state yet")
            print("[elastic] no stashed state to rescue (loss before the "
                  "first checkpoint interval); resuming from the newest "
                  "committed step checkpoint instead",
                  file=sys.stderr, flush=True)
            return None
        from ..train.checkpoint import CheckpointError
        from ..train.ckpt_manager import CheckpointManager
        mgr = CheckpointManager(self.steps_dir, keep=self.ckpt_keep)
        meta = dict(self.geometry)
        meta["elastic_gen"] = new_gen
        try:
            path = mgr.save(stash["params"], stash["key"], self.impl,
                            step=stash.get("step", 0),
                            epoch=stash.get("epoch", 0),
                            offset=stash.get("offset", 0),
                            meta=meta, pin=True, resid=stash.get("resid"))
        except CheckpointError as e:
            # a failed rescue must not kill the reaction: the routine step
            # checkpoints are still on disk
            flight.record("elastic_rescue_failed", generation=new_gen,
                          error=str(e)[:500])
            print(f"[elastic] rescue checkpoint failed ({e}); falling back "
                  f"to the newest committed step checkpoint",
                  file=sys.stderr, flush=True)
            return None
        flight.record("elastic_rescue", generation=new_gen, path=path,
                      step=stash.get("step", 0))
        get_registry().counter("elastic.rescues").inc()
        print(f"[elastic] rescue checkpoint pinned: {path}",
              file=sys.stderr, flush=True)
        return path

    def rewire_env(self, new_gen: int, new_rank: int,
                   new_world: int) -> dict:
        """The env delta the re-exec'd process rendezvouses under: dense
        new rank/world, the generation counter, and generation-derived
        MASTER_PORT (never the old world's socket)."""
        base_port = int(os.environ.get("MASTER_PORT", "29500"))
        # base port = the ORIGINAL launch's port: un-apply this process's
        # own generation offset so port math never compounds across
        # repeated shrinks
        base_port -= world_generation()
        return {
            "RANK": str(new_rank),
            "WORLD_SIZE": str(new_world),
            "MASTER_ADDR": os.environ.get("MASTER_ADDR", "127.0.0.1"),
            "MASTER_PORT": str(rendezvous_port(base_port, new_gen)),
            GEN_ENV: str(new_gen),
        }

    def reexec_argv(self) -> list:
        """This launch's argv with any --resume/--start_epoch replaced by
        a resume from the shared steps directory, and the wireup method
        forced to `env` (the re-wire env above IS the topology; a
        scheduler-derived method would re-read the DEAD world's vars)."""
        argv = list(self.argv_tail if self.argv_tail is not None
                    else sys.argv[1:])
        argv = _strip_opt(argv, "--resume", 1)
        argv = _strip_opt(argv, "--start_epoch", 1)
        argv = _strip_opt(argv, "--wireup_method", 1)
        return argv + ["--resume", self.steps_dir,
                       "--wireup_method", "env"]

    def _reexec(self, new_gen: int, new_rank: int, new_world: int):
        """Replace this process with the surviving world's member. execv
        IS the teardown: the old client's sockets close with the image,
        and the fresh wireup re-rendezvouses cleanly (the same contract as
        the outage path's _persist_and_reexec)."""
        os.environ.update(self.rewire_env(new_gen, new_rank, new_world))
        argv = self.reexec_argv()
        print(f"[elastic] re-wiring: rank {self.rank} -> {new_rank} of "
              f"{new_world}, generation {new_gen}; re-exec with "
              f"--resume {self.steps_dir} --reshape {self.reshape_mode}",
              file=sys.stderr, flush=True)
        sys.stdout.flush()
        sys.stderr.flush()
        os.execv(sys.executable, [sys.executable, "-m",
                                  "pytorch_ddp_mnist_tpu.cli.train", *argv])


def _strip_opt(argv: list, flag: str, nvalues: int) -> list:
    """Drop every `flag [value...]` occurrence (both '--flag v' and
    '--flag=v' spellings)."""
    out = []
    i = 0
    while i < len(argv):
        if argv[i] == flag:
            i += 1 + nvalues
            continue
        if argv[i].startswith(flag + "="):
            i += 1
            continue
        out.append(argv[i])
        i += 1
    return out
