"""Checkpoint-geometry re-mapping — the elastic shrink/grow math.

A step-checkpoint manifest is stamped with the run geometry it was written
under (`cli.train._run_geometry`): `(epoch, offset)` address batches of a
specific `global_batch`, and the int8 error-feedback residual is shaped
`(n_devices, elems)`. Today a resume under ANY other geometry is refused
by name (train/ckpt_manager.geometry_mismatch_message) — correct for an
accidental flag change, fatal for elastic training, where losing a rank
IS a geometry change. This module computes the deliberate re-mapping
instead, with semantics pinned by tests/test_elastic.py.

Two reshape modes (`--reshape`):

  global_batch  (default) the GLOBAL batch is preserved: each surviving
                device takes a larger micro-batch (manifest global_batch /
                new device count — must divide, refused by name
                otherwise). The optimizer trajectory keeps its effective
                batch and lr scaling; the sampler offset is preserved
                verbatim (offset counts GLOBAL batches, and the global
                batch did not change). The int8 error-feedback residual is
                RE-MAPPED: dead device rows fold into survivors
                round-robin — new_row[i] = sum(old_row[j] for j % new_n
                == i) — preserving the total outstanding quantization
                error exactly (f32 adds, drift bound 0 beyond addition
                reordering); on grow, surviving rows keep their residual
                and new devices start at zero.

  per_rank      the PER-DEVICE batch is fixed: the global batch shrinks
                (or grows) with the world — degraded throughput, but no
                divisibility constraint. (epoch, offset) address DIFFERENT
                sample counts now, so the offset is re-mapped by samples
                consumed: new_offset = old_offset * old_gb // new_gb
                (floor — up to one new-geometry batch's samples replay,
                never skipped). The residual is DROPPED deliberately
                (per-device rows have no meaning when every device's
                batch share changed): at most one step's quantization
                error is lost — the same bound the multi-host residual
                skip in cli.train already documents.

Both modes re-shard the `ShardedSampler` (the global permutation is a
pure function of seed+epoch — world-independent, so survivors re-split
the SAME order) and, through it, the `pipeline/` rank assignment
(`pipeline.reader.reshard_source`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

RESHAPE_MODES = ("global_batch", "per_rank")


class ReshapeError(ValueError):
    """A geometry re-mapping that cannot be done soundly — refused by name
    (never silently degraded)."""


@dataclass
class ReshapePlan:
    """The re-mapping from a manifest's geometry to the new world's, fully
    determined before any state is touched."""
    mode: str
    old_global_batch: int
    new_global_batch: int
    per_device_batch: int     # each device's micro-batch under the plan
    old_devices: int
    new_devices: int
    offset_map: str           # "preserved" | "floor_rescaled"
    resid_map: str            # "folded" | "grown_zeros" | "dropped" | "kept"

    @property
    def changed(self) -> bool:
        return (self.old_devices != self.new_devices
                or self.old_global_batch != self.new_global_batch)


def plan_reshape(old_global_batch: int, old_devices: int, new_devices: int,
                 *, mode: str, per_device_batch: int = 0) -> ReshapePlan:
    """Compute the reshape plan; raises ReshapeError naming any unsound
    geometry instead of producing one.

    `per_device_batch` is the new run's --batch_size — consulted only by
    per_rank mode (global_batch mode DERIVES the micro-batch from the
    manifest instead, which is the point of the mode)."""
    if mode not in RESHAPE_MODES:
        raise ReshapeError(f"unknown reshape mode {mode!r}; expected one "
                           f"of {RESHAPE_MODES}")
    if old_devices < 1 or new_devices < 1:
        raise ReshapeError(f"device counts must be >= 1; got "
                           f"{old_devices} -> {new_devices}")
    if mode == "global_batch":
        if old_global_batch % new_devices:
            raise ReshapeError(
                f"--reshape global_batch preserves the manifest's global "
                f"batch ({old_global_batch}) by re-splitting it over the "
                f"surviving devices, but {old_global_batch} is not "
                f"divisible by {new_devices} device(s) — use --reshape "
                f"per_rank (fixed per-device batch, global batch scales "
                f"with the world) for this geometry")
        micro = old_global_batch // new_devices
        resid = ("kept" if new_devices == old_devices
                 else "folded" if new_devices < old_devices
                 else "grown_zeros")
        return ReshapePlan(mode=mode, old_global_batch=old_global_batch,
                           new_global_batch=old_global_batch,
                           per_device_batch=micro, old_devices=old_devices,
                           new_devices=new_devices, offset_map="preserved",
                           resid_map=resid)
    if per_device_batch < 1:
        raise ReshapeError("--reshape per_rank keeps the per-device batch "
                           "fixed; it needs --batch_size >= 1")
    new_gb = per_device_batch * new_devices
    return ReshapePlan(mode=mode, old_global_batch=old_global_batch,
                       new_global_batch=new_gb,
                       per_device_batch=per_device_batch,
                       old_devices=old_devices, new_devices=new_devices,
                       offset_map=("preserved" if new_gb == old_global_batch
                                   else "floor_rescaled"),
                       resid_map=("kept" if new_gb == old_global_batch
                                  and new_devices == old_devices
                                  else "dropped"))


def remap_offset(offset: int, plan: ReshapePlan) -> int:
    """The sampler offset under the plan's new global batch.

    `offset` counts whole GLOBAL batches consumed in the epoch in
    progress. global_batch mode preserves it verbatim (same global batch
    -> same sample position). per_rank mode re-maps by SAMPLES consumed,
    flooring to a whole new-geometry batch: up to new_global_batch - 1
    samples of the epoch replay (training twice is benign; silently
    skipping samples would not be)."""
    if offset < 0:
        raise ReshapeError(f"offset must be >= 0; got {offset}")
    if plan.offset_map == "preserved":
        return int(offset)
    samples = int(offset) * plan.old_global_batch
    return samples // plan.new_global_batch


def remap_residual(resid: Optional[Any], plan: ReshapePlan):
    """The int8 error-feedback residual under the plan.

    Returns `(new_resid, disposition)` where disposition is the plan's
    resid_map string. The fold rule (global_batch shrink) is the
    documented one the tests pin: dead device row j lands in surviving
    row j % new_n, so column sums — the total outstanding quantization
    error per element — are preserved exactly up to f32 addition
    reordering. Grow appends zero rows (new devices owe no error yet).
    per_rank DROPS the residual (None): per-device rows are meaningless
    once every device's share of the batch changed; the cost is bounded
    at ONE step's quantization error, same as the documented multi-host
    degrade in cli.train's step hook."""
    if resid is None:
        return None, "absent"
    arr = np.asarray(resid, np.float32)
    if arr.ndim != 2:
        raise ReshapeError(f"residual must be (n_devices, elems); got "
                           f"shape {arr.shape}")
    if arr.shape[0] != plan.old_devices:
        raise ReshapeError(
            f"residual carries {arr.shape[0]} device row(s) but the "
            f"manifest geometry says {plan.old_devices} — refusing to "
            f"re-map inconsistent state")
    if plan.resid_map == "dropped":
        return None, "dropped"
    if plan.resid_map == "kept" or plan.new_devices == plan.old_devices:
        return arr, "kept"
    if plan.new_devices < plan.old_devices:
        out = np.zeros((plan.new_devices, arr.shape[1]), np.float32)
        for j in range(plan.old_devices):
            out[j % plan.new_devices] += arr[j]
        return out, "folded"
    out = np.zeros((plan.new_devices, arr.shape[1]), np.float32)
    out[:plan.old_devices] = arr
    return out, "grown_zeros"


def reshape_checkpoint(restored, plan: ReshapePlan):
    """Apply the plan to a restored StepCheckpoint-shaped object: returns
    `(new_offset, new_resid, resid_disposition)`. The params/key are
    geometry-free (replicated) and pass through untouched; the caller
    re-stamps the manifest meta with the NEW geometry on its next save."""
    new_offset = remap_offset(restored.offset, plan)
    new_resid, disposition = remap_residual(restored.resid, plan)
    return new_offset, new_resid, disposition


def reshard_sampler(sampler, plan: ReshapePlan, *, rank: int,
                    num_replicas: int):
    """Re-split the sampler for the new membership (ShardedSampler.reshard
    — same global permutation, new round-robin split). Thin veneer so the
    elastic call site reads as part of one reshape."""
    return sampler.reshard(num_replicas, rank)
