"""Preemption-tolerant elastic training (docs/ROBUSTNESS.md §Elastic).

`coordinator.py` is the per-rank reaction loop over the PR 14-15 detection
signals (peer loss -> rescue -> membership beacons -> re-wire under the
next world generation); `reshape.py` is the checkpoint-geometry re-mapping
(`--reshape global_batch|per_rank`) that lets a manifest written at one
world size resume at another. `--elastic` off leaves training
bitwise-identical to the non-elastic CLI (pinned by tests/test_elastic.py).
"""

from .coordinator import (ElasticCoordinator, ElasticHandoffError,  # noqa: F401
                          classify_peer_loss, clear_beacons,
                          collect_membership, next_generation,
                          read_beacons, rendezvous_port, world_generation,
                          write_beacon)
from .reshape import (RESHAPE_MODES, ReshapeError, ReshapePlan,  # noqa: F401
                      plan_reshape, remap_offset, remap_residual,
                      reshape_checkpoint, reshard_sampler)
