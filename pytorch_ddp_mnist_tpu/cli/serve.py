"""Serving CLI — turn a training checkpoint into a running inference
service.

    python -m pytorch_ddp_mnist_tpu serve --checkpoint model.msgpack

Two front doors over the same `serve.ServeService` request path:

* default: a newline-delimited-JSON TCP server. One request per line,
  `{"pixels": [784 numbers]}` -> `{"ok": true, "pred": k}`;
  `{"op": "metrics"}` -> the serving dashboard snapshot; `{"op": "stats"}`
  -> the unified telemetry registry snapshot (serve counters + latency
  histogram, XLA compile counter, memory gauges — docs/OBSERVABILITY.md)
  alongside the dashboard; `{"op": "health"}` -> the live SLO view
  (rolling-window p99 + observed service rate + queue depth — the inputs
  SLO-aware admission will consume); backpressure rejections
  answer `{"ok": false, "error": ..., "retry_after_ms": ...}` without
  closing the connection. `--port 0` binds an ephemeral port and prints
  `serving on HOST:PORT` (stderr) so a harness can connect. SIGINT/SIGTERM
  triggers the graceful drain: in-flight requests finish, new ones are
  refused, then the loop exits and the final metrics snapshot prints.
* `--selftest N`: no socket — drive N open-loop Poisson requests through
  the full admission/batcher/engine path in-process and print the metrics
  snapshot as one JSON line. The smoke entry `make serve-smoke` and tests
  use this.

Without `--checkpoint` the engine serves freshly initialized params
(`--seed`) — the full path exercisable anywhere, including under
JAX_PLATFORMS=cpu where the whole subsystem behaves identically.

`--telemetry DIR` turns on request-scoped tracing: every request/batch
leaves schema-v1 spans under DIR (read back with `trace report --serve
DIR`), and the drain flushes the slowest-request exemplars + any rejects
to a flight-recorder dump beside them. `--admit predicted_p99` switches
admission from the raw depth budget to the SLO boundary (`--slo_p99_ms`)
— docs/SERVING.md §Admission modes.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

import numpy as np


def initial_params(a):
    """The weights generation 0 serves: the --checkpoint file (msgpack or
    the reference's torch .pt) or a fresh --seed init."""
    import jax

    from ..models import init_mlp
    from ..train.checkpoint import load_checkpoint

    if a.checkpoint:
        return load_checkpoint(a.checkpoint, init_mlp(jax.random.key(0)))
    return init_mlp(jax.random.key(a.seed))


def engine_builder(a):
    """`build(params) -> InferenceEngine` with the CLI's geometry baked
    in — called once for a single-engine service, N times (plus per
    restart / per reload generation) by `FleetService`."""
    from ..parallel import data_parallel_mesh
    from ..serve import InferenceEngine

    mesh = None
    if a.mesh:
        mesh = data_parallel_mesh()
        if mesh.devices.size == 1:
            mesh = None  # 1-device mesh is the serial engine

    def build(params):
        return InferenceEngine(params, max_batch=a.max_batch, mesh=mesh,
                               input_dtype=a.input_dtype)
    return build


def build_engine(a):
    return engine_builder(a)(initial_params(a))


async def handle_request(service, req: dict) -> dict:
    """One JSON request -> one JSON response dict (the protocol core,
    transport-free so tests drive it without a socket):

      {"pixels": [...784...]}  -> {"ok": true, "pred": k}
      {"op": "metrics"}        -> the serving dashboard snapshot (legacy)
      {"op": "stats"}          -> {"registry": <telemetry registry
                                   snapshot — serve.* counters/histograms,
                                   compile counter, memory gauges>,
                                   "serve": <dashboard snapshot, incl. the
                                   "attribution" section: per-stage
                                   p50/p99 under the serve/tracing.py
                                   stage names + current predicted_p99 —
                                   the same names the JSONL trace uses,
                                   so the health op and the trace can
                                   never disagree>}
      {"op": "health"}         -> the LIVE health view: the rolling-window
                                   SLO monitor (rolling p50/p99, observed
                                   service rate over the recent window),
                                   the predicted p99 the admission SLO
                                   boundary consumes, plus the
                                   instantaneous queue depth
    """
    op = req.get("op")
    if op == "metrics":
        return {"ok": True, **service.metrics.snapshot()}
    if op == "stats":
        from ..telemetry import collect_memory
        reg = service.metrics.registry
        collect_memory(reg)  # stats reads the instant, not construction time
        return {"ok": True, "registry": reg.snapshot(),
                "serve": service.metrics.snapshot()}
    if op == "health":
        pred = service.metrics.predicted_p99()
        health = {**service.metrics.slo.snapshot(),
                  "predicted_p99_ms": (round(pred * 1e3, 3)
                                       if pred is not None
                                       else None),
                  "queue_depth": service.admission.depth,
                  "draining": service.admission.draining}
        # a fleet front door also answers replica states, degradation and
        # the failover/restart/reload counters (--replicas / --reload_dir)
        fleet_snap = getattr(service, "fleet_snapshot", None)
        if fleet_snap is not None:
            health["fleet"] = fleet_snap()
        return {"ok": True, "health": health}
    pixels = np.asarray(req["pixels"])
    return {"ok": True, "pred": await service.handle(pixels)}


async def _handle_conn(service, reader, writer):
    from ..serve import Rejected
    while True:
        line = await reader.readline()
        if not line:
            break
        try:
            resp = await handle_request(service, json.loads(line))
        except Rejected as e:
            resp = {"ok": False, "error": e.reason,
                    "retry_after_ms": round(e.retry_after_s * 1e3, 1)}
        except Exception as e:  # malformed request: answer, don't die
            resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
        writer.write((json.dumps(resp) + "\n").encode())
        await writer.drain()
    writer.close()


async def _serve_tcp(service, host: str, port: int,
                     reload_dir: str | None = None,
                     poll_interval_s: float = 0.25) -> None:
    import signal

    watcher = None
    if reload_dir:
        from ..serve import ReloadWatcher
        watcher = ReloadWatcher(service, reload_dir,
                                poll_interval_s=poll_interval_s)
        watcher.start()
        print(f"reload watcher: polling {reload_dir} every "
              f"{poll_interval_s}s (serving step "
              f"{service.serving_step})", file=sys.stderr, flush=True)
    server = await asyncio.start_server(
        lambda r, w: _handle_conn(service, r, w), host, port)
    bound = server.sockets[0].getsockname()
    print(f"serving on {bound[0]}:{bound[1]}", file=sys.stderr, flush=True)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:  # non-unix event loop
            pass
    await stop.wait()
    print("drain: refusing new requests, finishing in-flight ones",
          file=sys.stderr, flush=True)
    if watcher is not None:   # no swap may start once the drain begins
        await watcher.stop()
    await service.shutdown()
    server.close()
    await server.wait_closed()
    # Post-mortem: the admission reject ring (telemetry/flight.py) is only
    # non-empty when this server refused requests — flush it so an
    # overloaded-then-killed server leaves WHO it turned away, not just the
    # aggregate counter in the final metrics snapshot.
    from ..telemetry import flight
    dump = flight.dump(reason="serve drain")
    if dump:
        print(f"flight recorder: {dump}", file=sys.stderr, flush=True)


def main(argv=None) -> int:
    from ..parallel.wireup import _honor_platform_env
    _honor_platform_env()

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--checkpoint", default=None,
                   help="params checkpoint to serve (.msgpack or the "
                        "reference's .pt/.pth); default: fresh --seed init")
    p.add_argument("--seed", type=int, default=0,
                   help="init seed when no --checkpoint is given")
    p.add_argument("--max_batch", type=int, default=64,
                   help="largest coalesced batch = top compile bucket "
                        "(powers of two up to it are precompiled)")
    p.add_argument("--max_delay_ms", type=float, default=2.0,
                   help="longest a request waits for coalescing partners "
                        "before its batch flushes anyway")
    p.add_argument("--queue_depth", type=int, default=256,
                   help="admission budget: in-flight requests beyond this "
                        "are rejected with a retry-after hint")
    p.add_argument("--admit", choices=("depth", "predicted_p99"),
                   default="depth",
                   help="admission mode: raw queue-depth budget, or reject "
                        "when the PREDICTED p99 (rolling p99 + queue-drain "
                        "time from the live SLO window) would bust "
                        "--slo_p99_ms (docs/SERVING.md §Admission)")
    p.add_argument("--slo_p99_ms", type=float, default=50.0,
                   help="the p99 SLO (ms) the predicted_p99 admission mode "
                        "protects; ignored under --admit depth")
    p.add_argument("--telemetry", default=None, metavar="DIR",
                   help="emit request/batch spans (schema-v1 JSONL) and "
                        "drain-time flight dumps under DIR; read back with "
                        "`trace report --serve DIR` "
                        "(docs/OBSERVABILITY.md §Request tracing)")
    p.add_argument("--input_dtype", choices=("float32", "uint8"),
                   default="float32",
                   help="request payload dtype: pre-normalized float32 "
                        "rows, or raw uint8 pixels normalized on device "
                        "(the training path's exact op chain)")
    p.add_argument("--mesh", action="store_true",
                   help="replicate over every device of the data-parallel "
                        "mesh (each batch's rows shard across chips); "
                        "default single-device")
    p.add_argument("--no_fast", dest="fast", action="store_false",
                   help="force the legacy stack-at-flush batcher instead "
                        "of the staged fast path (persistent staging "
                        "buffers + off-loop reply scatter) — an A/B and "
                        "escape hatch (docs/SERVING.md §Fast path)")
    p.add_argument("--replicas", type=int, default=1,
                   help="engine replicas behind the shared admission "
                        "layer; >1 enables SLO-aware routing, the wedge "
                        "watchdog and bounded request failover "
                        "(docs/SERVING.md §Replica fleet & hot reload)")
    p.add_argument("--reload_dir", default=None, metavar="DIR",
                   help="watch this checkpoint directory (train/"
                        "ckpt_manager layout) and hot-swap replicas to "
                        "newly committed steps behind per-replica drains; "
                        "torn/non-finite candidates are refused by name "
                        "while the incumbent keeps serving (TCP mode only)")
    p.add_argument("--wedge_timeout_ms", type=float, default=250.0,
                   help="fleet watchdog: a replica whose oldest in-flight "
                        "batch ages past this is quarantined, its requests "
                        "failed over to a survivor, and it is restarted")
    p.add_argument("--retry_budget", type=int, default=2,
                   help="failover attempts per admitted request before it "
                        "errors out (bounds the work one poisoned request "
                        "can burn across the fleet)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="TCP port (0 = ephemeral; the bound port prints "
                        "to stderr)")
    p.add_argument("--selftest", type=int, default=None, metavar="N",
                   help="serve N open-loop Poisson requests in-process "
                        "and print the metrics snapshot (no socket)")
    p.add_argument("--offered_rps", type=float, default=500.0,
                   help="--selftest arrival rate")
    p.add_argument("--shape", choices=("poisson", "ramp", "spike"),
                   default="poisson",
                   help="--selftest arrival shape: homogeneous poisson, a "
                        "0.2x->1.8x linear ramp, or a 3x mid-run burst "
                        "(docs/SERVING.md §Load generator)")
    a = p.parse_args(argv)
    for name in ("max_batch", "queue_depth", "replicas"):
        if getattr(a, name) < 1:
            p.error(f"--{name} must be >= 1")
    if a.max_delay_ms < 0:
        p.error("--max_delay_ms must be >= 0")
    if a.admit == "predicted_p99" and a.slo_p99_ms <= 0:
        p.error("--slo_p99_ms must be > 0 under --admit predicted_p99")
    if a.wedge_timeout_ms <= 0:
        p.error("--wedge_timeout_ms must be > 0")
    if a.retry_budget < 0:
        p.error("--retry_budget must be >= 0")
    if a.reload_dir and a.selftest is not None:
        p.error("--reload_dir needs the TCP server (the watcher lives on "
                "its event loop); drop --selftest")

    from ..serve import ServeService
    from .. import telemetry
    from ..telemetry import flight
    # Serve metrics publish into the process-wide registry so the
    # {"op": "stats"} endpoint answers one unified snapshot; the compile
    # listener is armed BEFORE the engine warms its bucket ladder so the
    # warmup compiles are on the record (and anything after warmup would
    # be visible evidence of a cold compile).
    telemetry.install_compile_listener()
    reg = telemetry.get_registry()
    # live HBM watermark gauges (mem.*): the stats snapshot and any
    # Prometheus scrape read the instant (guarded probes, None on CPU)
    telemetry.install_memory_watermarks(reg)
    if a.telemetry:
        # request/batch spans into DIR (the tracer swap happens BEFORE the
        # first request, so every request_id is on the record), and the
        # flight recorder's drain dump lands beside the trace
        telemetry.enable(a.telemetry)
        flight.set_dump_dir(a.telemetry)
    common = dict(max_delay_ms=a.max_delay_ms, max_depth=a.queue_depth,
                  registry=reg, admit_mode=a.admit,
                  slo_p99_s=(a.slo_p99_ms / 1e3
                             if a.admit == "predicted_p99" else None),
                  fast=a.fast)
    fleet_mode = a.replicas > 1 or a.reload_dir
    if fleet_mode:
        # N replicas (or 1 + hot reload, which still needs the fleet's
        # drain-and-swap machinery) behind the same admission layer
        from ..serve import FleetService
        service = FleetService(
            engine_builder(a), initial_params(a), n_replicas=a.replicas,
            max_batch=a.max_batch,
            wedge_timeout_s=a.wedge_timeout_ms / 1e3,
            retry_budget=a.retry_budget, **common)
        engine = service.engine
    else:
        engine = build_engine(a)
        service = ServeService(engine, **common)
    telemetry.record_engine_compiles(reg, engine.compile_count)
    print(f"engine warm: buckets={list(engine.buckets)} "
          f"compiles={engine.compile_count} "
          f"input_dtype={engine.input_dtype} admit={a.admit} "
          f"replicas={a.replicas} "
          f"fast={'on' if service.batcher.fast_path else 'off'}",
          file=sys.stderr, flush=True)

    def _close_telemetry(reason: str, dump: bool = True) -> None:
        """End-of-run trace hygiene: stamp the final registry snapshot
        (check_telemetry --require serve. gates on it), flush the flight
        ring (slow-request exemplars + rejects; skipped when the TCP
        drain already dumped it), close the JSONL file."""
        if not a.telemetry:
            return
        telemetry.get_tracer().snapshot(reg)
        if dump:
            path = flight.dump(reason=reason)
            if path:
                print(f"flight recorder: {path}", file=sys.stderr,
                      flush=True)
        telemetry.disable()

    if a.selftest is not None:
        if a.selftest < 1:
            p.error("--selftest must be >= 1")
        from ..serve.loadgen import run_loadgen
        out = run_loadgen(service, offered_rps=a.offered_rps,
                          n_requests=a.selftest, seed=a.seed,
                          shape=a.shape)
        out.pop("predictions")          # counters, not payloads
        _close_telemetry("serve selftest")
        print(json.dumps(out))
        return 0

    asyncio.run(_serve_tcp(service, a.host, a.port,
                           reload_dir=a.reload_dir))
    _close_telemetry("serve drain", dump=False)  # _serve_tcp just dumped
    print(json.dumps(service.metrics.snapshot()))
    return 0


if __name__ == "__main__":
    sys.exit(main())
