"""Trace CLI — read side of `--telemetry`: analyze, gate, and export the
JSONL event traces training and serving emit.

    python -m pytorch_ddp_mnist_tpu trace report /tmp/obs
    python -m pytorch_ddp_mnist_tpu trace report /tmp/obs --json > new.json
    python -m pytorch_ddp_mnist_tpu trace report /tmp/obs \
        --baseline old_run/ --threshold 1.5      # exit 3 past threshold
    python -m pytorch_ddp_mnist_tpu trace report --serve /tmp/serve_obs
                                                 # serve-path attribution
    python -m pytorch_ddp_mnist_tpu trace report --serve /tmp/serve_obs \
        --baseline OLD       # stage-share gate: exit 3 when compute's
                             # share of e2e drops past --threshold
    python -m pytorch_ddp_mnist_tpu trace report --data /tmp/obs \
        [--baseline OLD]            # input attribution + data-share gate
    python -m pytorch_ddp_mnist_tpu trace report --cluster /tmp/obs
                     # cluster forensics from per-rank collective journals
                     # (--journal runs): desync (exit 3, both ranks named),
                     # per-rank-pair straggler skew, hang report
    python -m pytorch_ddp_mnist_tpu trace report --overhead /tmp/obs \
        [--baseline OLD]   # dispatch-overhead attribution (named host
                           # phases, >=90% coverage assert, worst phase;
                           # gate: exit 3 when a phase's share grows) —
                           # target may also be a stamped DDP artifact
    python -m pytorch_ddp_mnist_tpu trace export /tmp/obs -o trace.json
                                                 # load in Perfetto
    python -m pytorch_ddp_mnist_tpu trace cost -o COST.json \
        [--telemetry DIR] [--model mlp --param_scale 16]
                                    # HARVEST per-program cost records
    python -m pytorch_ddp_mnist_tpu trace report --cost COST.json \
        [--baseline OLD]     # program forensics + compile/HBM/efficiency
                             # gate (also takes MULTICHIP_r0X.json)

`report --data` reads the per-epoch `data_wait` spans a `--telemetry`
streaming train run emits and prints the input-attribution story: what
share of each epoch the host spent blocked on the input pipeline
(p50/p95/max of data_wait/epoch). With `--baseline` it becomes the
data_wait-share regression gate — exit 3 when the share regresses past
`--threshold` (sub-millisecond waits exempt), mirroring the step-time and
efficiency gates so a pipeline win cannot silently rot (docs/DATA.md).

`report --serve` reads the request/batch spans a `--telemetry`-enabled
serve run emits (serve/tracing.py) and prints the tail-latency
attribution: per-stage p50/p95/p99 and each stage's share of end-to-end
time (admission / queue / batch_form / pad_h2d / compute / reply — they
telescope, so the shares genuinely decompose the e2e story), batch
occupancy / padding waste / coalesce-reason counts, and the slowest-K
requests as full stage trees. With `--baseline OLD` (a trace dir/file or
a saved `--serve --json` report) it becomes the stage-SHARE regression
gate: exit 3 when compute's share of e2e drops — or an overhead stage's
share grows — past `--threshold`, sub-millisecond stages exempt. This is
how the fast-path wins are pinned (`make serve-fast-smoke`,
docs/SERVING.md §Fast path).

`report` merges every per-process `events*.jsonl` under the target (a
--telemetry dir, a single file, or several), reconstructs the span tree,
and prints per-phase step-time statistics (data_wait / step_compute / eval /
fused_run: p50/p95/max), the per-epoch trend, and cross-process straggler
skew. `--baseline OLD` diffs against another run — OLD may be a trace
dir/file or a saved `--json` report — and exits 3 when any phase's p50/p95
regresses past `--threshold`x: the step-time regression gate bench.py and
CI hang off (`make trace-smoke`).

Target and baseline may also be DDP bench artifacts (MULTICHIP_r0X.json /
anything carrying `strategies` rows): the gate then compares each
strategy's `scaling_efficiency_vs_1dev` and exits 3 when efficiency drops
past the same threshold — the multichip efficiency regression gate:

    python -m pytorch_ddp_mnist_tpu trace report MULTICHIP_r08.json \
        --baseline MULTICHIP_r07.json

Only rows measured on the SAME workload pair up: a row's label carries
its `--model`/`--param_scale` when non-default, so a scale-16 artifact
never false-regresses against a scale-1 baseline (they share no rows).

`export` renders the merged trace as Chrome trace-event JSON, loadable in
Perfetto (https://ui.perfetto.dev) or `chrome://tracing`: one track per
process, aggregate phase durations on their own thread, counter tracks from
registry snapshots.

Exit codes: 0 ok, 1 unreadable/empty target, 2 usage, 3 regression gate.
"""

from __future__ import annotations

import argparse
import json
import sys


def _load_report(target: str):
    """A report dict from `target`: either a saved `trace report --json`
    file (recognized by its "report" tag; the combined --baseline shape
    `{"report": {...}, "comparison": ...}` unwraps to its report) or a
    trace dir/file to analyze. Returns (report, error_message)."""
    import os

    from ..telemetry import analysis

    paths = analysis.trace_files(target)
    if os.path.isfile(target) and not target.endswith(".jsonl"):
        # An explicitly named non-trace FILE may be a saved report (saved
        # reports are small; never sniffed for dir targets, whose
        # events*.jsonl can be large JSONL streams).
        try:
            with open(target) as f:
                head = json.load(f)
        except ValueError:
            head = None  # not one JSON document: treat as a JSONL trace
        if isinstance(head, dict):
            if head.get("report") == "trace_phase_stats":
                return head, None
            nested = head.get("report")
            if isinstance(nested, dict) \
                    and nested.get("report") == "trace_phase_stats":
                return nested, None  # a saved --baseline --json document
            if isinstance(head.get("strategies"), list):
                # a DDP bench artifact (MULTICHIP_r0X.json): gate on its
                # per-strategy scaling_efficiency_vs_1dev rows — the
                # efficiency regression gate (ROADMAP item 2), same exit-3
                # contract as the step-time phases
                rep = analysis.efficiency_report(head, path=target)
                if rep["records"] == 0:
                    return None, (f"{target}: artifact carries no "
                                  f"strategy rows with "
                                  f"{analysis.EFFICIENCY_STAT}")
                return rep, None
    if not paths:
        return None, f"{target}: no events*.jsonl found"
    report = analysis.analyze(paths)
    if report["records"] == 0:
        return None, f"{target}: empty trace"
    return report, None


def _load_tagged_report(target: str, tag: str, build, is_empty,
                        empty_msg: str):
    """A report from `target`: a saved `--json` file recognized by its
    `tag` (plain, or nested under the combined --baseline shape
    `{"report": {...}, "comparison": ...}`), or a trace dir/file run
    through `build(paths)`. Returns (report, error_message) — the one
    loader the --data and --serve report/gate paths share, so a format
    tweak cannot silently diverge between them."""
    import os

    from ..telemetry import analysis

    if os.path.isfile(target) and not target.endswith(".jsonl"):
        try:
            with open(target) as f:
                head = json.load(f)
        except ValueError:
            head = None  # not one JSON document: treat as a JSONL trace
        if isinstance(head, dict):
            if head.get("report") == tag:
                return head, None
            nested = head.get("report")
            if isinstance(nested, dict) and nested.get("report") == tag:
                return nested, None
    paths = analysis.trace_files(target)
    if not paths:
        return None, f"{target}: no events*.jsonl found"
    report = build(paths)
    if is_empty(report):
        return None, f"{target}: {empty_msg}"
    return report, None


def _load_data_report(target: str):
    from ..telemetry import analysis

    return _load_tagged_report(
        target, "trace_data_stats", analysis.data_report,
        lambda r: r["epochs"] == 0,
        "no epoch spans with data_wait attribution (train with "
        "--telemetry on the STREAMING path to emit them)")


def _load_serve_report(target: str):
    from ..telemetry import analysis

    return _load_tagged_report(
        target, "serve_trace_attribution", analysis.serve_report,
        lambda r: r["requests"] == 0,
        "no serve.request spans (serve with --telemetry DIR to emit "
        "them)")


def _load_overhead_report(target: str):
    """The dispatch-overhead report from `target`: a saved `--overhead
    --json` report, a DDP bench artifact (MULTICHIP_r0X.json — rows
    stamped by `bench.py --mode ddp`'s dispatch probe), or a
    `--profile_dispatch` trace dir/file."""
    import os

    from ..telemetry import analysis

    if os.path.isfile(target) and not target.endswith(".jsonl"):
        try:
            with open(target) as f:
                head = json.load(f)
        except ValueError:
            head = None  # not one JSON document: treat as a JSONL trace
        if isinstance(head, dict):
            if head.get("report") == analysis.OVERHEAD_REPORT_TAG:
                return head, None
            nested = head.get("report")
            if isinstance(nested, dict) \
                    and nested.get("report") == analysis.OVERHEAD_REPORT_TAG:
                return nested, None
            if isinstance(head.get("strategies"), list):
                rep = analysis.overhead_from_artifact(head, path=target)
                if not rep["rows"]:
                    return None, (f"{target}: artifact carries no "
                                  f"strategy rows")
                return rep, None
    return _load_tagged_report(
        target, analysis.OVERHEAD_REPORT_TAG, analysis.overhead_report,
        lambda r: not r["rows"],
        "no dispatch_phase/dispatch_window points (train with "
        "--telemetry DIR --profile_dispatch to emit them)")


def _cmd_ledger_gate(a) -> int:
    """`trace report TARGET --ledger DIR`: the pairwise gates' multi-run
    mode. TARGET (an ingestible artifact — the newest run) gates against
    the ledger HISTORY under DIR instead of one --baseline artifact: the
    median+MAD band of each series' last --window runs. The report-family
    flag narrows which series gate (--serve: serve.*, --data: input.*,
    --cost: cost.*, --overhead: the ddp overhead shares); exit semantics
    match the pairwise gates — 1 when nothing overlapped (the gate
    checked nothing), 3 naming series + runs on regression."""
    import os

    from ..telemetry import ledger as ledger_mod

    try:
        target_rows, _skips = ledger_mod.load_artifact(a.target)
    except ledger_mod.LedgerError as e:
        print(f"trace report: {e}", file=sys.stderr)
        return 1
    prefixes = None
    if a.serve:
        prefixes = ("serve.",)
    elif a.data:
        prefixes = ("input.",)
    elif a.cost:
        prefixes = ("cost.",)
    elif a.overhead:
        prefixes = ("ddp.overhead", "ddp.journal_overhead_share")
    if prefixes:
        target_rows = [r for r in target_rows
                       if r["metric"].startswith(prefixes)]
    if not target_rows:
        print(f"trace report: {a.target}: no gateable ledger rows"
              + (f" for the selected family ({'/'.join(prefixes)}*)"
                 if prefixes else ""), file=sys.stderr)
        return 1
    target_abs = os.path.abspath(a.target)
    history_paths = [p for p in ledger_mod.discover(a.ledger)
                     if os.path.abspath(p) != target_abs]
    try:
        hist = ledger_mod.ingest(history_paths)
    except ledger_mod.LedgerError as e:
        print(f"trace report: --ledger {e}", file=sys.stderr)
        return 1
    target_series = {r["series"] for r in target_rows}
    rows = [r for r in hist["rows"] if r["series"] in target_series]
    rows += target_rows
    rep = ledger_mod.gate(rows, window=a.window, threshold=a.threshold)
    if a.json:
        print(json.dumps(rep, indent=2 if sys.stdout.isatty() else None))
    checked = [s for s in rep["series"] if s["n"] >= 2]
    if not checked:
        print(f"trace report: no series of {a.target} overlaps the "
              f"ledger history under {a.ledger} — the gate checked "
              f"nothing (different workload/backend stamps?)",
              file=sys.stderr)
        return 1
    if rep["failures"]:
        for line in rep["failures"]:
            print(f"trace report: LEDGER REGRESSION {line}",
                  file=sys.stderr)
        return 3
    if not a.json:
        print(f"trace report: ledger gate OK — {len(checked)} series of "
              f"{os.path.basename(a.target)} checked against "
              f"{len(history_paths)} historical artifact(s) (window "
              f"{a.window}, threshold {a.threshold:g}), 0 regressions")
    return 0


def _cmd_report(a) -> int:
    from ..telemetry import analysis

    if a.ledger:
        return _cmd_ledger_gate(a)

    if a.cluster:
        # cluster forensics (docs/OBSERVABILITY.md §Cluster forensics):
        # TARGET is a --telemetry dir holding per-rank journal*.jsonl
        # files (cli/train --journal) — merged into one causal timeline:
        # desync detection (exit 3, naming both ranks and the diverging
        # collective), per-rank-pair straggler attribution, and the hang
        # report (open collectives + every rank's last journal position)
        from ..telemetry import cluster
        if not cluster.journal_files(a.target):
            print(f"trace report: {a.target}: no journal*.jsonl found "
                  f"(train with --journal --telemetry DIR to emit them)",
                  file=sys.stderr)
            return 1
        report = cluster.cluster_report(a.target)
        if a.json:
            print(json.dumps(report,
                             indent=2 if sys.stdout.isatty() else None))
        else:
            print(cluster.format_cluster_report(report))
        if not report["desync"]["ok"]:
            v = report["desync"]["violations"][0]
            print(f"trace report: cross-rank DESYNC at seq {v['seq']} "
                  f"between rank {v['ranks'][0]} and rank {v['ranks'][1]}"
                  f": {v['detail']}", file=sys.stderr)
            return 3
        return 0

    if a.cost:
        # the program-forensics report + the compile/HBM/efficiency gate
        # (docs/OBSERVABILITY.md §Program forensics): TARGET is a saved
        # `trace cost` report (COST_r0X.json) or a DDP bench artifact
        # (MULTICHIP_r0X.json), whose measured rows decompose into
        # analytic compute/comm/overhead shares — framework-free, like
        # the other report paths
        from ..telemetry import costs
        report, err = costs.load_cost_report(a.target,
                                             per_chip_batch=a.batch)
        if err:
            print(f"trace report: {err}", file=sys.stderr)
            return 1
        if a.baseline:
            baseline, err = costs.load_cost_report(a.baseline,
                                                   per_chip_batch=a.batch)
            if err:
                print(f"trace report: baseline {err}", file=sys.stderr)
                return 1
            diff = costs.compare_cost(report, baseline,
                                      threshold=a.threshold)
            if a.json:
                print(json.dumps({"report": report, "comparison": diff},
                                 indent=2 if sys.stdout.isatty() else None))
            else:
                print(costs.format_cost_report(report))
                print(costs.format_compare_cost(diff))
            if not diff["rows"]:
                print("trace report: no cost metric overlaps the baseline "
                      "— the gate checked nothing", file=sys.stderr)
                return 1
            return 3 if diff["regressions"] else 0
        if a.json:
            print(json.dumps(report,
                             indent=2 if sys.stdout.isatty() else None))
        else:
            print(costs.format_cost_report(report))
        return 0

    if a.data:
        # the input-attribution report + the data_wait-share regression
        # gate (docs/DATA.md): exit 3 when the share of epoch time spent
        # blocked on input regresses past --threshold (sub-ms exempt)
        report, err = _load_data_report(a.target)
        if err:
            print(f"trace report: {err}", file=sys.stderr)
            return 1
        if a.baseline:
            baseline, err = _load_data_report(a.baseline)
            if err:
                print(f"trace report: baseline {err}", file=sys.stderr)
                return 1
            diff = analysis.compare_data(report, baseline,
                                         threshold=a.threshold)
            if a.json:
                print(json.dumps({"report": report, "comparison": diff},
                                 indent=2 if sys.stdout.isatty() else None))
            else:
                print(analysis.format_data_report(report))
                print(analysis.format_compare_data(diff))
            if not diff["rows"]:
                print("trace report: no share stat overlaps the baseline "
                      "— the gate checked nothing", file=sys.stderr)
                return 1
            return 3 if diff["regressions"] else 0
        if a.json:
            print(json.dumps(report,
                             indent=2 if sys.stdout.isatty() else None))
        else:
            print(analysis.format_data_report(report))
        return 0

    if a.overhead:
        # the dispatch-overhead attribution report (docs/OBSERVABILITY.md
        # §Dispatch forensics): named host phases + coverage of the
        # profiled window / the roofline's O, worst phase; with
        # --baseline, the phase-SHARE regression gate (exit 3, sub-ms
        # phases exempt). Coverage below OVERHEAD_COVERAGE_MIN is a
        # hard failure — the decomposition stopped explaining the
        # overhead it exists to attribute.
        report, err = _load_overhead_report(a.target)
        if err:
            print(f"trace report: {err}", file=sys.stderr)
            return 1
        if a.baseline:
            baseline, err = _load_overhead_report(a.baseline)
            if err:
                print(f"trace report: baseline {err}", file=sys.stderr)
                return 1
            diff = analysis.compare_overhead(report, baseline,
                                             threshold=a.threshold)
            if a.json:
                print(json.dumps({"report": report, "comparison": diff},
                                 indent=2 if sys.stdout.isatty() else None))
            else:
                print(analysis.format_overhead_report(report))
                print(analysis.format_compare_overhead(diff))
            if not diff["rows"]:
                print("trace report: no phase share overlaps the baseline "
                      "— the gate checked nothing", file=sys.stderr)
                return 1
            return 3 if diff["regressions"] else 0
        if a.json:
            print(json.dumps(report,
                             indent=2 if sys.stdout.isatty() else None))
        else:
            print(analysis.format_overhead_report(report))
        low = [r for r in report["rows"]
               if isinstance(r.get("coverage"), (int, float))
               and not r.get("note")
               and r["coverage"] < analysis.OVERHEAD_COVERAGE_MIN]
        if low:
            r = low[0]
            print(f"trace report: {r['program']}: phases explain only "
                  f"{r['coverage']:.0%} of the overhead window (floor "
                  f"{analysis.OVERHEAD_COVERAGE_MIN:.0%}) — unprofiled "
                  f"host work grew outside the named phases",
                  file=sys.stderr)
            return 1
        return 0

    if a.serve:
        # the serve-path attribution report (docs/OBSERVABILITY.md
        # §Request tracing): per-stage p50/p95/p99 + %-of-e2e, batch
        # occupancy/padding waste, slowest-request exemplar trees; with
        # --baseline, the stage-SHARE regression gate — exit 3 when
        # compute's share of e2e drops (or an overhead stage's share
        # grows) past --threshold, sub-ms stages exempt (docs/SERVING.md
        # §Fast path)
        report, err = _load_serve_report(a.target)
        if err:
            print(f"trace report: {err}", file=sys.stderr)
            return 1
        if a.baseline:
            baseline, err = _load_serve_report(a.baseline)
            if err:
                print(f"trace report: baseline {err}", file=sys.stderr)
                return 1
            diff = analysis.compare_serve(report, baseline,
                                          threshold=a.threshold)
            if a.json:
                print(json.dumps({"report": report, "comparison": diff},
                                 indent=2 if sys.stdout.isatty() else None))
            else:
                print(analysis.format_serve_report(report))
                print(analysis.format_compare_serve(diff))
            if not diff["rows"]:
                print("trace report: no stage share overlaps the baseline "
                      "— the gate checked nothing", file=sys.stderr)
                return 1
            return 3 if diff["regressions"] else 0
        if a.json:
            print(json.dumps(report,
                             indent=2 if sys.stdout.isatty() else None))
        else:
            print(analysis.format_serve_report(report))
        return 0

    report, err = _load_report(a.target)
    if err:
        print(f"trace report: {err}", file=sys.stderr)
        return 1
    if a.baseline:
        baseline, err = _load_report(a.baseline)
        if err:
            print(f"trace report: baseline {err}", file=sys.stderr)
            return 1
        diff = analysis.compare(report, baseline, threshold=a.threshold)
        if a.json:
            print(json.dumps({"report": report, "comparison": diff},
                             indent=2 if sys.stdout.isatty() else None))
        else:
            print(analysis.format_report(report))
            print(analysis.format_compare(diff))
        if not diff["rows"]:
            # ZERO overlapping (phase, stat) rows means the gate compared
            # nothing — renamed/dropped spans or a fused run against a
            # non-fused baseline. A silent PASS here would let a real
            # regression in the missing phase sail through CI.
            print("trace report: no phase overlaps the baseline — the "
                  "gate checked nothing (renamed spans? fused vs "
                  "non-fused run?)", file=sys.stderr)
            return 1
        return 3 if diff["regressions"] else 0
    if a.json:
        print(json.dumps(report,
                         indent=2 if sys.stdout.isatty() else None))
    else:
        print(analysis.format_report(report))
    return 0


def _cmd_cost(a) -> int:
    from ..telemetry import costs
    if a.param_scale < 1 or a.batch < 1 or a.n_devices < 1:
        print("trace cost: --param_scale/--batch/--n_devices must be >= 1",
              file=sys.stderr)
        return 2
    return costs.harvest_cli(a)


def _cmd_export(a) -> int:
    from ..telemetry import analysis, cluster, export

    paths = analysis.trace_files(a.target)
    ledger_series = None
    if a.ledger:
        # the multi-run performance-ledger counter tracks (one per
        # series, own pid) — a ledger-only export is valid: the artifact
        # history exists independently of any single run's events files
        from ..telemetry import ledger as ledger_mod
        artifact_paths = ledger_mod.discover(a.ledger)
        if not artifact_paths:
            print(f"trace export: --ledger {a.ledger}: no artifacts "
                  f"found", file=sys.stderr)
            return 1
        try:
            ledger_series = ledger_mod.histories(
                ledger_mod.ingest(artifact_paths)["rows"])
        except ledger_mod.LedgerError as e:
            print(f"trace export: --ledger {e}", file=sys.stderr)
            return 1
    if not paths and not ledger_series:
        print(f"trace export: {a.target}: no events*.jsonl found",
              file=sys.stderr)
        return 1
    # per-rank collective journals beside the trace (a --journal run)
    # render as per-rank collective tracks with seq-aligned flow arrows
    journal_paths = cluster.journal_files(a.target) if paths else []
    n = export.write_chrome_trace(paths, a.out,
                                  journal_paths=journal_paths,
                                  ledger_series=ledger_series)
    if n == 0:
        print(f"trace export: {a.target}: no timeline records",
              file=sys.stderr)
        return 1
    extra = (f" (+ {len(journal_paths)} collective journal(s))"
             if journal_paths else "")
    if ledger_series:
        extra += f" (+ {len(ledger_series)} ledger series)"
    print(f"trace export: wrote {n} event(s) from {len(paths)} file(s)"
          f"{extra} to {a.out} (load in Perfetto or chrome://tracing)")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="analyze / gate / export telemetry JSONL traces "
                    "(see docs/OBSERVABILITY.md)")
    sub = p.add_subparsers(dest="cmd", required=True,
                           metavar="report|export|cost")

    r = sub.add_parser(
        "report", help="per-phase p50/p95/max, epoch trend, straggler "
                       "skew; --baseline gates step-time regressions")
    r.add_argument("target",
                   help="a --telemetry dir (merges every events*.jsonl), "
                        "one trace file, or a saved --json report")
    r.add_argument("--serve", action="store_true",
                   help="the serve-path tail-latency attribution report "
                        "instead of the train phase report: per-stage "
                        "p50/p95/p99 + %% of e2e, batch occupancy and "
                        "padding waste, slowest-request exemplars; with "
                        "--baseline, the stage-share regression gate — "
                        "exit 3 when compute's share of e2e drops past "
                        "--threshold, sub-ms stages exempt "
                        "(docs/OBSERVABILITY.md §Request tracing)")
    r.add_argument("--data", action="store_true",
                   help="the input-attribution report instead of the train "
                        "phase report: per-epoch data_wait share of epoch "
                        "time (how much of training the host spent blocked "
                        "on the input pipeline); with --baseline, the "
                        "data_wait-share regression gate — exit 3 past "
                        "--threshold, sub-ms data_wait exempt "
                        "(docs/DATA.md)")
    r.add_argument("--cluster", action="store_true",
                   help="the cluster-forensics report instead of the "
                        "train phase report: TARGET is a --telemetry dir "
                        "holding per-rank collective journals (train with "
                        "--journal) — cross-rank desync detection (exit 3 "
                        "naming both ranks and the diverging collective), "
                        "per-collective straggler attribution per rank "
                        "pair, and the hang report (open collectives + "
                        "every rank's last journal position) "
                        "(docs/OBSERVABILITY.md §Cluster forensics)")
    r.add_argument("--overhead", action="store_true",
                   help="the dispatch-overhead attribution report instead "
                        "of the train phase report: TARGET is a "
                        "--profile_dispatch trace dir, a saved --json "
                        "report, or a DDP bench artifact with stamped "
                        "overhead decompositions — named host phases "
                        "(python_prestep/dispatch/device_idle/sync_wait), "
                        "coverage of the overhead window (exit 1 below "
                        "90%%), worst phase; with --baseline, the "
                        "phase-share regression gate — exit 3 past "
                        "--threshold, sub-ms phases exempt "
                        "(docs/OBSERVABILITY.md §Dispatch forensics)")
    r.add_argument("--cost", action="store_true",
                   help="the program-forensics report: TARGET is a saved "
                        "`trace cost` report (COST_r0X.json) or a DDP "
                        "bench artifact whose measured rows decompose "
                        "into analytic compute/comm/overhead shares; "
                        "with --baseline, the compile-count / peak-HBM / "
                        "analytic-efficiency regression gate — exit 3 "
                        "(docs/OBSERVABILITY.md §Program forensics)")
    r.add_argument("--batch", type=int, default=None,
                   help="with --cost: per-chip batch of a LEGACY artifact "
                        "whose rows predate the per_chip_batch stamp "
                        "(MULTICHIP_r07 measured at 4; default 128, the "
                        "bench default)")
    r.add_argument("--baseline", metavar="OLD", default=None,
                   help="diff against another run (trace dir/file or saved "
                        "--json report); exit 3 when any phase p50/p95 "
                        "ratio exceeds --threshold")
    r.add_argument("--ledger", metavar="DIR", default=None,
                   help="gate TARGET (an ingestible artifact) against the "
                        "performance-ledger HISTORY under DIR instead of "
                        "one --baseline: the median+MAD band of each "
                        "series' last --window runs (telemetry/ledger.py; "
                        "the report-family flag narrows which series "
                        "gate). Exit 3 names series + offending runs")
    r.add_argument("--window", type=int, default=5,
                   help="with --ledger: history runs the band is computed "
                        "over (default %(default)s)")
    r.add_argument("--threshold", type=float, default=1.5,
                   help="regression gate ratio (default 1.5; the injected-"
                        "2x acceptance trips it with margin)")
    r.add_argument("--json", action="store_true",
                   help="print the machine-readable report instead of the "
                        "table (feed a saved copy back as --baseline)")
    r.set_defaults(run=_cmd_report)

    e = sub.add_parser(
        "export", help="merged trace -> Chrome trace-event JSON "
                       "(Perfetto / chrome://tracing)")
    e.add_argument("target", help="a --telemetry dir or one trace file")
    e.add_argument("-o", "--out", default="trace.chrome.json",
                   help="output path (default ./trace.chrome.json)")
    e.add_argument("--ledger", metavar="DIR", default=None,
                   help="also render the performance-ledger history under "
                        "DIR as one Perfetto counter track per series "
                        "(own pid; runs spaced 1s apart). Works without "
                        "events files — the repo history is a timeline of "
                        "its own")
    e.set_defaults(run=_cmd_export)

    c = sub.add_parser(
        "cost", help="HARVEST program cost/memory records: compile the "
                     "comm x overlap DDP matrix (statics program "
                     "builders) + the serve bucket ladder, extract "
                     "cost_analysis/memory_analysis per program, write a "
                     "COST_r0X.json artifact (read it back with "
                     "`trace report --cost`)")
    c.add_argument("-o", "--out", default=None,
                   help="write the cost report JSON here (stdout table "
                        "always prints)")
    c.add_argument("--telemetry", metavar="DIR", default=None,
                   help="also emit the JSONL trace: one program_cost "
                        "point per record + a final registry snapshot "
                        "(xla.* compile metrics, mem.* watermarks) — the "
                        "check_telemetry --require xla./mem. surface")
    c.add_argument("--model", default="mlp",
                   help="workload family (models/zoo.py; default mlp)")
    c.add_argument("--param_scale", type=int, default=1,
                   help="hidden-width multiplier (16 = the MULTICHIP_r07 "
                        "5.8M-param geometry)")
    c.add_argument("--batch", type=int, default=16,
                   help="PER-DEVICE batch rows of the harvested step "
                        "programs (default 16, the audit geometry)")
    c.add_argument("--n_devices", type=int, default=8,
                   help="mesh size (default 8, the audit geometry); "
                        "without that many real devices the harvest "
                        "degrades to deviceless cost-only records")
    c.add_argument("--form", choices=("step", "run", "both"),
                   default="step",
                   help="which DDP program forms to harvest (default "
                        "step — the measured strategy programs)")
    c.add_argument("--no-serve-ladder", dest="serve_ladder",
                   action="store_false",
                   help="skip the serve engine bucket-ladder records")
    c.add_argument("--serve_max_batch", type=int, default=128,
                   help="serve ladder cap (default 128, the engine "
                        "default: buckets 1..128)")
    c.add_argument("--artifact", default=None,
                   help="a DDP bench artifact (MULTICHIP_r0X.json) whose "
                        "measured rows become the roofline attribution "
                        "section of the report")
    c.add_argument("--per_chip_batch", type=int, default=None,
                   help="the artifact's measured per-chip batch when its "
                        "rows predate the stamp (r07: 4)")
    c.set_defaults(run=_cmd_cost)

    a = p.parse_args(argv)
    if a.cmd == "report":
        if a.threshold <= 0:
            p.error("--threshold must be > 0")
        picked = [f for f in ("serve", "data", "cost", "cluster",
                              "overhead")
                  if getattr(a, f)]
        if len(picked) > 1:
            p.error(f"--{picked[0]} and --{picked[1]} select different "
                    f"reports; pass one")
        if a.cluster and a.baseline:
            p.error("--cluster compares ranks against each other, not "
                    "runs against a baseline; drop --baseline")
        if a.ledger and a.baseline:
            p.error("--ledger gates against the whole history band; "
                    "--baseline is the one-step pairwise mode — pass one")
        if a.ledger and a.cluster:
            p.error("--cluster reads per-rank journals, not ledger "
                    "artifacts; drop --ledger")
        if a.window < 1:
            p.error("--window must be >= 1")
        if a.batch is not None and not a.cost:
            p.error("--batch only applies to the --cost report")
        if a.batch is not None and a.batch < 1:
            p.error("--batch must be >= 1 (the artifact's measured "
                    "per-chip batch)")
    if a.cmd == "cost" and a.per_chip_batch is not None \
            and a.per_chip_batch < 1:
        p.error("--per_chip_batch must be >= 1")
    return a.run(a)


if __name__ == "__main__":
    sys.exit(main())
