"""Unified trainer CLI — the capability of the reference's five entry scripts
behind one config surface (SURVEY.md §0 capability matrix).

  serial (default)      -> ddp_tutorial_cpu.py analog
  --parallel            -> ddp_tutorial_multi_gpu.py / mnist_cpu_mp.py analog:
                           SPMD data parallel over all devices of the mesh
  --netcdf              -> mnist_pnetcdf_cpu[_mp].py analog: NetCDF data path
  --wireup_method ...   -> multi-host rendezvous (reference `distributed` class)

Run: python -m pytorch_ddp_mnist_tpu.cli.train [--parallel] [--n_epochs N] ...
"""

from __future__ import annotations

import os
import sys

import jax
import numpy as np

from ..data import BatchLoader, normalize_images
from ..data.mnist import get_mnist
from ..models import param_count
from ..parallel import ShardedSampler
from ..train import (TrainState, fit, save_checkpoint, load_checkpoint)
from ..train.config import configure

# The rank-gated stash filename _persist_and_reexec falls back to when
# --checkpoint is empty; the end-of-run cleanup matches on it too.
_DEFAULT_STASH = "outage_resume.msgpack"


def _run_geometry(tcfg, dcfg, global_batch: int) -> dict:
    """The config fields whose change would silently RE-INTERPRET a step
    checkpoint — stamped into every manifest and compared at directory
    resume (same values or refuse by name). (epoch, offset) only address
    the right batches under the same global_batch/limit/sampler_rng, and
    the params blob only restores into the right model under the same
    --model/--param_scale (flax from_bytes matches dict KEYS, not shapes,
    so a scale-8 blob would silently load into a scale-1 template)."""
    return {"global_batch": int(global_batch), "limit": int(dcfg["limit"]),
            "sampler_rng": tcfg["sampler_rng"],
            "model": tcfg["model"],
            "param_scale": int(tcfg["param_scale"])}


def _persist_and_reexec(tcfg, stash, remaining: int, process_index: int,
                        why: str) -> None:
    """Persist the stash (per-rank checkpoint + RNG sidecar) and replace
    this process with a fresh CLI invocation resuming at the next global
    epoch. Never returns. Shared by the serial wedged-client path and the
    parallel coordinated resume; callers have already verified the CLI
    context (argv is None, no PDMT_NO_REEXEC)."""
    ckpt = tcfg["checkpoint"] or _DEFAULT_STASH
    # Rank-gated stash files: rank 0 persists to the real checkpoint path;
    # every other rank to a rank-suffixed sibling (multi-host ranks cannot
    # read each other's filesystems, and params are replicated — identical
    # bytes on every rank). The resumed processes re-rendezvous and each
    # loads its own file.
    my_ckpt = ckpt if process_index == 0 else f"{ckpt}.rank{process_index}"
    save_checkpoint(my_ckpt, stash["params"])
    np.savez(my_ckpt + ".rng.npz", key=stash["key"], impl=tcfg["impl"])
    if not tcfg["parallel"]:
        # Serial wedged path: once-only (the marker survives execv). The
        # PARALLEL path must NOT set it — its re-exec'd world carries the
        # decremented --outage_retries budget, which is the loop bound, and
        # a marker would make every remaining retry dead on arrival.
        os.environ["PDMT_NO_REEXEC"] = "1"
    print(f"[outage] {why}; re-exec'ing with --resume {my_ckpt} "
          f"--start_epoch {stash['epoch'] + 1}", file=sys.stderr, flush=True)
    # execv replaces the process without flushing Python's buffers: under
    # nohup/tee (block-buffered stdout — the outage workflow) unflushed
    # epoch lines would vanish here.
    sys.stdout.flush()
    sys.stderr.flush()
    os.execv(sys.executable, [
        sys.executable, "-m", "pytorch_ddp_mnist_tpu.cli.train",
        *sys.argv[1:], "--resume", my_ckpt,
        "--start_epoch", str(stash["epoch"] + 1),
        "--outage_retries", str(remaining)])


def _train_with_outage_retry(run_fit, state, tcfg, stash, trace, argv,
                             process_index: int = 0):
    """Run the fit closure, retraining through backend outages when
    --outage_retries > 0 (the tunneled-TPU failure mode this framework's
    bench machinery already handles at startup — this extends it MID-run).

    On a device/backend RuntimeError escaping the fit, SERIAL runs: wait
    for the backend (hang-bounded probes, parallel/wireup.py), then

    - recovered in-process: rebuild device state from the host stash (last
      completed epoch's params + key) and continue at the next GLOBAL epoch
      — with start_epoch keeping the sampler's reshuffle sequence and the
      key chain intact, the resumed trajectory is bitwise the unbroken one;
    - client WEDGED (a hung init holds xla_bridge's lock — no in-process
      query can ever succeed): persist the stash to the checkpoint plus an
      RNG sidecar and re-exec with --resume/--start_epoch and the remaining
      retry budget (CLI path only, once — the PDMT_NO_REEXEC marker, same
      contract as bench.py);
    - backend stays down past the wait budget (PDMT_BACKEND_WAIT, default
      1 h): SystemExit with the named error.

    PARALLEL runs (VERDICT r4 #5) go straight to the coordinated
    persist + re-exec: every rank independently catches the collective's
    failure, stashes the last completed epoch's replicated state to its
    own rank-gated file, polls backend health OUT of process (bounded by
    the same wait budget; an in-process probe could wedge behind the dead
    client's bridge lock), and re-execs into a fresh CLI invocation —
    fresh processes re-rendezvous through a clean jax.distributed
    initialize, where in-place re-initialization would have to rebuild
    every mesh/step closure against a torn-down client. The resumed world
    resumes at the next global epoch, bitwise the unbroken run
    (tests/test_multiprocess.py pins it at 4 processes).

    With retries == 0 (the default) this is exactly one un-wrapped call —
    interactive errors stay immediate.
    """
    import time

    from ..parallel.wireup import (BackendUnavailableError,
                                   BackendWedgedError,
                                   _subprocess_backend_healthy,
                                   backend_wait_env, looks_like_backend_loss,
                                   wait_for_backend)

    retries = tcfg["outage_retries"]
    start = tcfg["start_epoch"]
    # The program name an allocation failure will be attributed to
    # (telemetry/costs.py OOM forensics): the DDP label matches the cost
    # harvest's step records, the serial label names the kernel.
    if tcfg["parallel"]:
        from ..parallel.collectives import step_cost_label
        # --cached runs the scan program (the harvest's ddp.run.* records),
        # streaming the step program — the label must join the cost table
        program_label = step_cost_label(
            tcfg["ddp_comm"], tcfg["overlap"],
            form="run" if tcfg["cached"] else "step")
    else:
        program_label = f"train.{tcfg['kernel']}"
    from ..telemetry.runtime import label_compiles
    attempt = 0
    while True:
        try:
            # compiles inside the fit attribute to this run's program
            # label (telemetry/costs.py compile_attribution)
            with trace(tcfg["profile"]), label_compiles(program_label):
                return run_fit(state, start)
        except RuntimeError as e:
            # OOM forensics FIRST, unconditionally: an allocation failure
            # is not an outage (no backend-loss signature), so it will
            # re-raise below — but it must die naming the program and the
            # memory budget it blew (no-op for non-OOM errors).
            from ..telemetry.costs import record_oom_forensics
            record_oom_forensics(e, program=program_label)
            if attempt >= retries:
                raise
            # Outage vs program error (ADVICE r4). SERIAL runs retry when
            # the error carries a backend-loss signature, or — for
            # unrecognized messages — when a fresh out-of-process probe
            # confirms the backend is actually down: a deterministic
            # failure (XLA shape/compile error, NaN guard) on a healthy
            # backend would just burn every retry re-hitting the same
            # error. PARALLEL runs triage by SIGNATURE ONLY — no health
            # probe: the retry decision must be as close to identical on
            # every rank as possible, and probe outcomes are
            # timing-dependent mid-outage while the signature is a pure
            # function of the message. A rank-local error (host I/O, a
            # program bug) thus fails fast instead of re-exec'ing one
            # lone rank into a rendezvous no other rank will join; a real
            # backend loss surfaces the gRPC signatures on every rank and
            # the whole world takes the coordinated path together.
            if tcfg["parallel"]:
                if not looks_like_backend_loss(e):
                    raise
            elif not looks_like_backend_loss(e) and \
                    _subprocess_backend_healthy(30.0):
                raise
            attempt += 1
            from ..telemetry import flight
            flight.record("train_outage", attempt=attempt, retries=retries,
                          epoch_stash=stash.get("epoch"),
                          error=str(e)[:500])
            print(f"[outage] training interrupted mid-run: {e}; waiting for "
                  f"the backend (retry {attempt}/{retries})",
                  file=sys.stderr, flush=True)
            if tcfg["parallel"]:
                # All ranks poll health from FRESH interpreters until the
                # backend answers (never an in-process device query: the
                # dead client can hold the bridge lock forever), then
                # re-exec; the fresh processes' initialize() is the
                # re-rendezvous barrier. No PDMT_NO_REEXEC check here: the
                # decremented budget in the re-exec'd argv is the loop
                # bound, and main() validated the CLI context at parse
                # time (argv is None).
                deadline = time.monotonic() + backend_wait_env(3600.0)
                while not _subprocess_backend_healthy(45.0):
                    if time.monotonic() > deadline:
                        flight.dump(reason="parallel train outage: backend "
                                           "never recovered")
                        raise SystemExit(
                            "[outage] backend did not recover within the "
                            "wait budget after a mid-run interruption of "
                            "the parallel run") from e
                    time.sleep(10.0)
                _persist_and_reexec(
                    tcfg, stash, retries - attempt, process_index,
                    "backend answers again; coordinated parallel resume")
            try:
                wait_for_backend(max_wait_s=backend_wait_env(3600.0))
            except BackendWedgedError:
                if argv is not None or os.environ.get("PDMT_NO_REEXEC") == "1":
                    raise
                _persist_and_reexec(
                    tcfg, stash, retries - attempt, process_index,
                    "backend recovered but this process's jax client is "
                    "wedged")
            except BackendUnavailableError as be:
                flight.dump(reason="train outage: backend never recovered")
                raise SystemExit(
                    f"[outage] backend did not recover within the wait "
                    f"budget after a mid-run interruption: {be}") from e
            start = stash["epoch"] + 1
            state = TrainState(
                jax.tree_util.tree_map(jax.device_put, stash["params"]),
                jax.random.wrap_key_data(jax.numpy.asarray(stash["key"]),
                                         impl=tcfg["impl"]))


def main(argv=None) -> int:
    from ..parallel.wireup import _honor_platform_env
    _honor_platform_env()  # JAX_PLATFORMS from the launcher wins (e.g. cpu)
    config = configure(argv)
    tcfg, dcfg = config["trainer"], config["data"]

    # Fault injection (--fault + $PDMT_FAULT, utils/faultpoints.py): parse
    # NOW so a typo'd chaos spec refuses to start instead of silently
    # running fault-free; the real process rank binds after wireup.
    from ..utils import faultpoints
    try:
        faultpoints.install(tcfg["fault"])
    except faultpoints.FaultSpecError as e:
        raise SystemExit(f"--fault: {e}")

    # --telemetry DIR: arm the compile listener BEFORE the first jit (it is
    # pure jax.monitoring plumbing — no backend touch), and open the JSONL
    # trace now for serial runs. PARALLEL runs defer the trace open until
    # after wireup: stamping records with the process index queries the
    # backend, which must not initialize before jax.distributed's
    # rendezvous (same constraint as the probe ordering below).
    from .. import telemetry
    if tcfg["telemetry"]:
        telemetry.install_compile_listener()
        # live HBM/RSS watermark gauges (mem.*): Prometheus scrapes and
        # registry snapshots read the instant; guarded probes — None off-
        # accelerator, same degrade contract as the memory_stats stamp
        telemetry.install_memory_watermarks()
        # Post-mortems land beside the JSONL trace: the flight recorder
        # (wireup probe/retry + serve reject ring) dumps into the telemetry
        # dir on a fatal backend outage or a caller's SIGTERM, so a killed
        # run leaves structured evidence next to its trace.
        os.makedirs(tcfg["telemetry"], exist_ok=True)
        telemetry.flight.set_dump_dir(tcfg["telemetry"])
        if argv is None:  # CLI context: signal dispositions are ours to set
            telemetry.flight.install_sigterm_flush()
        if not tcfg["parallel"]:
            # process_index=0 explicitly: a serial run IS process 0, and
            # resolving it via jax.process_index() here would be the first
            # backend query — ahead of the PDMT_BACKEND_WAIT outage guard
            # below, which must own that first touch.
            telemetry.enable(tcfg["telemetry"], process_index=0)

    # Opt-in bounded backend retry (PDMT_BACKEND_WAIT=<seconds>): a serial
    # training job launched into a transient accelerator outage polls
    # instead of dying at its first device query — same machinery as
    # bench.py's --backend_wait, off by default so interactive errors stay
    # immediate. NOT applied to --parallel runs: probing devices initializes
    # the local backend, which must not happen before
    # jax.distributed.initialize's rendezvous (initialize_runtime below).
    if not tcfg["parallel"]:
        from ..parallel.wireup import (BackendUnavailableError,
                                       backend_wait_env, wait_for_backend)
        wait_s = backend_wait_env(0.0)
        if wait_s > 0:
            try:
                wait_for_backend(max_wait_s=wait_s)
            except BackendUnavailableError as e:
                raise SystemExit(f"accelerator backend unavailable after "
                                 f"PDMT_BACKEND_WAIT={wait_s:.0f}s: {e}")

    if tcfg["kernel"] != "auto":
        # single source of truth for kernel/dtype compatibility
        # (train.scan._check_kernel; every kernel composes with bfloat16)
        from ..train.scan import _check_kernel
        try:
            _check_kernel(tcfg["kernel"], tcfg["dtype"])
        except ValueError as e:
            raise SystemExit(str(e))
    if tcfg["kernel"] in ("pallas_rng", "pallas_epoch") and not tcfg["cached"]:
        raise SystemExit(f"--kernel {tcfg['kernel']} runs inside the epoch "
                         "scan; add --cached")
    if tcfg["kernel"] == "pallas_epoch" and tcfg["parallel"]:
        # stderr (stdout stays machine-parseable epoch lines); printed
        # pre-wireup so a user sees it even if rendezvous then hangs —
        # worth the per-process duplication in multi-process runs.
        print("[experimental] --kernel pallas_epoch --parallel: per-step "
              "DDP mean-gradients via the IN-KERNEL ICI ring allreduce "
              "(weights stay VMEM-resident on every chip). Semantically "
              "pinned by tests at 1 device; the multi-chip ring has not "
              "executed on real hardware yet", file=sys.stderr, flush=True)
    if tcfg["kernel"] == "pallas_epoch":
        from ..ops.pallas_step import EPOCH_KERNEL_MAX_BATCH
        if (tcfg["batch_size"] % 8 != 0
                or tcfg["batch_size"] > EPOCH_KERNEL_MAX_BATCH):
            raise SystemExit(
                f"--kernel pallas_epoch needs a batch divisible by 8 and "
                f"<= {EPOCH_KERNEL_MAX_BATCH} (one VMEM block per step); "
                f"got {tcfg['batch_size']} — use --kernel pallas instead")
    if tcfg["fused"] and not tcfg["cached"]:
        raise SystemExit("--fused fuses the epoch scan; add --cached")
    if tcfg["journal"]:
        # the collective journal's by-name hygiene (the unroll lesson):
        # every configuration that would silently record nothing refuses
        # to start instead
        if not tcfg["telemetry"]:
            raise SystemExit("--journal writes journal*.jsonl beside the "
                             "JSONL trace; add --telemetry DIR")
        if not tcfg["parallel"]:
            raise SystemExit("--journal records the DDP step's collectives "
                             "over the 'dp' mesh; a serial run issues none "
                             "— add --parallel")
        if tcfg["cached"]:
            raise SystemExit("--journal needs the streaming path: --cached "
                             "runs steps inside a jitted scan, so the host "
                             "observes only chunk boundaries and the "
                             "per-collective journal cannot stamp them — "
                             "drop --cached (and --fused)")
        if tcfg["kernel"] in ("pallas", "pallas_rng", "pallas_epoch"):
            raise SystemExit(f"--journal needs the XLA step program (it "
                             f"declares its collective schedule); --kernel "
                             f"{tcfg['kernel']} owns its own comms")
    if tcfg.get("profile_dispatch"):
        # same by-name hygiene: a profiler whose records nobody persists
        # or whose trainer has no step boundary refuses to start
        if not tcfg["telemetry"]:
            raise SystemExit("--profile_dispatch flushes dispatch_phase/"
                             "dispatch_window points into the JSONL trace; "
                             "add --telemetry DIR")
        if tcfg["fused"]:
            raise SystemExit("--profile_dispatch decomposes the per-step/"
                             "per-chunk host boundary; --fused runs all "
                             "epochs as ONE device program with no such "
                             "boundary — drop --fused")
    if tcfg["ddp_comm"] != "pmean":
        # the comm strategies are per-step XLA collectives over the 'dp'
        # mesh — meaningless serially, and the whole-epoch kernel owns its
        # allreduce in-kernel (--kernel pallas_epoch's ICI ring)
        if not tcfg["parallel"]:
            raise SystemExit(
                f"--ddp_comm {tcfg['ddp_comm']} selects the DDP gradient "
                f"collective; it needs --parallel")
        if tcfg["kernel"] == "pallas_epoch":
            raise SystemExit(
                f"--ddp_comm {tcfg['ddp_comm']} selects the per-step XLA "
                f"gradient collective; --kernel pallas_epoch performs its "
                f"allreduce IN-kernel (the ICI ring) and never reads it")
    if tcfg["bf16_rounding"] != "nearest" and tcfg["ddp_comm"] != "bf16":
        raise SystemExit(
            f"--bf16_rounding {tcfg['bf16_rounding']} rounds the bf16 "
            f"strategy's wire cast; --ddp_comm {tcfg['ddp_comm']} never "
            f"casts — use --ddp_comm bf16")
    # int8 / overlap / model-zoo knob hygiene: every knob that some other
    # configuration would silently ignore is rejected by name instead
    # (the unroll lesson) — single sources of truth in
    # parallel/collectives.py and models/zoo.py.
    from ..models.zoo import is_default_model, validate_model
    from ..parallel.collectives import validate_int8_options
    try:
        validate_model(tcfg["model"], tcfg["param_scale"])
        validate_int8_options(tcfg["quant_block"], tcfg["error_feedback"],
                              tcfg["ddp_comm"])
    except ValueError as e:
        raise SystemExit(str(e))
    nondefault_model = not is_default_model(tcfg["model"],
                                            tcfg["param_scale"])
    if tcfg["overlap"] and not tcfg["parallel"]:
        raise SystemExit(
            "--overlap bucket-pipelines the DDP gradient collectives; it "
            "needs --parallel")
    # The new strategies and the model zoo run on the XLA kernels only:
    # the Pallas kernels hard-code the reference MLP's VMEM shapes and the
    # fused-kernel DP step does not thread error-feedback state. An
    # explicit conflicting --kernel is rejected by name; 'auto' (which
    # would promote to Pallas on TPU) resolves to xla for these configs.
    _xla_only = []
    if tcfg["overlap"]:
        _xla_only.append("--overlap (bucket-pipelined XLA collectives)")
    if tcfg["ddp_comm"] == "int8":
        _xla_only.append("--ddp_comm int8 (error-feedback state threading)")
    if nondefault_model:
        _xla_only.append(f"--model {tcfg['model']} --param_scale "
                         f"{tcfg['param_scale']} (non-reference shapes)")
    if _xla_only:
        if tcfg["kernel"] in ("pallas", "pallas_rng", "pallas_epoch"):
            raise SystemExit(
                f"--kernel {tcfg['kernel']} hard-codes the reference MLP / "
                f"owns its own comms; {'; '.join(_xla_only)} need(s) "
                f"--kernel xla")
        if tcfg["kernel"] == "auto":
            tcfg["kernel"] = "xla"
    if nondefault_model and tcfg["dropout_rng"] == "torch":
        raise SystemExit(
            "--dropout_rng torch streams masks sized for the reference "
            "MLP's hidden layer; --model/--param_scale change that "
            "geometry — use the default jax dropout stream")
    # Input-pipeline knob hygiene (pipeline/, docs/DATA.md): reject every
    # combination some path would silently ignore, by name.
    if tcfg["input_workers"] < 0:
        raise SystemExit("--input_workers must be >= 0")
    if tcfg["prefetch_depth"] < 1:
        raise SystemExit("--prefetch_depth must be >= 1")
    if tcfg["input_workers"] and tcfg["cached"]:
        raise SystemExit(
            "--input_workers feeds the streaming loader through the input "
            "pipeline; --cached holds the dataset in HBM with no loader to "
            "feed — drop --cached (the streaming loop) to use it")
    if tcfg["input_workers"] and tcfg["num_workers"]:
        raise SystemExit(
            "--input_workers (the staged pipeline) supersedes the NetCDF "
            "loader's --num_workers readahead; pass one of the two")
    if tcfg["prefetch_depth"] != 1 and tcfg["fused"]:
        raise SystemExit(
            "--prefetch_depth pipelines per-chunk/per-batch device "
            "transfers; --fused places ONE index array for the whole run — "
            "there is nothing to prefetch")
    if not 0 <= tcfg["start_epoch"] <= tcfg["n_epochs"]:
        raise SystemExit(f"--start_epoch {tcfg['start_epoch']} outside "
                         f"[0, {tcfg['n_epochs']}] (n_epochs is the TOTAL "
                         f"run length; start_epoch resumes inside it)")
    if tcfg["outage_retries"] < 0:
        raise SystemExit("--outage_retries must be >= 0")
    if tcfg["ckpt_every_steps"] < 0:
        raise SystemExit("--ckpt_every_steps must be >= 0")
    if tcfg["ckpt_keep"] < 1:
        raise SystemExit("--ckpt_keep must be >= 1")
    if tcfg["metrics_port"] is not None and tcfg["metrics_port"] < 0:
        raise SystemExit("--metrics_port must be >= 0 (0 = ephemeral)")
    if tcfg["health"] != "off":
        if tcfg["fused"]:
            raise SystemExit(
                "--health observes at the chunk/epoch boundaries the host "
                "controls; --fused runs all epochs as ONE device program "
                "with no live host — use plain --cached")
        if tcfg["health"] == "checkpoint-and-warn" and not tcfg["checkpoint"]:
            raise SystemExit(
                "--health checkpoint-and-warn saves the last known-good "
                "state under <--checkpoint>.steps/; pass a non-empty "
                "--checkpoint to derive the directory from")
    if tcfg["ckpt_every_steps"]:
        if tcfg["fused"]:
            raise SystemExit(
                "--ckpt_every_steps saves at chunk boundaries the host "
                "controls; --fused runs all epochs as ONE device program "
                "with no mid-run host control — use plain --cached")
        if tcfg["kernel"] == "pallas_epoch":
            raise SystemExit(
                "--ckpt_every_steps chunks the epoch scan; --kernel "
                "pallas_epoch splits its dropout key once per EPOCH and "
                "chunking would fork the RNG chain — use --kernel "
                "xla/pallas")
        if not tcfg["checkpoint"]:
            raise SystemExit(
                "--ckpt_every_steps writes step checkpoints under "
                "<--checkpoint>.steps/; pass a non-empty --checkpoint to "
                "derive the directory from")
    # --outage_retries composes with --parallel since round 5: every rank
    # persists its own stash and the world re-execs into a fresh
    # rendezvous (_train_with_outage_retry's parallel branch). That resume
    # REPLACES the process, so it needs the CLI context — fail fast at
    # parse time for programmatic callers instead of logging a retry line
    # and re-raising at the first outage.
    if tcfg["outage_retries"] and tcfg["parallel"] and argv is not None:
        raise SystemExit(
            "--outage_retries with --parallel resumes by re-exec'ing the "
            "process and is only available from the CLI (argv=None); "
            "programmatic callers should relaunch with --resume instead")
    if tcfg["outage_retries"] and tcfg["fused"]:
        raise SystemExit(
            "--outage_retries needs per-epoch state to resume from; "
            "--fused runs all epochs as one device program with no "
            "mid-run state (use plain --cached)")
    # --elastic knob hygiene (the unroll lesson): every configuration under
    # which the reaction loop could not actually rescue/re-wire is rejected
    # by name at parse time — not discovered at the first peer loss.
    if tcfg["reshape"] is not None and not tcfg["elastic"]:
        raise SystemExit(
            "--reshape re-maps checkpoint geometry across an elastic "
            "membership change; it needs --elastic")
    if tcfg["elastic"]:
        tcfg["reshape"] = tcfg["reshape"] or "global_batch"
        if not tcfg["parallel"]:
            raise SystemExit(
                "--elastic reacts to the loss of a PEER rank; a serial run "
                "has no peers — add --parallel")
        if not tcfg["telemetry"]:
            raise SystemExit(
                "--elastic coordinates the surviving membership through "
                "beacon files (and leaves its forensics) in the telemetry "
                "directory; add --telemetry DIR")
        if not (tcfg["checkpoint"] and tcfg["ckpt_every_steps"]):
            raise SystemExit(
                "--elastic rescues into (and resumes out of) the "
                "step-checkpoint directory; pass a non-empty --checkpoint "
                "and --ckpt_every_steps N")
        if tcfg["cached"]:
            raise SystemExit(
                "--elastic keeps a per-step host-side rescue stash on "
                "every rank; --cached/--fused run steps inside a jitted "
                "scan with no per-step host control — drop --cached")
        if argv is not None:
            raise SystemExit(
                "--elastic re-wires the surviving world by re-exec'ing the "
                "process and is only available from the CLI (argv=None); "
                "programmatic callers should relaunch with --resume and "
                "--reshape instead")
    if tcfg["dropout_rng"] == "torch":
        # The torch mask stream is drawn on the HOST per step (exactly like
        # torch) — that shape fits only the serial streaming loop. The
        # cached/fused epoch programs draw masks in-device, and DP replicas
        # need per-rank streams the single global torch generator does not
        # model; each combination is rejected by name, not degraded.
        if tcfg["parallel"]:
            raise SystemExit(
                "--dropout_rng torch is serial-only: DDP replicas draw "
                "per-rank dropout streams, and the reference's single "
                "global torch generator has no per-rank split to mirror")
        if tcfg["cached"]:
            raise SystemExit(
                "--dropout_rng torch streams host-drawn masks per step; "
                "the --cached/--fused epoch programs draw masks in-device "
                "— drop --cached (the streaming loop) to use it")
        if tcfg["kernel"] not in ("auto", "xla"):
            raise SystemExit(
                f"--dropout_rng torch uses the XLA step with streamed "
                f"masks; --kernel {tcfg['kernel']} draws its own masks "
                f"in-kernel")
        if tcfg["outage_retries"]:
            # --resume/--start_epoch compose: the mask position is a pure
            # function of completed steps, so a COLD resume fast-forwards
            # the stream (make_torch_dropout_train_step skip_steps). The
            # in-process retry cannot: its live generator has already
            # advanced through the dead epoch's partial draws, and that
            # position is host state the stash does not capture — reject
            # by name rather than silently train on out-of-position masks.
            raise SystemExit(
                "--dropout_rng torch does not compose with "
                "--outage_retries: the in-process retry would continue "
                "the torch mask stream mid-epoch instead of at the resume "
                "boundary; use --resume/--start_epoch (which re-seat the "
                "stream exactly) or the default jax dropout stream")
        if (tcfg["resume"] and not tcfg["start_epoch"]
                and not os.path.isdir(tcfg["resume"])):
            # the fast-forward is driven by --start_epoch; a resume
            # without it would train mid-run weights on masks from stream
            # position 0 — silently off the bitwise trajectory this flag
            # exists to guarantee. A DIRECTORY resume is exempt: the step
            # checkpoint manifest carries the exact position and the
            # stream fast-forwards from it below.
            raise SystemExit(
                "--dropout_rng torch with --resume needs --start_epoch "
                "(it positions the mask stream at the resume boundary; "
                "without it the stream would restart at epoch 0)")
        tcfg["kernel"] = "xla"

    # .pt/.pth checkpoint paths need torch — fail BEFORE training, not after
    # a completed run's first save (which would lose the trained params).
    from ..train.checkpoint import is_torch_path
    if any(p and is_torch_path(p)
           for p in (tcfg["resume"], tcfg["checkpoint"])):
        try:
            import torch  # noqa: F401
        except ImportError:
            raise SystemExit(
                "a .pt/.pth checkpoint path requires torch (not installed); "
                "use a .msgpack path for the torch-free format")

    def _pallas_interpret() -> bool:
        # The kernel needs Mosaic (TPU); on CPU backends fall back to the
        # Pallas interpreter so the same CLI runs everywhere. Must only be
        # called AFTER wireup (see on_tpu_backend).
        from ..parallel.wireup import on_tpu_backend
        return not on_tpu_backend()

    def _resolve_kernel() -> bool:
        # '--kernel auto' -> the bench.py policy (pallas on TPU+f32). Same
        # post-wireup constraint as _pallas_interpret; both branches below
        # call this exactly once, before any kernel choice is consumed.
        if tcfg["kernel"] == "auto":
            from ..train.scan import resolve_kernel
            tcfg["kernel"] = resolve_kernel(tcfg["dtype"],
                                            not _pallas_interpret())
        if (tcfg["kernel"] in ("pallas_rng", "pallas_epoch")
                and _pallas_interpret()):
            raise SystemExit(f"--kernel {tcfg['kernel']} uses the TPU core "
                             "PRNG; it needs a real TPU backend")
        return tcfg["kernel"] == "pallas"

    process_index, num_processes = 0, 1
    train_step = None
    put = None
    mesh = None
    runtime = None
    journal = None
    if tcfg["parallel"]:
        from ..parallel.wireup import initialize_runtime
        from ..parallel.ddp import (make_dp_train_step, dp_mesh,
                                    global_batch_from_local, replicate_state)
        runtime = initialize_runtime(tcfg["wireup_method"])
        process_index, num_processes = jax.process_index(), jax.process_count()
        faultpoints.set_rank(process_index)  # rank-gated specs bind here
        telemetry.flight.set_rank(process_index)  # flight entries likewise
        if tcfg["telemetry"]:  # post-rendezvous: the real rank is known now
            telemetry.enable(tcfg["telemetry"], process_index=process_index)
        use_pallas = _resolve_kernel()
        if tcfg["journal"] and use_pallas:
            raise SystemExit("--journal needs the XLA step program; "
                             "--kernel auto resolved to pallas here — pass "
                             "--kernel xla to journal this run")
        mesh = dp_mesh()  # global: all devices of all processes
        if not tcfg["cached"]:  # the cached path builds its own step fns
            if use_pallas:
                from ..ops.pallas_step import make_pallas_dp_train_step
                train_step = make_pallas_dp_train_step(
                    mesh, tcfg["lr"], interpret=_pallas_interpret(),
                    dtype=tcfg["dtype"], comm=tcfg["ddp_comm"],
                    bf16_rounding=tcfg["bf16_rounding"])
            else:
                train_step = make_dp_train_step(
                    mesh, tcfg["lr"], dtype=tcfg["dtype"],
                    comm=tcfg["ddp_comm"],
                    bf16_rounding=tcfg["bf16_rounding"],
                    overlap=tcfg["overlap"],
                    quant_block=tcfg["quant_block"],
                    error_feedback=tcfg["error_feedback"],
                    model=tcfg["model"], param_scale=tcfg["param_scale"],
                    # fold the watchdog's grad-norm/finite-check aux into
                    # the step program (telemetry/health.py) — rides the
                    # existing per-epoch loss fetch, zero extra syncs
                    health=tcfg["health"] != "off")
        put = lambda b: global_batch_from_local(mesh, b)  # noqa: E731
        num_shards = mesh.devices.size  # data sharding is per-device
        local_shards = len(jax.local_devices())
        if tcfg["journal"]:
            # the per-rank collective journal + hang watchdog
            # (telemetry/cluster.py; docs/OBSERVABILITY.md §Cluster
            # forensics). The startup barrier right after enabling puts
            # seq 0 on every rank's journal at the same collective — the
            # alignment anchor every cross-rank comparison rides — and is
            # the injectable `collective_timeout` faultpoint: an injected
            # (or real) timeout leaves the barrier's enter open, and the
            # except below turns it into a named hang report instead of a
            # raw traceback (the journal and flight ring ARE the report).
            journal = telemetry.cluster.enable_journal(
                tcfg["telemetry"], rank=process_index,
                world=num_processes)
            from ..parallel.wireup import looks_like_backend_loss
            try:
                runtime.barrier()
            except RuntimeError as e:
                if not looks_like_backend_loss(e):
                    raise
                entry = journal.open_entry() or {"seq": 0,
                                                 "kind": "barrier"}
                telemetry.cluster.report_hang(journal, entry)
                telemetry.cluster.disable_journal(clean=False)
                raise SystemExit(
                    f"[cluster] collective timeout in the startup barrier "
                    f"(seq {entry.get('seq')}): {e} — hang report in the "
                    f"flight dump under {tcfg['telemetry']}; read it with "
                    f"`trace report --cluster {tcfg['telemetry']}`")
    else:
        use_pallas = _resolve_kernel()
        if use_pallas and not tcfg["cached"]:
            from ..ops.pallas_step import make_pallas_train_step
            train_step = make_pallas_train_step(
                tcfg["lr"], interpret=_pallas_interpret(),
                dtype=tcfg["dtype"])
        # (--dropout_rng torch builds its step AFTER the loader exists —
        # the resume fast-forward needs the epoch's step count)
        num_shards = local_shards = 1

    # Elastic geometry pre-pass (--elastic --reshape, elastic/reshape.py):
    # under `global_batch` mode the per-device micro-batch is DERIVED from
    # the manifest (manifest global_batch / surviving devices), and the
    # data plane below sizes its loader from it — so the manifest meta is
    # peeked (no payload touch) BEFORE global_batch/local_batch bind. The
    # full restore further down still verifies payload intactness.
    reshape_plan = None
    if tcfg["elastic"] and tcfg["resume"] and os.path.isdir(tcfg["resume"]):
        from ..elastic import ReshapeError, plan_reshape
        from ..train.ckpt_manager import peek_latest_meta
        peek = peek_latest_meta(tcfg["resume"])
        if peek and "global_batch" in peek.get("meta", {}):
            old_gb = int(peek["meta"]["global_batch"])
            old_devices = int(peek["meta"].get("devices") or num_shards)
            try:
                reshape_plan = plan_reshape(
                    old_gb, old_devices, num_shards, mode=tcfg["reshape"],
                    per_device_batch=tcfg["batch_size"])
            except ReshapeError as e:
                raise SystemExit(f"--reshape: {e}")
            if tcfg["reshape"] == "global_batch":
                tcfg["batch_size"] = reshape_plan.per_device_batch

    if tcfg["elastic"]:
        # startup stamps: the generation/world gauges scrapes and registry
        # snapshots read, the run-start flight marker, and beacon hygiene —
        # rank 0 sweeps every PAST generation's beacons so a later shrink
        # round starts clean (the CURRENT round's set went quiet before any
        # survivor re-exec'd; stragglers past the settle window were
        # already counted dead).
        from ..elastic import clear_beacons, world_generation
        _gen = world_generation()
        telemetry.get_registry().gauge("elastic.generation").set(_gen)
        telemetry.get_registry().gauge("elastic.world").set(num_processes)
        telemetry.flight.record("elastic_run_start", generation=_gen,
                                world=num_processes, rank=process_index,
                                reshape=tcfg["reshape"])
        if process_index == 0:
            for g in range(_gen + 1):
                clear_beacons(tcfg["telemetry"], g)

    global_batch = tcfg["batch_size"] * num_shards
    local_batch = tcfg["batch_size"] * local_shards

    # Data plane: every process loads ONLY the rows for its own devices
    # (PnetCDF independent-read analog); the sampler shards at process
    # granularity and global_batch_from_local stitches the per-process
    # shards into the global dp-sharded array. Single process degrades to
    # the whole batch.
    if dcfg["netcdf"]:
        # NetCDF path (mnist_pnetcdf_cpu[_mp].py analog): train batches are
        # sharded row-gathers straight from the .nc file; the test split is
        # read whole per process, like the serial variant's collective read
        # (mnist_pnetcdf_cpu.py:47).
        from ..data.loader import NetCDFShardLoader
        from ..data.netcdf import read_mnist_netcdf
        train_nc = os.path.join(dcfg["path"], "mnist_train_images.nc")
        test_nc = os.path.join(dcfg["path"], "mnist_test_images.nc")
        for p in (train_nc, test_nc):
            if not os.path.exists(p):
                raise SystemExit(
                    f"--netcdf: {p} not found; produce it with "
                    "`python -m pytorch_ddp_mnist_tpu.data.convert`")
        test_images, test_labels = read_mnist_netcdf(test_nc)
        x_test = normalize_images(test_images)
        test_labels = test_labels.astype(np.int32)
        loader = NetCDFShardLoader(train_nc, batch_size=local_batch,
                                   num_workers=tcfg["num_workers"])
        n_train = loader.num_samples  # header parse + label cache; sampler below
        if dcfg["limit"] and dcfg["limit"] > 0:
            n_train = min(n_train, dcfg["limit"])
        loader.sampler = ShardedSampler(n_train, num_replicas=num_processes,
                                        rank=process_index, shuffle=True,
                                        seed=42,
                                        permutation=tcfg["sampler_rng"])
    else:
        # Multi-process: rank 0 downloads (when asked) BEFORE anyone probes
        # the path, then a barrier releases the other processes to read the
        # same files — otherwise non-zero ranks would race the fetch and
        # silently land on the synthetic fallback while rank 0 trains on
        # real MNIST. Single-process: get_mnist handles the probe order.
        if dcfg["download"] and num_processes > 1:
            if process_index == 0:
                from ..data.download import download_mnist
                try:
                    download_mnist(dcfg["path"])
                except Exception as e:  # noqa: BLE001 — rank 0 MUST reach
                    # the barrier below or every other rank hangs in it;
                    # any failure (mirrors, checksums, unwritable --path)
                    # degrades to the synthetic fallback on all ranks.
                    print(f"[data] MNIST download failed ({e}); synthetic "
                          f"fallback will be used")
            runtime.barrier()
        # Every rank passes the real flag: a successful rank-0 fetch
        # short-circuits on checksum (no refetch); a failed one yields an
        # accurate per-rank message instead of a contradictory hint.
        train = get_mnist(dcfg["path"], train=True, download=dcfg["download"])
        test = get_mnist(dcfg["path"], train=False, download=dcfg["download"])
        if dcfg["limit"] and dcfg["limit"] > 0:
            train.images = train.images[:dcfg["limit"]]
            train.labels = train.labels[:dcfg["limit"]]
        x_test = normalize_images(test.images)
        test_labels = test.labels.astype(np.int32)
        if not tcfg["cached"]:
            # The streaming loop's loader; --cached instead hands raw uint8
            # images to fit_cached below (no full-dataset host normalize).
            sampler = ShardedSampler(len(train), num_replicas=num_processes,
                                     rank=process_index, shuffle=True,
                                     seed=42,
                                     permutation=tcfg["sampler_rng"])
            loader = BatchLoader(normalize_images(train.images), train.labels,
                                 sampler, batch_size=local_batch)

    # Params init always uses threefry (bit-stable across --impl: the same
    # seed gives the same initial weights); --impl only selects the engine
    # of the TRAIN key, i.e. the dropout stream. The model spec
    # (models/zoo.py) resolves --model/--param_scale; the default is
    # literally init_mlp/mlp_apply, bit-for-bit.
    from ..models import resolve_model
    model_spec = resolve_model(tcfg["model"], tcfg["param_scale"])
    state = TrainState(model_spec.init(jax.random.key(tcfg["seed"])),
                       jax.random.key(tcfg["seed"] + 1, impl=tcfg["impl"]))
    # Sidecar lifetime (ADVICE r4): the (checkpoint, .rng.npz) pair must
    # survive until the resumed run actually OVERWRITES that checkpoint —
    # deleting at load time would let a resume that dies before its first
    # save strand the next manual --resume on the --seed key chain. The
    # pair is consumed by _consume_sidecar below, at the first save to the
    # same path; a sidecar paired with a checkpoint this run never writes
    # to stays on disk, still correctly paired.
    sidecar_box = {"sidecar": None, "ckpt": None}

    def _consume_sidecar(saved_path: str) -> None:
        if (sidecar_box["sidecar"]
                and os.path.abspath(saved_path)
                == os.path.abspath(sidecar_box["ckpt"])):
            try:
                os.remove(sidecar_box["sidecar"])
            except FileNotFoundError:
                pass
            sidecar_box["sidecar"] = None

    start_offset = 0           # mid-epoch resume position (directory resume)
    start_step = 0             # global step at the resume point (watchdog seed)
    if tcfg["resume"] and os.path.isdir(tcfg["resume"]):
        # Step-granular resume: --resume points at a ckpt_manager directory
        # (the <--checkpoint>.steps/ that --ckpt_every_steps writes). The
        # newest INTACT checkpoint supplies params, the RNG key chain, and
        # the exact sampler position — no --start_epoch needed (and a
        # conflicting one is refused rather than silently ignored).
        from ..train.checkpoint import CheckpointError
        from ..train.ckpt_manager import CheckpointManager
        if tcfg["start_epoch"]:
            raise SystemExit(
                "--start_epoch conflicts with a step-checkpoint directory "
                "--resume: the manifest carries the exact resume position")
        try:
            restored = CheckpointManager(
                tcfg["resume"], keep=tcfg["ckpt_keep"]).restore_latest(
                    state.params)
        except CheckpointError as e:
            raise SystemExit(f"--resume: {e}")
        if restored.epoch > tcfg["n_epochs"]:
            raise SystemExit(
                f"--resume: checkpoint at epoch {restored.epoch} is past "
                f"--n_epochs {tcfg['n_epochs']} (n_epochs is the TOTAL run "
                f"length)")
        # Run-geometry guard: (epoch, offset) only address the right
        # batches under the SAME geometry the manifest was stamped with —
        # a different global batch / dataset limit / permutation source
        # would silently re-interpret the position and walk off the
        # bitwise trajectory. Refuse by name instead.
        geometry = _run_geometry(tcfg, dcfg, global_batch)
        from ..train.ckpt_manager import geometry_mismatch_message
        manifest_geo = {k: v for k, v in restored.meta.items()
                        if k in geometry}
        if tcfg["elastic"] and reshape_plan is not None:
            # global_batch is the ONE stamp an elastic reshape re-maps
            # (the plan below); the rest — limit/sampler_rng/model/
            # param_scale — stay hard refusals (reshape re-splits a world,
            # it does not reinterpret a dataset or a model)
            manifest_geo.pop("global_batch", None)
        refusal = geometry_mismatch_message(manifest_geo, geometry)
        if refusal:
            raise SystemExit("--resume: " + refusal)
        absent = sorted(k for k in geometry if k not in restored.meta)
        if absent:
            # a manifest written through the raw manager API (no CLI
            # stamp): the guard cannot verify these — say so rather than
            # implying it did
            print(f"[ckpt] warning: manifest carries no run-geometry "
                  f"stamp for {absent}; cannot verify this run matches "
                  f"the checkpoint's geometry", file=sys.stderr, flush=True)
        if restored.offset and (tcfg["fused"]
                                or tcfg["kernel"] == "pallas_epoch"):
            # same conflicts --ckpt_every_steps rejects above, caught at
            # the CLI boundary instead of as fit_cached's ValueError after
            # data setup
            raise SystemExit(
                f"--resume: checkpoint is MID-epoch (offset "
                f"{restored.offset}) and needs step-granular replay; "
                + ("--fused runs all epochs as ONE device program"
                   if tcfg["fused"] else
                   "--kernel pallas_epoch splits its dropout key once per "
                   "EPOCH")
                + " — resume with plain --cached / --kernel xla|pallas")
        carries_resid = (tcfg["ddp_comm"] == "int8"
                         and tcfg["error_feedback"])
        if restored.resid is not None and not carries_resid:
            print("[ckpt] note: checkpoint carries an int8 error-feedback "
                  "residual this run's comm strategy never reads "
                  f"(--ddp_comm {tcfg['ddp_comm']}); ignoring it",
                  file=sys.stderr, flush=True)
        resume_resid = restored.resid if carries_resid else None
        resume_offset = restored.offset
        if tcfg["elastic"] and reshape_plan is not None:
            # The deliberate geometry re-mapping (elastic/reshape.py,
            # semantics pinned by tests/test_elastic.py): offset under the
            # new global batch, residual folded/grown/dropped per mode.
            from ..elastic import (ReshapeError, plan_reshape,
                                   remap_offset, remap_residual)
            if (resume_resid is not None
                    and int(np.asarray(resume_resid).shape[0])
                    != reshape_plan.old_devices):
                # a pre-elastic manifest carries no "devices" stamp and the
                # pre-pass guessed; the residual's row count is the actual
                # old device count — re-plan against it
                try:
                    reshape_plan = plan_reshape(
                        reshape_plan.old_global_batch,
                        int(np.asarray(resume_resid).shape[0]), num_shards,
                        mode=tcfg["reshape"],
                        per_device_batch=tcfg["batch_size"])
                except ReshapeError as e:
                    raise SystemExit(f"--reshape: {e}")
            if reshape_plan.changed:
                try:
                    resume_offset = remap_offset(restored.offset,
                                                 reshape_plan)
                    resume_resid, resid_disp = remap_residual(resume_resid,
                                                              reshape_plan)
                except ReshapeError as e:
                    raise SystemExit(f"--reshape: {e}")
                telemetry.flight.record(
                    "elastic_reshape", mode=reshape_plan.mode,
                    old_global_batch=reshape_plan.old_global_batch,
                    new_global_batch=reshape_plan.new_global_batch,
                    old_devices=reshape_plan.old_devices,
                    new_devices=reshape_plan.new_devices,
                    offset_in=restored.offset, offset_out=resume_offset,
                    resid=resid_disp)
                telemetry.get_registry().counter("elastic.reshapes").inc()
                print(f"[elastic] reshaped checkpoint geometry "
                      f"({reshape_plan.mode}): global_batch "
                      f"{reshape_plan.old_global_batch} -> "
                      f"{reshape_plan.new_global_batch}, devices "
                      f"{reshape_plan.old_devices} -> "
                      f"{reshape_plan.new_devices}, offset "
                      f"{restored.offset} -> {resume_offset}, residual "
                      f"{resid_disp}", file=sys.stderr, flush=True)
        if carries_resid and resume_resid is not None and mesh is not None:
            # Residual-geometry guard: the error-feedback state is
            # per-DEVICE (one row per mesh device), so _run_geometry's
            # batch/model stamp cannot catch a device-count change — an
            # 8-device residual has no meaning on a 4-device mesh. Refuse
            # by name here like every other geometry mismatch, instead of
            # surfacing place_comm_state's ValueError mid-fit. (An elastic
            # resume re-mapped the rows above and sails through.)
            resid_rows = int(np.asarray(resume_resid).shape[0])
            if resid_rows != int(mesh.devices.size):
                raise SystemExit(
                    f"--resume: checkpoint's int8 error-feedback residual "
                    f"was saved on {resid_rows} device(s); this run has "
                    f"{int(mesh.devices.size)} — per-device residuals "
                    f"cannot be re-sharded across a different mesh size "
                    f"(resume on {resid_rows} device(s), re-map them with "
                    f"--elastic --reshape global_batch|per_rank, or "
                    f"restart the run fresh and lose one step's "
                    f"quantization error)")
        state = TrainState(restored.params, jax.random.wrap_key_data(
            jax.numpy.asarray(restored.key_data), impl=restored.impl),
            resid=resume_resid)
        tcfg["start_epoch"] = restored.epoch
        start_offset = resume_offset
        start_step = restored.step
        # the manifest's PRNG engine is authoritative for the restored key
        # chain; everything downstream (stash keys, sidecars, new step
        # checkpoints) describes THAT key, so the config follows it
        tcfg["impl"] = restored.impl
        print(f"[ckpt] resuming from {restored.path}: step {restored.step} "
              f"(epoch {restored.epoch}, offset {restored.offset})",
              file=sys.stderr, flush=True)
    elif tcfg["resume"]:
        state = TrainState(load_checkpoint(tcfg["resume"], state.params),
                           state.key)
        # RNG sidecar (written by the outage-resume re-exec): restores the
        # epoch-k key so the resumed dropout stream continues the unbroken
        # run's chain bitwise, not restarting from --seed.
        rng_sidecar = tcfg["resume"] + ".rng.npz"
        if os.path.exists(rng_sidecar):
            z = np.load(rng_sidecar)
            state = TrainState(state.params, jax.random.wrap_key_data(
                jax.numpy.asarray(z["key"]), impl=str(z["impl"])))
            sidecar_box["sidecar"] = rng_sidecar
            sidecar_box["ckpt"] = tcfg["resume"]
    if mesh is not None:
        # the error-feedback residual stays a HOST array here: it is
        # device-VARYING state (sharded over 'dp', not replicated) and the
        # trainers place it themselves via collectives.place_comm_state
        state = TrainState(replicate_state(mesh, state.params),
                           replicate_state(mesh, state.key),
                           resid=state.resid)

    # --health: the live training-health watchdog (telemetry/health.py).
    # Detectors run on every rank (each rank's health events land in ITS
    # trace file, proc-stamped — the cross-process story); the
    # checkpoint-and-warn RESCUE hook is rank-0-gated like every other
    # checkpoint write, saving the last known-good state through the same
    # step-checkpoint manager (atomic, CRC-stamped, geometry-stamped) so
    # a NaN'd run always leaves an intact pre-poison resume point.
    watchdog = None
    if tcfg["health"] != "off":
        from ..telemetry.health import HealthConfig, Watchdog
        on_fatal = None
        if tcfg["health"] == "checkpoint-and-warn" and process_index == 0:
            from ..train.ckpt_manager import CheckpointManager
            rescue_mgr = CheckpointManager(tcfg["checkpoint"] + ".steps",
                                           keep=tcfg["ckpt_keep"])

            def on_fatal(stash):
                # pin=True: the rescue must survive keep-last-N rotation —
                # the run keeps training (warn semantics) and its routine
                # saves would otherwise rotate the one good state away
                path = rescue_mgr.save(
                    stash["params"], stash["key"], tcfg["impl"],
                    step=stash["step"], epoch=stash["epoch"],
                    offset=stash["offset"],
                    meta=_run_geometry(tcfg, dcfg, global_batch), pin=True,
                    # the int8 error-feedback residual the watchdog
                    # stashed alongside params/key (None off-int8 or in
                    # a multi-host world — see Watchdog._stash)
                    resid=stash.get("resid"))
                print(f"[health] rescue checkpoint committed: {path}",
                      file=sys.stderr, flush=True)
        watchdog = Watchdog(HealthConfig(policy=tcfg["health"]),
                            lr=tcfg["lr"], on_fatal=on_fatal,
                            rank=process_index)
        watchdog.seed_good(state, epoch=tcfg["start_epoch"],
                           offset=start_offset, step=start_step)

    # --profile_dispatch K: the per-step host-boundary decomposition
    # (telemetry/dispatch.py; docs/OBSERVABILITY.md §Dispatch forensics).
    # The hooks in the loops hold a NullProfiler otherwise, so this is
    # the only place a syncing profiler can come from.
    dispatch_profiler = None
    if tcfg.get("profile_dispatch"):
        dispatch_profiler = telemetry.DispatchProfiler(
            sample_every=int(tcfg["profile_dispatch"]))

    # --metrics_port: the live pull endpoint (telemetry/prom.py) — the
    # unified registry as Prometheus text at GET /metrics, the health
    # verdict at GET /healthz, from a stdlib daemon thread. Rank 0 only
    # (one scrape target per host run; every rank's state is visible in
    # the trace). Started AFTER the watchdog exists so the very first
    # scrape already shows the health_* gauges (worst severity 0 =
    # healthy), and before training so a scraper watches the run come up.
    metrics_server = None
    if tcfg["metrics_port"] is not None and process_index == 0:
        from ..telemetry.prom import start_metrics_server
        # scrapes should see the HBM watermarks even without --telemetry
        telemetry.install_memory_watermarks()
        metrics_server = start_metrics_server(tcfg["metrics_port"])
        mhost, mport = metrics_server.server_address[:2]
        print(f"metrics on http://{mhost}:{mport}/metrics",
              file=sys.stderr, flush=True)

    if process_index == 0:
        print(f"pytorch_ddp_mnist_tpu: devices={jax.device_count()} "
              f"processes={num_processes} params={param_count(state.params)} "
              f"global_batch={global_batch} parallel={tcfg['parallel']}")

    # Epoch-granular checkpointing (added capability — the reference saves
    # only once, after training, ddp_tutorial_multi_gpu.py:143-144; rank-0
    # gating matches it). Atomic overwrite, so preemption at epoch k resumes
    # from k-1 via --resume. Exception: --fused replays hooks after the
    # whole-run program finishes, so mid-run preemption leaves no
    # intermediate checkpoint (documented on the flag).
    user_hook = None
    if process_index == 0 and tcfg["checkpoint"]:
        def user_hook(e, st):
            save_checkpoint(tcfg["checkpoint"], st.params)
            _consume_sidecar(tcfg["checkpoint"])
    hook = user_hook

    # Mid-run outage resilience (--outage_retries, serial only): the hook
    # additionally keeps HOST-side copies of the latest completed epoch's
    # params AND key, so a dead backend cannot take the run's progress with
    # it — _train_with_outage_retry resumes from this stash at the next
    # global epoch. Seeded below with the starting state (epoch
    # start_epoch-1) so an outage before the first epoch completes can
    # still rebuild.
    stash = {}
    if tcfg["outage_retries"]:
        def hook(e, st):
            stash["epoch"] = e
            stash["params"] = jax.tree_util.tree_map(np.asarray, st.params)
            stash["key"] = np.asarray(jax.random.key_data(st.key))
            if user_hook is not None:
                user_hook(e, st)

        stash["epoch"] = tcfg["start_epoch"] - 1
        stash["params"] = jax.tree_util.tree_map(np.asarray, state.params)
        stash["key"] = np.asarray(jax.random.key_data(state.key))

    # Step-granular crash-consistent checkpointing (--ckpt_every_steps,
    # train/ckpt_manager.py): rank 0 commits the FULL resume state —
    # params, RNG key chain, epoch/step/sampler offset — every N steps
    # (and at epoch ends) into <--checkpoint>.steps/, atomic +
    # CRC-stamped + keep-last-N. A kill at ANY step then resumes bitwise
    # via `--resume <that directory>`. A FAILED save must never take down
    # a healthy run: it degrades to a flight-recorder entry and a stderr
    # line (durability shrinks; training continues).
    step_hook = None
    _ckpt_meta = _run_geometry(tcfg, dcfg, global_batch)
    if tcfg["elastic"]:
        # elastic manifests additionally stamp the device count (the
        # reshape pre-pass plans from it; pre-elastic manifests fall back
        # to the residual's row count) and the world generation
        from ..elastic import world_generation as _world_generation
        _ckpt_meta = {**_ckpt_meta, "devices": num_shards,
                      "elastic_gen": _world_generation()}
    if tcfg["ckpt_every_steps"] and process_index == 0:
        from ..train.checkpoint import CheckpointError
        from ..train.ckpt_manager import CheckpointManager
        step_mgr = CheckpointManager(tcfg["checkpoint"] + ".steps",
                                     keep=tcfg["ckpt_keep"])

        resid_warned = [False]

        def step_hook(ep, off, gs, st):
            # the int8 strategy's error-feedback residual rides the
            # checkpoint so a resumed run continues the unbroken
            # quantization-error accounting — but it is dp-SHARDED
            # device state, and in a multi-HOST world rank 0 cannot
            # fetch the other hosts' shards without a collective (only
            # rank 0 runs this hook, so a collective here would
            # deadlock). Degrade loudly: the checkpoint commits without
            # it and a resume reseeds a zero residual, losing at most
            # one step's quantization error — never the run.
            resid = None
            if st.resid is not None:
                if getattr(st.resid, "is_fully_addressable", True):
                    resid = np.asarray(st.resid)
                elif not resid_warned[0]:
                    resid_warned[0] = True
                    telemetry.flight.record("checkpoint_resid_skipped",
                                            step=gs)
                    print("[ckpt] int8 residual spans non-addressable "
                          "devices (multi-host world); step checkpoints "
                          "commit without it — a resume reseeds a zero "
                          "residual", file=sys.stderr, flush=True)
            try:
                step_mgr.save(st.params,
                              np.asarray(jax.random.key_data(st.key)),
                              tcfg["impl"], step=gs, epoch=ep, offset=off,
                              meta=_ckpt_meta, resid=resid)
            except CheckpointError as e:
                telemetry.flight.record("checkpoint_save_failed", step=gs,
                                        error=str(e)[:500])
                print(f"[ckpt] step checkpoint save failed (training "
                      f"continues): {e}", file=sys.stderr, flush=True)

    # --elastic: EVERY rank keeps a host-side copy of the last step-hook
    # state (the elastic stash). The rescue leader after a peer loss is the
    # lowest SURVIVING rank — often not rank 0, since rank 0 may be the
    # dead one — and a rescue can only pin what this rank stashed. Rides
    # the existing step-hook cadence (--ckpt_every_steps, which --elastic
    # requires): host copies of replicated arrays every N steps, no extra
    # device work.
    elastic_stash = {}
    coordinator = None
    if tcfg["elastic"]:
        from ..elastic import ElasticCoordinator
        _ckpt_step_hook = step_hook

        def _stash_state(ep, off, gs, st):
            elastic_stash["epoch"] = ep
            elastic_stash["offset"] = off
            elastic_stash["step"] = gs
            elastic_stash["params"] = jax.tree_util.tree_map(np.asarray,
                                                             st.params)
            elastic_stash["key"] = np.asarray(jax.random.key_data(st.key))
            # same multi-host degrade as the step hook above: a
            # non-addressable residual is dropped from the stash (a rescue
            # reseeds zeros — one step's quantization error, not the run)
            elastic_stash["resid"] = (
                np.asarray(st.resid) if st.resid is not None
                and getattr(st.resid, "is_fully_addressable", True)
                else None)

        def step_hook(ep, off, gs, st):  # noqa: F811 — elastic wrapper
            _stash_state(ep, off, gs, st)
            if _ckpt_step_hook is not None:
                _ckpt_step_hook(ep, off, gs, st)

        # seed with the starting state so a peer loss BEFORE the first
        # checkpoint interval still has something to rescue
        _stash_state(tcfg["start_epoch"], start_offset, start_step, state)
        coordinator = ElasticCoordinator(
            steps_dir=tcfg["checkpoint"] + ".steps",
            telemetry_dir=tcfg["telemetry"], rank=process_index,
            world=num_processes, reshape_mode=tcfg["reshape"],
            impl=tcfg["impl"], geometry=_ckpt_meta,
            ckpt_keep=tcfg["ckpt_keep"])

    # --eval_shuffle: the reference's shuffled test loader, engine-faithful
    # (torch-bitwise MT19937 randperm, seeded --seed + epoch since the
    # reference's is unseeded). Only the ref-unit val_loss's batch
    # segmentation changes; eval device work is untouched.
    eval_perm = None
    if tcfg["eval_shuffle"]:
        from ..parallel.torch_rng import torch_randperm
        n_test = len(test_labels)
        eval_perm = lambda e: torch_randperm(n_test, tcfg["seed"] + e)  # noqa: E731

    from ..utils.logging import rank_zero_log
    # --profile: op-level jax.profiler capture, entered through the
    # telemetry package's export surface (one front door from phase stats
    # down to XPlane protos; same no-op-when-falsy contract as before).
    from ..telemetry.export import profiler_trace as trace
    log = rank_zero_log(print)
    if tcfg["cached"]:
        # Epoch-scanned fast path: dataset resident in HBM, one jitted
        # lax.scan program per epoch (train/scan.py). Works multi-process
        # too: every process holds the dataset host-side (the PnetCDF
        # COLLECTIVE-read analog, mnist_pnetcdf_cpu.py:47) and the same
        # global sampler state; the scan shards each global batch's index
        # rows over the mesh devices.
        from ..train.scan import fit_cached
        if dcfg["netcdf"]:
            # Gather only the sampled rows (honors --limit via the n_train
            # computed above; whole-file fast path when unlimited).
            rows = (None if n_train == loader.num_samples
                    else np.arange(n_train))
            images, labels = read_mnist_netcdf(train_nc, rows)
            y_train = labels.astype(np.int32)
        else:
            n_train = len(train)
            images = train.images
            y_train = train.labels.astype(np.int32)
        # Raw uint8 pixels go to HBM; the scan normalizes per gather
        # (train/scan.py resident_images — 4x less HBM than resident f32).
        sampler = ShardedSampler(n_train, num_replicas=1, rank=0,
                                 shuffle=True, seed=42,
                                 permutation=tcfg["sampler_rng"])

        def run_fit(st, start):
            # start_offset belongs to THE run epoch it was restored into:
            # an outage-retry re-entry at a later epoch starts it at 0 (a
            # re-entry at the SAME epoch means no epoch completed — the
            # stash holds the restored mid-epoch state, offset and all)
            return fit_cached(st, images, y_train, sampler, x_test,
                              test_labels, epochs=tcfg["n_epochs"],
                              batch_size=global_batch, lr=tcfg["lr"],
                              mesh=mesh, dtype=tcfg["dtype"],
                              kernel=tcfg["kernel"],
                              interpret=use_pallas and _pallas_interpret(),
                              fused=tcfg["fused"], comm=tcfg["ddp_comm"],
                              bf16_rounding=tcfg["bf16_rounding"],
                              overlap=tcfg["overlap"],
                              quant_block=tcfg["quant_block"],
                              error_feedback=tcfg["error_feedback"],
                              model=tcfg["model"],
                              param_scale=tcfg["param_scale"],
                              log=log, epoch_hook=hook, start_epoch=start,
                              start_offset=(start_offset
                                            if start == tcfg["start_epoch"]
                                            else 0),
                              ckpt_every_steps=tcfg["ckpt_every_steps"],
                              step_hook=step_hook,
                              eval_perm=eval_perm,
                              watchdog=watchdog,
                              prefetch_depth=tcfg["prefetch_depth"],
                              dispatch_profiler=dispatch_profiler)
    else:
        if tcfg["dropout_rng"] == "torch":
            # Masks stream from torch's bitwise CPU bernoulli stream
            # (train/loop.py make_torch_dropout_train_step; the draw of
            # ddp_tutorial_cpu.py:47, seeded --seed). Built HERE, after
            # the loader, because a resumed run (--start_epoch k)
            # fast-forwards the stream by k epochs' worth of steps — the
            # per-epoch step count comes from the sampler's padded shard
            # size (every batch is wrap-padded to full batch_size).
            from ..train.loop import make_torch_dropout_train_step
            train_step = make_torch_dropout_train_step(
                tcfg["lr"], tcfg["seed"],
                # mask position is a pure function of completed steps, so a
                # mid-epoch directory resume fast-forwards by the manifest
                # offset on top of the whole-epoch skip
                skip_steps=tcfg["start_epoch"] * len(loader) + start_offset,
                batch_size=tcfg["batch_size"])

        def run_fit(st, start):
            return fit(st, loader, x_test, test_labels,
                       epochs=tcfg["n_epochs"],
                       batch_size=global_batch,
                       **({"lr": tcfg["lr"]} if train_step is None else {}),
                       log=log, train_step=train_step, put=put,
                       model_apply=model_spec.apply,
                       epoch_hook=hook, start_epoch=start,
                       start_offset=(start_offset
                                     if start == tcfg["start_epoch"]
                                     else 0),
                       ckpt_every_steps=tcfg["ckpt_every_steps"],
                       step_hook=step_hook,
                       eval_perm=eval_perm,
                       watchdog=watchdog,
                       input_workers=tcfg["input_workers"],
                       prefetch_depth=tcfg["prefetch_depth"],
                       journal=journal,
                       dispatch_profiler=dispatch_profiler)
    if coordinator is not None:
        # The elastic reaction intercepts BEFORE the outage machinery: a
        # RuntimeError with a backend-loss signature may be a DEAD PEER
        # (membership change — rescue, re-rank, re-exec into the surviving
        # world; react never returns) rather than a transient backend blip.
        # react re-raises when it is NOT a peer loss — a program error, or
        # every rank beaconed back (nobody died) — and the error falls
        # through to _train_with_outage_retry's existing triage unchanged.
        _plain_run_fit = run_fit

        def run_fit(st, start):  # noqa: F811 — elastic wrapper
            try:
                return _plain_run_fit(st, start)
            except RuntimeError as e:
                coordinator.react(e, elastic_stash, journal=journal)
                raise

    from ..telemetry.health import TrainingHealthError
    try:
        state = _train_with_outage_retry(run_fit, state, tcfg, stash, trace,
                                         argv, process_index=process_index)
    except TrainingHealthError as e:
        # --health abort: the watchdog already emitted the health events
        # and dumped the flight ring; exit by name, not by traceback (a
        # diverged model is a diagnosed outcome, not a crash)
        raise SystemExit(f"[health] {e}")

    if journal is not None:
        # clean shutdown: the journal_end trailer marks this rank as
        # having finished its collective sequence (the desync detector
        # only compares positions of cleanly-closed journals), and the
        # watchdog thread stops. BEFORE the registry snapshot below so
        # the cluster.* metrics land in the trace's final record.
        telemetry.cluster.disable_journal()
    if tcfg["telemetry"]:
        # End of run: stamp the memory gauges, write the final registry
        # snapshot as the trace's last record, close the file, and print
        # the rank-0 one-line summary the flag promises.
        reg = telemetry.get_registry()
        telemetry.collect_memory(reg)
        snap = reg.snapshot()
        telemetry.get_tracer().snapshot(reg)
        telemetry.disable()
        rss = snap["gauges"].get("host.rss_bytes")
        dev = snap["gauges"].get("device.peak_bytes_in_use")
        log(f"[telemetry] epochs={tcfg['n_epochs']} "
            f"xla_compiles={snap['counters'].get('xla.compiles', 0)} "
            f"host_rss_mb={rss // 2**20 if rss else None} "
            f"device_peak_mb={dev // 2**20 if dev is not None else None} "
            f"trace={tcfg['telemetry']}")

    if process_index == 0 and tcfg["checkpoint"]:
        save_checkpoint(tcfg["checkpoint"], state.params)
        _consume_sidecar(tcfg["checkpoint"])
        print(f"saved checkpoint to {tcfg['checkpoint']}")
    # The run resumed from an outage STASH file and completed: the stash
    # has served its purpose — same durable-progress rule as the sidecar.
    # Two shapes qualify (both otherwise persist forever in the cwd):
    #   * a non-zero rank's rank-suffixed sibling (never path-matched by
    #     _consume_sidecar);
    #   * rank 0's default-named stash (--checkpoint was empty, so
    #     _persist_and_reexec fell back to _DEFAULT_STASH and no final
    #     save ever overwrites/consumes it).
    # A user's own --resume checkpoint never matches either shape.
    stale_stash = None
    if tcfg["resume"]:
        if (process_index > 0
                and tcfg["resume"].endswith(f".rank{process_index}")):
            stale_stash = tcfg["resume"]
        elif (process_index == 0 and not tcfg["checkpoint"]
                and os.path.basename(tcfg["resume"]) == _DEFAULT_STASH):
            stale_stash = tcfg["resume"]
    if stale_stash:
        for stale in (stale_stash, stale_stash + ".rng.npz"):
            try:
                os.remove(stale)
            except FileNotFoundError:
                pass
    if metrics_server is not None:
        metrics_server.shutdown()   # daemon thread; explicit close anyway
    return 0


if __name__ == "__main__":
    sys.exit(main())
