"""`python -m pytorch_ddp_mnist_tpu ledger` — the performance-ledger CLI.

Three verbs over one artifact directory (default: the current repo root):

  ingest DIR      parse every committed artifact generation into canonical
                  ledger rows; print the row/series/skip census (--json for
                  the raw rows). Exit 1 when DIR holds no artifacts.
  report DIR      the per-series trajectory table — first -> latest, best,
                  current-vs-best %, consecutive-worse streak. Markdown by
                  default (docs embed it verbatim); --json for machines.
  gate DIR        the direction-aware trend gate: exit 3 naming series +
                  offending runs when the newest point regresses past
                  --threshold vs the median+MAD band of the last --window
                  runs. Exit 0 on a healthy trajectory, 1 when there was
                  nothing to gate.

--telemetry OUT emits one schema-v1 `ledger_row` point per canonical row
plus `ledger.series` / `ledger.regressions` / `ledger.rows` registry
metrics, so `scripts/check_telemetry.py --require ledger.` can gate a
ledger run like any other telemetry producer.

Everything here is stdlib-only (telemetry/ledger.py's contract): the
ledger must run wherever the artifacts land, jax installed or not.
"""

from __future__ import annotations

import argparse
import json
import sys

from ..telemetry.ledger import (DEFAULT_THRESHOLD, DEFAULT_WINDOW,
                                LedgerError, discover, gate, ingest,
                                render_markdown, report)

EXIT_OK = 0
EXIT_EMPTY = 1
EXIT_USAGE = 2
EXIT_REGRESSION = 3


def _emit_telemetry(out_dir: str, rows, rep) -> None:
    """Mirror of costs.harvest_cli's producer shape: enable -> points ->
    registry snapshot -> disable. One `ledger_row` point per canonical
    row; the registry carries the census the checker's --require gates."""
    from ..telemetry import disable, enable, get_registry, get_tracer
    enable(out_dir, process_index=0)
    try:
        tracer = get_tracer()
        reg = get_registry()
        for row in rows:
            tracer.point("ledger_row", series=row["series"],
                         metric=row["metric"], value=row["value"],
                         direction=row["direction"],
                         run_ord=row["run_ord"], source=row["source"])
        reg.counter("ledger.rows").inc(len(rows))
        reg.gauge("ledger.series").set(float(rep["n_series"]))
        reg.gauge("ledger.regressions").set(float(len(rep["regressions"])))
        tracer.snapshot(reg)
    finally:
        disable()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="performance ledger: every committed artifact as one "
                    "direction-aware metric history with trend gates")
    p.add_argument("command", choices=("ingest", "report", "gate"),
                   help="ingest: parse + census; report: trajectory "
                        "table; gate: trend regression gate (exit 3)")
    p.add_argument("dir", nargs="?", default=".",
                   help="artifact directory (default: current directory)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output instead of the table")
    p.add_argument("--markdown", action="store_true",
                   help="force the markdown table (report's default)")
    p.add_argument("--window", type=int, default=DEFAULT_WINDOW,
                   help="history runs the median+MAD band is computed "
                        "over (default %(default)s)")
    p.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                   help="direction-aware worse-ratio past which the "
                        "newest point regresses (default %(default)s)")
    p.add_argument("--telemetry", metavar="DIR",
                   help="emit ledger_row points + registry snapshot as a "
                        "schema-v1 JSONL trace under DIR")
    a = p.parse_args(argv)
    if a.json and a.markdown:
        print("ledger: --json and --markdown are mutually exclusive",
              file=sys.stderr)
        return EXIT_USAGE

    paths = discover(a.dir)
    if not paths:
        print(f"ledger: no artifacts under {a.dir} (looked for "
              f"BENCH_r*/MULTICHIP_r*/COST_r*/SERVE_r*/INPUT_r*/"
              f"bench_matrix_r*.json)", file=sys.stderr)
        return EXIT_EMPTY
    try:
        ing = ingest(paths)
    except LedgerError as e:
        print(f"ledger: {e}", file=sys.stderr)
        return EXIT_EMPTY
    rows = ing["rows"]
    rep = gate(rows, window=a.window, threshold=a.threshold)
    if a.telemetry:
        _emit_telemetry(a.telemetry, rows, rep)

    if a.command == "ingest":
        if a.json:
            json.dump(ing, sys.stdout, indent=2)
            print()
        else:
            print(f"ledger: {ing['artifacts']} artifact(s) -> "
                  f"{len(rows)} row(s) in {rep['n_series']} series "
                  f"across {len(rep['families'])} families "
                  f"({', '.join(rep['families'])}); "
                  f"{len(ing['skipped'])} skip(s)")
            for s in ing["skipped"]:
                print(f"  skipped {s['source']}: {s['reason']}")
        return EXIT_OK

    if a.command == "report":
        if a.json:
            json.dump(rep, sys.stdout, indent=2)
            print()
        else:
            print(render_markdown(rep))
        return EXIT_OK

    # gate
    if a.json:
        json.dump(rep, sys.stdout, indent=2)
        print()
    if not rows:
        print("ledger gate: artifacts present but no gateable rows",
              file=sys.stderr)
        return EXIT_EMPTY
    if rep["failures"]:
        for line in rep["failures"]:
            print(f"ledger gate: REGRESSION {line}", file=sys.stderr)
        print(f"ledger gate: {len(rep['failures'])} series regressed "
              f"(of {rep['n_series']} checked)", file=sys.stderr)
        return EXIT_REGRESSION
    if not a.json:
        print(f"ledger gate: OK — {rep['n_series']} series checked "
              f"(window {a.window}, threshold {a.threshold:g}), "
              f"0 regressions")
    return EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
