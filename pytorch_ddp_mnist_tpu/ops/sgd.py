"""Plain SGD, matching the reference optimizer exactly: torch.optim.SGD with
lr=0.01 and no momentum / weight decay / schedule
(ddp_tutorial_multi_gpu.py:75). Stateless, so the "optimizer state" in our
train step is just the params pytree itself — one less buffer to shard.
"""

from __future__ import annotations

import jax


def sgd_step(params, grads, lr: float):
    """params <- params - lr * grads, elementwise over the pytree."""
    return jax.tree_util.tree_map(lambda p, g: p - lr * g.astype(p.dtype),
                                  params, grads)


def sgd_step_flat(flat_params, flat_grads, lr: float):
    """The sharded-update variant: the same `p - lr*g` math on ONE flat
    (n,) slice — the 1/N shard each device owns after the reduce-scatter
    in `parallel.collectives.sharded_update`. Kept beside `sgd_step` so
    the two spellings of the optimizer can never drift apart."""
    return flat_params - lr * flat_grads.astype(flat_params.dtype)
