"""Plain SGD, matching the reference optimizer exactly: torch.optim.SGD with
lr=0.01 and no momentum / weight decay / schedule
(ddp_tutorial_multi_gpu.py:75). Stateless, so the "optimizer state" in our
train step is just the params pytree itself — one less buffer to shard.
"""

from __future__ import annotations

import jax


def sgd_step(params, grads, lr: float):
    """params <- params - lr * grads, elementwise over the pytree."""
    return jax.tree_util.tree_map(lambda p, g: p - lr * g.astype(p.dtype),
                                  params, grads)
