"""Fused train-step Pallas kernel: the whole fwd+bwd in ONE TPU kernel.

The hot op of this framework is the MLP training step
(flatten -> fc1+ReLU+dropout -> fc2+ReLU -> fc3 -> CE loss -> backward ->
grads; reference semantics at ddp_tutorial_multi_gpu.py:90-95). The model is
118k params — every weight, the per-chip batch, and all activations fit in
one core's VMEM with room to spare, so the XLA-kernel-per-op model (HBM round
trips between fusions) is pure overhead. This kernel keeps the entire
fwd+bwd dataflow resident in VMEM: six MXU matmuls (three forward, three
gradient) plus all elementwise work in a single `pallas_call`.

Design notes (see /opt/skills/guides/pallas_guide.md):
  * The batch dimension is a Pallas GRID: each grid step streams one
    MAX_BATCH_BLOCK-row block of x/y/mask through VMEM while weights stay
    resident (their index_map pins block (0,0) every step), and gradients
    accumulate across the sequential TPU grid iterations — so per-chip batch
    scales past a single VMEM block with bounded memory (~5 MB at block 512).
  * The class dimension (10) is zero-padded to one full 128 lane tile
    (`PADDED_CLASSES`); padded logit columns are masked to -1e30 before the
    softmax, so their probability — and therefore their gradient — is
    exactly 0 and fc3's padded weight columns stay zero through SGD.
  * The dropout mask arrives PRE-SCALED (0 or 1/keep) as a kernel input
    rather than being drawn in-kernel from pltpu.prng_random_bits: the mask
    then comes from the same jax.random.bernoulli stream as the reference
    path (models/mlp.py), making the fused step bitwise-matched in RNG to
    the unfused one (tested), and the kernel stays deterministic and
    CPU-interpretable. An all-ones mask gives the eval/no-dropout step.
  * Gradients are returned (not applied): the serial wrapper fuses the SGD
    update in the surrounding jit; the DP wrapper `pmean`s them across the
    mesh first — the same split as parallel/ddp.py, so the kernel slots
    into both without an in-kernel collective.
  * All matmuls accumulate in float32 on the MXU via preferred_element_type
    (bfloat16 inputs welcome; master weights stay f32 in the wrapper).

Beyond the per-step kernel, this module provides (round 2-3):
  * `epoch_fused_sgd` / `_make_epoch_kernel` — the WHOLE-EPOCH kernel:
    weights VMEM-resident across every SGD step of an epoch, raw-uint8
    batch blocks normalized on the VPU at load, in-kernel core-PRNG
    dropout; the single-chip headline path (docs/PERF.md).
  * bf16-matmul mode for BOTH kernels (bf16 MXU operands, f32
    accumulation/master weights), keyed off the batch dtype; oracle:
    `step_reference_bf16`.
  * the EXPERIMENTAL DP epoch mode: per-step DDP mean gradients via an
    in-kernel ICI ring allreduce (remote DMAs + semaphores inside the
    grid) — see `_make_epoch_kernel`'s dp notes.
  * CPU-CI oracles: `epoch_sgd_reference` (pure-JAX epoch recurrence) and
    the masked, interpretable kernel variant (`masks=` + `interpret=`).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..compat import tpu_compiler_params

from ..data.mnist import MNIST_MEAN, MNIST_STD
from ..models.mlp import MLP_DIMS, DROPOUT_RATE

IN_DIM, HIDDEN1, HIDDEN2, NUM_CLASSES = MLP_DIMS
PADDED_CLASSES = 128  # one full lane tile
_NEG_INF = -1e30


# Per-grid-step batch block. Bounds VMEM regardless of total batch:
# x block (512x784 f32) 1.6 MB + ~8 block-sized activations (512x128 f32,
# 0.25 MB each) + weights/grads resident (~1.1 MB) ≈ 5 MB, well under the
# ~16 MB/core budget — so per-chip batch scales arbitrarily (VERDICT r1
# weak #5: the old single-block kernel capped batch at VMEM).
MAX_BATCH_BLOCK = 512


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


# P(bits < _KEEP_THRESH) = 1 - DROPOUT_RATE for uniform uint32 bits — the
# in-kernel Bernoulli of the pallas_rng variant.
_KEEP_THRESH = int(round((1.0 - DROPOUT_RATE) * 2**32))

# Threefry-2x32 rotation schedule (Random123 / jax._src.prng): 5 groups of
# 4 ARX rounds, alternating these two rotation lists, with a key injection
# after each group.
_TF_ROT_A = (13, 15, 26, 6)
_TF_ROT_B = (17, 29, 16, 24)


def threefry2x32(k0, k1, x0, x1):
    """jax's threefry2x32 block cipher as plain jnp uint32 ops.

    Bit-for-bit the stream behind every jax.random threefry draw (pinned by
    tests against jax.random.bits). Written in portable ops (add/xor/shift
    on uint32) so the SAME code runs under jit, the Pallas interpreter, and
    Mosaic — which is what makes the epoch kernel's in-kernel
    reference-RNG dropout CI-coverable on CPU, unlike the core-PRNG path.
    """
    u32 = jnp.uint32

    def rotl(x, d):
        return (x << u32(d)) | (x >> u32(32 - d))

    ks0, ks1 = k0, k1
    ks2 = k0 ^ k1 ^ u32(0x1BD11BDA)
    x0 = x0 + ks0
    x1 = x1 + ks1
    for i, (rots, (i0, i1)) in enumerate((
            (_TF_ROT_A, (ks1, ks2)), (_TF_ROT_B, (ks2, ks0)),
            (_TF_ROT_A, (ks0, ks1)), (_TF_ROT_B, (ks1, ks2)),
            (_TF_ROT_A, (ks2, ks0)))):
        for r in rots:
            x0 = x0 + x1
            x1 = rotl(x1, r)
            x1 = x1 ^ x0
        x0 = x0 + i0
        x1 = x1 + i1 + u32(i + 1)
    return x0, x1


def _threefry_mask_block(k0, k1, rows):
    """(rows, HIDDEN1) pre-scaled dropout mask == dropout_mask(key, rows)
    BIT-FOR-BIT, computed from the key's two uint32 words.

    Replays jax's exact draw: partitionable threefry random_bits (counts =
    the 64-bit element index split hi/lo — hi is 0 at these sizes — and
    bits = out0 ^ out1), uniform's mantissa fill ((bits>>9)|0x3f800000,
    bitcast, -1, max 0), bernoulli's `u < keep` compare, then the 1/keep
    inverted-dropout scale (models/mlp.py:85-88). Pure jnp, so it runs in
    the Mosaic kernel AND the interpreter AND plain jit identically."""
    assert HIDDEN1 == 128  # idx = (row << 7) | col below
    u32, f32 = jnp.uint32, jnp.float32
    r = jax.lax.broadcasted_iota(u32, (rows, HIDDEN1), 0)
    c = jax.lax.broadcasted_iota(u32, (rows, HIDDEN1), 1)
    idx = (r << u32(7)) | c                      # row-major element index
    o0, o1 = threefry2x32(k0, k1, jnp.zeros_like(idx), idx)
    bits = o0 ^ o1
    u = jax.lax.bitcast_convert_type(
        (bits >> u32(9)) | u32(0x3F800000), f32) - f32(1.0)
    u = jnp.maximum(f32(0.0), u)
    keep = f32(1.0 - DROPOUT_RATE)
    return jnp.where(u < keep, f32(1.0) / keep, f32(0.0))

# Largest per-step batch the whole-epoch kernel takes: its x input streams
# as ONE (B, 784) f32 block (double-buffered ~3.2 MB x2 at B=1024) next to
# two resident weight copies (~1.1 MB) and (B, 128) activations — ~10 MB at
# B=1024, inside the ~16 MB/core VMEM; B=2048 is not. (The per-step kernel
# instead grids over MAX_BATCH_BLOCK rows and takes any size.)
EPOCH_KERNEL_MAX_BATCH = 1024

# DP epoch kernel: the gradient comm buffer packs every grad tensor into one
# (EPOCH_COMM_ROWS, 128) f32 block. (row offset, rows) per tensor, in pack
# order gw1, gb1, gw2, gb2, gw3 — the ONE place the packed layout lives
# (pack and unpack in both ring strategies iterate this table).
_COMM_LAYOUT = (
    (0, IN_DIM),                       # gw1 rows [0, 784)
    (IN_DIM, 1),                       # gb1 [784]
    (IN_DIM + 1, HIDDEN2),             # gw2 [785, 913)
    (IN_DIM + 1 + HIDDEN2, 1),         # gb2 [913]
    (IN_DIM + 2 + HIDDEN2, PADDED_CLASSES),   # gw3 [914, 1042)
)
EPOCH_COMM_ROWS = _COMM_LAYOUT[-1][0] + _COMM_LAYOUT[-1][1]   # 1042
# The ring all-gather keeps one comm slot PER DEVICE in VMEM (n x 533 KB) so
# every replica can sum contributions in the same fixed order (bitwise-
# identical averaged grads -> weights stay in lockstep without a broadcast).
# 8 slots ≈ 4.3 MB next to the resident weights and batch blocks; past that
# the DP epoch kernel switches to the reduce-scatter ring below (~2 gradient
# blocks of VMEM plus an 8-rows-per-device tile-floor term — ~1.1 MB at n=8,
# ~+8 KB per extra device: one flat grad buffer + n-1 chunk recv slots).
EPOCH_KERNEL_MAX_DEVICES = 8

# rng_impl='threefry' rides the WHOLE per-step key table SMEM-resident as a
# (padded_steps, 2) int32 block (~4 KB for a real 469-step epoch). SMEM is
# the kernel's scarcest memory — scalars and control flow only — so the
# table gets an explicit steps cap like every other resource budget here:
# 4096 steps = 32 KB, ~8x the reference epoch, far below the point where
# Mosaic lowering would fail opaquely instead.
EPOCH_KERNEL_MAX_RNG_STEPS = 4096


def _rs_chunk_rows(n: int) -> int:
    """Reduce-scatter ring chunk height: EPOCH_COMM_ROWS split n ways,
    rounded up to the f32 sublane tile (8 rows) so every remote DMA and
    dynamic slice stays tile-aligned. n * chunk >= EPOCH_COMM_ROWS; the
    alignment tail is zeroed at pack time and discarded at unpack."""
    return _round_up(-(-EPOCH_COMM_ROWS // n), 8)


def _make_fused_kernel(total_batch: int, block: int,
                       in_kernel_rng: bool = False,
                       compute_bf16: bool = False):
    """Build the fwd+bwd kernel for a batch grid of `block`-row steps.

    TPU grid iterations run sequentially on a core, so gradient outputs (whose
    index_map pins the same block every step) accumulate across iterations:
    initialized at program_id 0, `+=` thereafter. Rows past `total_batch`
    (tail padding to a block multiple) are masked out of the loss and — by
    zeroing their dlogits — out of every gradient.

    `in_kernel_rng`: the third input is a (1,) int32 SMEM seed instead of a
    pre-drawn mask block; the kernel seeds the core PRNG with seed+program_id
    (an independent stream per batch block) and draws the pre-scaled dropout
    mask from hardware bits — no mask array ever exists in HBM.

    `compute_bf16`: matmul operands cast to bfloat16 (f32 MXU accumulation
    via preferred_element_type); everything else — loss, grads, elementwise,
    accumulator outputs — stays f32. Same recipe as the epoch kernel's
    bf16 mode (see _make_epoch_kernel).
    """
    mm_dt = jnp.bfloat16 if compute_bf16 else jnp.float32

    def kernel(x_ref, y_ref, m_ref, w1_ref, b1_ref, w2_ref, b2_ref,
               w3_ref, loss_ref, gw1_ref, gb1_ref, gw2_ref, gb2_ref,
               gw3_ref):
        """One block, whole fwd+bwd. Shapes (Bb = block):
        x (Bb,784) f32 · y (Bb,1) i32 · m (Bb,128) f32 pre-scaled dropout
        mask OR (1,) i32 seed (in_kernel_rng) · w1 (784,128) · b1 (1,128) ·
        w2 (128,128) · b2 (1,128) · w3 (128,PADDED_CLASSES) zero-padded past
        column NUM_CLASSES. Outputs: loss (1,1) SMEM · grads matching each
        weight input's shape, all accumulated over the batch grid.
        """
        f32 = jnp.float32
        pid = pl.program_id(0)
        x = x_ref[:]
        if in_kernel_rng:
            # hardware-hashed (seed, block) pair — see _make_epoch_kernel's
            # seed note for why this is not seed + pid
            pltpu.prng_seed(m_ref[0], pid)
            bits = pltpu.bitcast(
                pltpu.prng_random_bits((block, HIDDEN1)), jnp.uint32)
            m = jnp.where(bits < jnp.uint32(_KEEP_THRESH),
                          f32(1.0 / (1.0 - DROPOUT_RATE)), f32(0.0))
        else:
            m = m_ref[:]
        # validity of each row of this block in the ORIGINAL batch
        rows = jax.lax.broadcasted_iota(jnp.int32, (block, 1), 0) + pid * block
        valid = (rows < total_batch).astype(f32)           # (Bb,1)

        # ---- forward (matmul operands in mm_dt; casts are no-ops for f32
        # compute, and x arrives already in mm_dt from the wrapper) ----
        xm = x.astype(mm_dt)
        w1m, w2m, w3m = (w1_ref[:].astype(mm_dt), w2_ref[:].astype(mm_dt),
                         w3_ref[:].astype(mm_dt))
        z1 = jax.lax.dot_general(xm, w1m, (((1,), (0,)), ((), ())),
                                 preferred_element_type=f32) + b1_ref[:]
        h1 = jnp.maximum(z1, 0.0)
        d1 = h1 * m                                    # inverted dropout
        d1m = d1.astype(mm_dt)
        z2 = jax.lax.dot_general(d1m, w2m, (((1,), (0,)), ((), ())),
                                 preferred_element_type=f32) + b2_ref[:]
        h2 = jnp.maximum(z2, 0.0)
        h2m = h2.astype(mm_dt)
        logits = jax.lax.dot_general(h2m, w3m, (((1,), (0,)), ((), ())),
                                     preferred_element_type=f32)

        cols = jax.lax.broadcasted_iota(jnp.int32, (block, PADDED_CLASSES), 1)
        logits = jnp.where(cols < NUM_CLASSES, logits, _NEG_INF)

        # ---- softmax CE (stable); padded cols add exp(-1e30 - mx) = 0 ----
        mx = jnp.max(logits, axis=1, keepdims=True)
        ex = jnp.exp(logits - mx)
        se = jnp.sum(ex, axis=1, keepdims=True)
        onehot = (cols == y_ref[:]).astype(f32)
        logit_y = jnp.sum(jnp.where(onehot > 0, logits, 0.0), axis=1,
                          keepdims=True)
        losses = ((mx + jnp.log(se)) - logit_y) * valid    # -log p[y], (Bb,1)

        # ---- backward ----
        # (Bb,128); 0 on padded cols AND padded rows — zeroing dlogits for
        # pad rows kills their contribution to every downstream gradient.
        dlogits = (ex / se - onehot) * (valid * (1.0 / total_batch))
        dlm = dlogits.astype(mm_dt)
        # gw3 = h2^T @ dlogits (contract batch)
        gw3 = jax.lax.dot_general(h2m, dlm, (((0,), (0,)), ((), ())),
                                  preferred_element_type=f32)
        # dh2 = dlogits @ w3^T (contract class)
        dh2 = jax.lax.dot_general(dlm, w3m, (((1,), (1,)), ((), ())),
                                  preferred_element_type=f32)
        dz2 = dh2 * (z2 > 0.0).astype(f32)
        dz2m = dz2.astype(mm_dt)
        gw2 = jax.lax.dot_general(d1m, dz2m, (((0,), (0,)), ((), ())),
                                  preferred_element_type=f32)
        gb2 = jnp.sum(dz2, axis=0, keepdims=True)
        dd1 = jax.lax.dot_general(dz2m, w2m, (((1,), (1,)), ((), ())),
                                  preferred_element_type=f32)
        dz1 = (dd1 * m) * (z1 > 0.0).astype(f32)
        gw1 = jax.lax.dot_general(xm, dz1.astype(mm_dt),
                                  (((0,), (0,)), ((), ())),
                                  preferred_element_type=f32)
        gb1 = jnp.sum(dz1, axis=0, keepdims=True)

        @pl.when(pid == 0)
        def _init():
            loss_ref[0, 0] = 0.0
            gw1_ref[:] = jnp.zeros_like(gw1_ref)
            gb1_ref[:] = jnp.zeros_like(gb1_ref)
            gw2_ref[:] = jnp.zeros_like(gw2_ref)
            gb2_ref[:] = jnp.zeros_like(gb2_ref)
            gw3_ref[:] = jnp.zeros_like(gw3_ref)

        loss_ref[0, 0] += jnp.sum(losses) / total_batch
        gw1_ref[:] += gw1
        gb1_ref[:] += gb1
        gw2_ref[:] += gw2
        gb2_ref[:] += gb2
        gw3_ref[:] += gw3

    return kernel


def pad_fc3(w3: jax.Array) -> jax.Array:
    """(128, 10) -> (128, PADDED_CLASSES), zero-filled."""
    return jnp.pad(w3, ((0, 0), (0, PADDED_CLASSES - w3.shape[1])))


def fused_loss_and_grads(params, x, y, scaled_mask, *, interpret=False):
    """Run the kernel: (params pytree, x (B,784), y (B,) int, scaled_mask
    (B,128) in {0, 1/keep}) -> (mean_loss, grads pytree).

    Batches over MAX_BATCH_BLOCK rows run as a grid over batch blocks with
    gradient accumulation across the (sequential) grid steps; the tail is
    zero-padded to a block multiple and masked out inside the kernel, so any
    batch size works. `interpret=True` runs the Pallas interpreter (CPU
    tests). A bfloat16 `x` selects the bf16-matmul kernel (bf16 MXU
    operands, f32 accumulation/loss/grads — the --dtype bfloat16 recipe);
    any other dtype computes in f32."""
    return _run_fused(params, x, y, scaled_mask, in_kernel_rng=False,
                      interpret=interpret)


def fused_loss_and_grads_rng(params, x, y, seed):
    """The kernel with the dropout mask drawn INSIDE it from the TPU core
    PRNG (`--kernel pallas_rng`): (params, x (B,784), y (B,) int, seed ()
    or (1,) int32) -> (mean_loss, grads pytree).

    vs fused_loss_and_grads: no (B,128) mask array is materialized in HBM or
    streamed into VMEM — the seed is one SMEM scalar, and each batch block
    draws its own hardware-PRNG stream (seed, block index). Same
    Bernoulli(1-DROPOUT_RATE) keep distribution and 1/keep pre-scaling as
    every other engine; yet another stream, like threefry vs rbg. Mosaic
    (real TPU) only: pltpu.prng_* has no interpreter lowering. bf16-matmul
    mode selected by a bfloat16 `x`, as in fused_loss_and_grads."""
    seed = jnp.asarray(seed, jnp.int32).reshape((1,))
    return _run_fused(params, x, y, seed, in_kernel_rng=True,
                      interpret=False)


def _run_fused(params, x, y, mask_or_seed, *, in_kernel_rng, interpret):
    batch = x.shape[0]
    f32 = jnp.float32
    # bf16 compute is selected by the caller handing a bf16 batch (the scan
    # body casts x to the compute dtype); the kernel keeps f32 accumulation
    compute_bf16 = x.dtype == jnp.bfloat16
    in_dt = jnp.bfloat16 if compute_bf16 else f32
    # Block = whole batch when it fits (rounded to the f32 sublane multiple
    # of 8 for Mosaic); one grid step then reproduces the ungridded kernel
    # exactly. Larger batches split into the fewest ≤MAX_BATCH_BLOCK grid
    # steps with the rows REBALANCED across them (batch=576 -> 2x288, not
    # 512+64-plus-448-pad), so padding waste is capped at 7 rows.
    grid = max(1, -(-batch // MAX_BATCH_BLOCK))
    block = _round_up(-(-batch // grid), 8)
    padded = grid * block
    if padded != batch:
        pad = ((0, padded - batch), (0, 0))
        x = jnp.pad(x.astype(in_dt), pad)
        if not in_kernel_rng:
            mask_or_seed = jnp.pad(mask_or_seed.astype(f32), pad)
        y = jnp.pad(y.astype(jnp.int32), ((0, padded - batch),))
    vmem = partial(pl.BlockSpec, memory_space=pltpu.VMEM)
    resident = lambda shape: vmem(shape, lambda i: (0, 0))  # noqa: E731
    out_shapes = (
        jax.ShapeDtypeStruct((1, 1), f32),                       # loss
        jax.ShapeDtypeStruct((IN_DIM, HIDDEN1), f32),            # gw1
        jax.ShapeDtypeStruct((1, HIDDEN1), f32),                 # gb1
        jax.ShapeDtypeStruct((HIDDEN1, HIDDEN2), f32),           # gw2
        jax.ShapeDtypeStruct((1, HIDDEN2), f32),                 # gb2
        jax.ShapeDtypeStruct((HIDDEN2, PADDED_CLASSES), f32),    # gw3 (padded)
    )
    mask_spec = (pl.BlockSpec((1,), lambda i: (0,),
                              memory_space=pltpu.SMEM)
                 if in_kernel_rng
                 else vmem((block, HIDDEN1), lambda i: (i, 0)))
    loss, gw1, gb1, gw2, gb2, gw3 = pl.pallas_call(
        _make_fused_kernel(batch, block, in_kernel_rng=in_kernel_rng,
                           compute_bf16=compute_bf16),
        grid=(grid,),
        # The gradient outputs accumulate across grid steps, so the batch
        # grid MUST run sequentially — 'arbitrary' pins that down even on
        # megacore parts (v4/v5p) where 'parallel' dims split across cores.
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary",)),
        out_shape=out_shapes,
        in_specs=[
            vmem((block, IN_DIM), lambda i: (i, 0)),             # x
            vmem((block, 1), lambda i: (i, 0)),                  # y
            mask_spec,                                           # mask | seed
            resident((IN_DIM, HIDDEN1)),                         # w1
            resident((1, HIDDEN1)),                              # b1
            resident((HIDDEN1, HIDDEN2)),                        # w2
            resident((1, HIDDEN2)),                              # b2
            resident((HIDDEN2, PADDED_CLASSES)),                 # w3
        ],
        out_specs=(
            pl.BlockSpec((1, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),               # loss
            resident((IN_DIM, HIDDEN1)),
            resident((1, HIDDEN1)),
            resident((HIDDEN1, HIDDEN2)),
            resident((1, HIDDEN2)),
            resident((HIDDEN2, PADDED_CLASSES)),
        ),
        interpret=interpret,
    )(
        x.astype(in_dt),
        y.astype(jnp.int32)[:, None],
        mask_or_seed if in_kernel_rng else mask_or_seed.astype(f32),
        params["fc1"]["w"].astype(f32),
        params["fc1"]["b"].astype(f32)[None, :],
        params["fc2"]["w"].astype(f32),
        params["fc2"]["b"].astype(f32)[None, :],
        pad_fc3(params["fc3"]["w"].astype(f32)),
    )
    grads = {
        "fc1": {"w": gw1, "b": gb1[0]},
        "fc2": {"w": gw2, "b": gb2[0]},
        "fc3": {"w": gw3[:, :NUM_CLASSES]},
    }
    return loss[0, 0], grads


def _make_epoch_kernel(block: int, lr: float, *, rng: str = "core",
                       uint8_in: bool = False, axis_name: str | None = None,
                       n_devices: int = 1, compute_bf16: bool = False,
                       steps_per_iter: int = 1,
                       nsteps_total: int | None = None,
                       ring_rs: bool = False):
    """Whole-EPOCH kernel: grid = (nsteps,), one SGD step per grid iteration,
    weights VMEM-RESIDENT for the entire epoch.

    This removes the dominant remaining HBM term of the per-step design: the
    per-step kernel reads and writes every weight from/to HBM each step
    (~1.4 MB/step); here weights enter once, live in VMEM across all grid
    iterations (copied into the pinned output refs at iteration 0, updated in
    place by the in-kernel SGD), and are flushed once at epoch end. The
    epoch's batches stream through the pipelined x/y input blocks; dropout is
    drawn in-kernel per step.

    `rng` selects the dropout source (and the meaning of the third input):

    - "core" (default): the TPU core PRNG, hardware-hashed (seed, step)
      stream — same Bernoulli keep distribution as every other engine, its
      own stream. Third input = the SMEM epoch seed. Mosaic-only.
    - "threefry": jax's threefry2x32 evaluated IN-kernel on the VPU
      (threefry2x32/_threefry_mask_block above) — the masks are bit-for-bit
      models/mlp.py's bernoulli draw for the same per-step keys, i.e. the
      REFERENCE RNG semantics at epoch-kernel speed (the dropout of
      /root/reference/ddp_tutorial_cpu.py:47, stream and all). Third input
      = the WHOLE (padded_steps, 2) int32 key table, SMEM-resident and
      indexed by global step (a streamed (K, 2) block would be an illegal
      Mosaic block shape — the r05 hardware-window regression). Pure jnp
      ops, so this mode ALSO runs under the interpreter (CPU CI covers it
      end-to-end, unlike "core").
    - "masks": the third input is a streamed (K*block, HIDDEN1) pre-scaled
      mask block — the seeds->mask mapping abstracted to the caller
      (interpreter CI path of the wrapper plumbing).

    `uint8_in=True`: x blocks arrive as RAW uint8 pixels and the kernel
    normalizes on the VPU (/255 -> -mean -> /std, the normalize_images
    chain) — the epoch's input stream through HBM/VMEM is 4x smaller than
    pre-normalized f32, and no f32 epoch image array is ever materialized.

    `n_devices > 1` (with `axis_name`, called inside shard_map): the DDP
    variant — after each step's local grads, an in-kernel ICI ring
    all-gathers every replica's packed gradient block, each replica sums the
    slots in the same fixed order (bitwise-identical mean on every chip, so
    the VMEM-resident weights stay in lockstep with no broadcast), and the
    SGD update applies the mean. This is the per-step DDP allreduce riding
    ICI remote DMAs *inside* the kernel grid — the one thing the
    single-replica epoch kernel couldn't express (VERDICT r2 #8). Per step:
    a 2-neighbor handshake (regular semaphores) fences the previous step's
    slot reuse, then n-1 pipelined hops forward origin-indexed slots around
    the ring (per-hop DMA semaphores — no cross-hop signal conflation).

    `ring_rs=True`: the same per-step allreduce as a reduce-scatter +
    all-gather ring instead — 2(n-1) hops of one EPOCH_COMM_ROWS/n chunk
    each, so per-device ICI traffic drops from (n-1) to ~2 full gradient
    blocks and VMEM stays ~2 gradient blocks plus an 8-rows-per-device
    tile-floor term (the all-gather ring's n origin
    slots don't fit past EPOCH_KERNEL_MAX_DEVICES). Each chunk is reduced
    sequentially along the ring by a single chain (one final owner), then
    the finished chunks are re-broadcast — every device receives identical
    bytes, so the resident weights stay in lockstep exactly as in the
    fixed-order-sum ring.

    `compute_bf16=True`: the six matmuls take bfloat16 operands (f32 MXU
    accumulation via preferred_element_type) while everything else — master
    weights, SGD update, softmax/CE, dropout, gradients — stays float32.
    The f32 kernel is MXU-bound at this batch size (docs/PERF.md roofline);
    bf16 operands run the systolic array at ~4x the f32 rate. Same recipe as
    the XLA path's --dtype bfloat16 (bf16 fwd/bwd, f32 master weights),
    except elementwise ops here keep f32 — a strictly tighter numerics.

    `steps_per_iter=K` (K in {1,2,4,8}; single-replica only): K sequential
    SGD sub-steps per grid iteration, streaming a (K*block, ...) input block
    — amortizes the fixed per-grid-iteration cost (pipeline bookkeeping,
    loss-tile revisit merge) over K steps. The math is IDENTICAL to K=1:
    sub-step k trains on rows [k*block,(k+1)*block) of the iteration's
    block, seeds its dropout stream with the same (seed, global_step) words,
    and updates the resident weights in place before sub-step k+1 reads
    them. `nsteps_total` (required when the step count does not divide by K;
    the wrapper zero-pads the tail) marks trailing padded sub-steps: their
    loss rows are zeroed and their SGD update is skipped via lr=0."""
    dp = n_devices > 1
    K = steps_per_iter
    mm_dt = jnp.bfloat16 if compute_bf16 else jnp.float32

    def kernel(*refs):
        if dp and ring_rs:
            (x_ref, y_ref, m_ref, w1_ref, b1_ref, w2_ref, b2_ref, w3_ref,
             loss_ref, ow1, ob1, ow2, ob2, ow3,
             comm, rsbuf, send_sems, recv_sems, lsem, rsem) = refs
        elif dp:
            (x_ref, y_ref, m_ref, w1_ref, b1_ref, w2_ref, b2_ref, w3_ref,
             loss_ref, ow1, ob1, ow2, ob2, ow3,
             comm, send_sems, recv_sems, lsem, rsem) = refs
        else:
            (x_ref, y_ref, m_ref, w1_ref, b1_ref, w2_ref, b2_ref, w3_ref,
             loss_ref, ow1, ob1, ow2, ob2, ow3) = refs
        f32 = jnp.float32
        pid = pl.program_id(0)

        @pl.when(pid == 0)
        def _init():
            ow1[:] = w1_ref[:]
            ob1[:] = b1_ref[:]
            ow2[:] = w2_ref[:]
            ob2[:] = b2_ref[:]
            ow3[:] = w3_ref[:]

        me = jax.lax.axis_index(axis_name) if dp else None
        # Per-step loss into an (8,128)-tiled VMEM output: global step g owns
        # row g%8 of block g//8 (Mosaic needs ≥(8,128) blocks; a (1,1) SMEM
        # slot per step would be an illegal block shape for a (S,1) array).
        # The block is revisited for 8/K consecutive sequential iterations;
        # on first visit (base%8==0) the whole block is initialized,
        # afterwards merged. The K sub-steps' rows merge in-register and
        # store once at iteration end.
        base = pid * K                      # first global step this iteration
        off = jax.lax.rem(base, 8)
        lrow = jax.lax.broadcasted_iota(jnp.int32, (8, 128), 0)
        tile = jnp.where(off == 0, jnp.zeros((8, 128), f32), loss_ref[:])

        for k in range(K):
            gs = base + k                   # this sub-step's global step
            if rng == "threefry":
                # Reference-RNG dropout: this sub-step's key words (already
                # replica-distinct for DP — the wrapper folds the axis index
                # into the epoch key before splitting) drive the exact
                # models/mlp.py bernoulli draw on the VPU. The key table is
                # SMEM-resident whole (see third_spec), indexed by global
                # step. A padded tail sub-step gets zero key words —
                # harmless, its update is lr=0-masked below.
                m = _threefry_mask_block(m_ref[gs, 0].astype(jnp.uint32),
                                         m_ref[gs, 1].astype(jnp.uint32),
                                         block)
            elif rng == "core":
                # Multi-word seed: the hardware hashes (epoch_seed[,
                # replica], step) into the stream state, so per-step streams
                # are mixed non-linearly — no contiguous seed-range reuse
                # across epochs (a seed+step sum makes nearby epochs' step
                # ranges overlap at percent-level probability over long
                # runs). The replica word gives each DP rank an independent
                # dropout stream (SURVEY.md §7 parity item 4). The words are
                # the same (seed, global step) at every steps_per_iter, so K
                # does not change the masks.
                if dp:
                    pltpu.prng_seed(m_ref[0], me, gs)
                else:
                    pltpu.prng_seed(m_ref[0], gs)
                bits = pltpu.bitcast(
                    pltpu.prng_random_bits((block, HIDDEN1)), jnp.uint32)
                m = jnp.where(bits < jnp.uint32(_KEEP_THRESH),
                              f32(1.0 / (1.0 - DROPOUT_RATE)), f32(0.0))
            else:
                m = m_ref[pl.ds(k * block, block), :]

            x = x_ref[pl.ds(k * block, block), :]
            if uint8_in:
                # normalize_images' op chain, per block, on the VPU. Mosaic
                # has no direct u8->f32 convert; widen through int32 (exact
                # for 0..255, so the math is identical to the host/XLA
                # normalize).
                x = x.astype(jnp.int32).astype(f32)
                x = x / f32(255.0)
                x = x - f32(MNIST_MEAN)
                x = x / f32(MNIST_STD)
            # ---- forward (weights read from the resident, updated refs;
            # matmul operands cast to mm_dt — a no-op cast for f32 compute;
            # sub-step k reads the weights sub-step k-1 wrote) ----
            xm = x.astype(mm_dt)
            w1m, w2m, w3m = (ow1[:].astype(mm_dt), ow2[:].astype(mm_dt),
                             ow3[:].astype(mm_dt))
            z1 = jax.lax.dot_general(xm, w1m, (((1,), (0,)), ((), ())),
                                     preferred_element_type=f32) + ob1[:]
            h1 = jnp.maximum(z1, 0.0)
            d1 = h1 * m
            d1m = d1.astype(mm_dt)
            z2 = jax.lax.dot_general(d1m, w2m, (((1,), (0,)), ((), ())),
                                     preferred_element_type=f32) + ob2[:]
            h2 = jnp.maximum(z2, 0.0)
            h2m = h2.astype(mm_dt)
            logits = jax.lax.dot_general(h2m, w3m, (((1,), (0,)), ((), ())),
                                         preferred_element_type=f32)
            cols = jax.lax.broadcasted_iota(jnp.int32,
                                            (block, PADDED_CLASSES), 1)
            logits = jnp.where(cols < NUM_CLASSES, logits, _NEG_INF)

            mx = jnp.max(logits, axis=1, keepdims=True)
            ex = jnp.exp(logits - mx)
            se = jnp.sum(ex, axis=1, keepdims=True)
            onehot = (cols == y_ref[pl.ds(k * block, block), :]).astype(f32)
            logit_y = jnp.sum(jnp.where(onehot > 0, logits, 0.0), axis=1,
                              keepdims=True)
            step_loss = jnp.sum((mx + jnp.log(se)) - logit_y) / block
            if nsteps_total is not None:
                # zero-padded tail sub-step: keep the loss row zero and skip
                # the SGD update (lr=0 — the padded rows are zeros, finite,
                # so the masked grads are finite too)
                valid = gs < nsteps_total
                step_loss = jnp.where(valid, step_loss, f32(0.0))
                lr_k = jnp.where(valid, f32(lr), f32(0.0))
            else:
                lr_k = lr
            tile = jnp.where(lrow == off + k, step_loss, tile)

            # ---- backward + in-kernel SGD. Every row of a VALID sub-step
            # is real data (the sampler wrap-pads each step to `block` rows
            # exactly); a padded TAIL sub-step (K>1, ragged step count) has
            # arbitrary rows and is neutralized above: loss row zeroed,
            # update skipped via lr_k=0 (pad rows are finite, so 0*g=0) ----
            dlogits = (ex / se - onehot) * (1.0 / block)
            dlm = dlogits.astype(mm_dt)
            gw3 = jax.lax.dot_general(h2m, dlm, (((0,), (0,)), ((), ())),
                                      preferred_element_type=f32)
            dh2 = jax.lax.dot_general(dlm, w3m, (((1,), (1,)), ((), ())),
                                      preferred_element_type=f32)
            dz2 = dh2 * (z2 > 0.0).astype(f32)
            dz2m = dz2.astype(mm_dt)
            gw2 = jax.lax.dot_general(d1m, dz2m, (((0,), (0,)), ((), ())),
                                      preferred_element_type=f32)
            gb2 = jnp.sum(dz2, axis=0, keepdims=True)
            dd1 = jax.lax.dot_general(dz2m, w2m, (((1,), (1,)), ((), ())),
                                      preferred_element_type=f32)
            dz1 = (dd1 * m) * (z1 > 0.0).astype(f32)
            gw1 = jax.lax.dot_general(xm, dz1.astype(mm_dt),
                                      (((0,), (0,)), ((), ())),
                                      preferred_element_type=f32)
            gb1 = jnp.sum(dz1, axis=0, keepdims=True)

            if dp:
                n = n_devices
                left = jax.lax.rem(me + (n - 1), n)
                right = jax.lax.rem(me + 1, n)
                # MESH device ids: coordinates along the shard_map mesh axis —
                # correct even when the mesh's device array was topology-
                # reordered (raw LOGICAL ids would bypass that mapping).
                did = pltpu.DeviceIdType.MESH

                @pl.when(pid == 0)
                def _entry_barrier():
                    # Gate the FIRST remote signal of this kernel invocation on
                    # both neighbors having entered the kernel: the per-step
                    # handshake below signals scratch REGULAR semaphores, which
                    # is only safe once the neighbor's kernel (and its scratch
                    # allocation) is live. The global barrier semaphore (bound
                    # to collective_id) exists exactly for this cross-entry
                    # rendezvous.
                    bsem = pltpu.get_barrier_semaphore()
                    pltpu.semaphore_signal(bsem, inc=1, device_id=(left,),
                                           device_id_type=did)
                    pltpu.semaphore_signal(bsem, inc=1, device_id=(right,),
                                           device_id_type=did)
                    pltpu.semaphore_wait(bsem, 2)

                def _neighbor_handshake():
                    # Per-step neighbor handshake: my hop-0 send overwrites
                    # scratch on `right` that its PREVIOUS step last read, so
                    # I must not send until both neighbors have finished that
                    # step. Dedicated per-neighbor semaphores (I signal
                    # right's lsem as its left neighbor, and vice versa) — a
                    # shared counter could conflate one neighbor running two
                    # steps ahead. Shared by both ring strategies.
                    pltpu.semaphore_signal(lsem, inc=1, device_id=(right,),
                                           device_id_type=did)
                    pltpu.semaphore_signal(rsem, inc=1, device_id=(left,),
                                           device_id_type=did)
                    pltpu.semaphore_wait(lsem, 1)
                    pltpu.semaphore_wait(rsem, 1)

                if ring_rs:
                    # Reduce-scatter + all-gather ring: one flat padded grad
                    # buffer + n-1 chunk recv slots, vs the all-gather
                    # ring's n full origin slots — ~2 gradient blocks plus
                    # an 8-rows-per-device tile-floor term (see
                    # _rs_chunk_rows), i.e. ~1.1 MB at n=8 growing only
                    # ~8 KB per extra device. Chunk c is
                    # reduced SEQUENTIALLY along the ring by a single chain
                    # ending at device (c-1) mod n, then the finished chunks
                    # are re-broadcast — every device receives the same final
                    # bytes for every chunk, so the resident weights stay in
                    # bitwise lockstep (the per-chunk accumulation order
                    # differs from the all-gather ring's origin order; each
                    # is one valid float summation order).
                    C = _rs_chunk_rows(n)
                    total = n * C
                    # Pack this step's grads flat; zero the alignment tail
                    # (summed garbage would be discarded anyway, but scratch
                    # VMEM starts undefined and NaNs must never enter sums).
                    for (off, rows), grad in zip(
                            _COMM_LAYOUT, (gw1, gb1, gw2, gb2, gw3)):
                        comm[pl.ds(off, rows), :] = grad
                    comm[pl.ds(EPOCH_COMM_ROWS, total - EPOCH_COMM_ROWS),
                         :] = jnp.zeros((total - EPOCH_COMM_ROWS, 128), f32)
                    _neighbor_handshake()
                    # Phase 1 — reduce-scatter: hop h sends partial chunk
                    # (me-h) right, into the hop's DEDICATED recv slot
                    # (written once per step — reuse fenced by the entry
                    # handshake), then folds the arriving chunk (me-h-1)
                    # into the local partial it forwards next hop. After
                    # n-1 hops this device owns reduced chunk (me+1) mod n.
                    for h in range(n - 1):
                        send_c = jax.lax.rem(me - h + 2 * n, n)
                        rdma = pltpu.make_async_remote_copy(
                            src_ref=comm.at[pl.ds(send_c * C, C)],
                            dst_ref=rsbuf.at[h],
                            send_sem=send_sems.at[h],
                            recv_sem=recv_sems.at[h],
                            device_id=(right,), device_id_type=did)
                        rdma.start()
                        rdma.wait()   # my send done AND left's chunk landed
                        add_c = jax.lax.rem(me - h - 1 + 2 * n, n)
                        comm[pl.ds(add_c * C, C), :] = (
                            comm[pl.ds(add_c * C, C), :] + rsbuf[h])
                    # Phase 2 — all-gather of reduced chunks: hop a forwards
                    # the chunk finished at hop a-1 (hop 0: my own) into the
                    # SAME chunk position on the right neighbor. Each
                    # position takes exactly one incoming write per step,
                    # and chunk c's reduction chain passed through this
                    # device's phase-1 hop that last read comm[c] — the
                    # incoming write is transitively ordered after it, so
                    # the per-hop DMA semaphores are the only fence needed.
                    for a in range(n - 1):
                        send_c = jax.lax.rem(me + 1 - a + 2 * n, n)
                        rdma = pltpu.make_async_remote_copy(
                            src_ref=comm.at[pl.ds(send_c * C, C)],
                            dst_ref=comm.at[pl.ds(send_c * C, C)],
                            send_sem=send_sems.at[n - 1 + a],
                            recv_sem=recv_sems.at[n - 1 + a],
                            device_id=(right,), device_id_type=did)
                        rdma.start()
                        rdma.wait()
                    scale = f32(1.0 / n)
                    gw1, gb1, gw2, gb2, gw3 = (
                        comm[pl.ds(off, rows), :] * scale
                        for off, rows in _COMM_LAYOUT)
                else:
                    # Pack this replica's grads into its origin-indexed comm
                    # slot.
                    for (off, rows), grad in zip(
                            _COMM_LAYOUT, (gw1, gb1, gw2, gb2, gw3)):
                        comm[me, pl.ds(off, rows), :] = grad
                    _neighbor_handshake()
                    # Ring all-gather: hop h forwards the slot received at
                    # hop h-1 (hop 0: my own) to the right; slots keep their
                    # ORIGIN index on every device. Per-hop DMA semaphores so
                    # an out-of-order arrival of hop h+1's signal can never
                    # satisfy hop h's wait.
                    for h in range(n - 1):
                        send_slot = jax.lax.rem(me - h + n * 2, n)
                        rdma = pltpu.make_async_remote_copy(
                            src_ref=comm.at[send_slot],
                            dst_ref=comm.at[send_slot],
                            send_sem=send_sems.at[h],
                            recv_sem=recv_sems.at[h],
                            device_id=(right,), device_id_type=did)
                        rdma.start()
                        rdma.wait()   # send done AND my hop-h chunk arrived
                    # Fixed-order sum over origin slots: every replica
                    # reduces in the identical order -> bitwise-identical
                    # mean grads on all chips -> the resident weights stay
                    # in lockstep with no broadcast.
                    tot = comm[0]
                    for d in range(1, n):
                        tot = tot + comm[d]
                    g = tot * f32(1.0 / n)
                    gw1, gb1, gw2, gb2, gw3 = (
                        g[off:off + rows] for off, rows in _COMM_LAYOUT)

            ow1[:] -= lr_k * gw1
            ob1[:] -= lr_k * gb1
            ow2[:] -= lr_k * gw2
            ob2[:] -= lr_k * gb2
            ow3[:] -= lr_k * gw3

        loss_ref[:] = tile

    return kernel


def epoch_fused_sgd(params, xp, yp, seed, lr: float, batch: int, *,
                    masks=None, interpret: bool = False,
                    rng_impl: str = "core",
                    axis_name: str | None = None, axis_size: int = 1,
                    compute_bf16: bool = False, steps_per_iter: int = 1,
                    valid_steps: int | None = None, ring: str = "auto"):
    """One ENTIRE epoch as a single kernel (`--kernel pallas_epoch`):
    (params, xp (S*B, 784) pre-gathered epoch rows, yp (S*B,) int32,
    seed () int32, lr, batch=B) -> (params', losses (S,)).

    `xp` may be float32 (pre-normalized) or RAW uint8 pixels — uint8 streams
    a 4x smaller input through HBM/VMEM and is normalized in-kernel on the
    VPU (no f32 epoch array is ever materialized); the math is the same
    normalize chain, so results match the f32 path to float rounding.

    The caller flattens the epoch's sampler index rows (already wrap-padded
    to full batches) into xp/yp; grid step i trains on rows [i*B, (i+1)*B).
    Without `axis_size` the semantics are single-replica (a 1-device DP mesh
    is exactly this); `axis_size > 1` below adds the in-kernel DDP
    allreduce.

    `masks`: optional (S*B, HIDDEN1) pre-scaled dropout masks streamed per
    step INSTEAD of the in-kernel PRNG draw (`seed` is then unused). With
    masks the kernel contains no Mosaic-only ops, so `interpret=True` runs
    it on CPU — the CI path that covers this wrapper (loss detiling, batch
    validation, weight residency) without a TPU; `epoch_sgd_reference` is
    the matching pure-JAX oracle. The default (masks=None) draws in-kernel
    from the core PRNG and is Mosaic-only.

    `rng_impl='threefry'` (masks=None): dropout is drawn IN-kernel by jax's
    threefry2x32 on the VPU — `seed` is then an (S, 2) int32 array of
    per-step key words, and the masks are bit-for-bit
    `dropout_mask(step_key)`, i.e. the REFERENCE RNG semantics
    (models/mlp.py's bernoulli stream) at epoch-kernel speed instead of the
    mask-streaming per-step kernels. Pure jnp ops: this mode composes with
    `interpret=True`, so CI covers the whole path on CPU (the core-PRNG
    mode cannot).

    `axis_size > 1` (with `axis_name`; must be called inside shard_map over
    that axis): the DDP epoch kernel — batch/xp/yp/masks are this REPLICA's
    shard, and each step's SGD applies the cross-replica mean gradient via
    the in-kernel ICI ring allreduce (see _make_epoch_kernel). The returned
    losses are this replica's shard-local per-step means (pmean them outside
    for the DDP-reported loss); the returned params are bitwise-identical on
    every replica. EXPERIMENTAL: CI-covered via the n=1 degenerate + named
    errors; the ring itself needs real multi-chip hardware to execute, which
    this session does not have.

    `ring` selects the allreduce strategy: 'allgather' (n full origin slots
    in VMEM, one fixed-order sum per replica — n <= EPOCH_KERNEL_MAX_DEVICES
    only), 'reduce_scatter' (2(n-1) chunk hops, VMEM and per-device ICI
    traffic near-constant in n — any ring size), or 'auto' (allgather up to
    the
    slot budget, reduce_scatter beyond it). Both keep the resident weights
    in bitwise lockstep across replicas; their float summation orders
    differ, so cross-strategy results may differ by rounding.

    `steps_per_iter=K` (K in {1,2,4,8}; single-replica only): K sequential
    SGD steps per grid iteration streaming one (K*B, ...) input block —
    same math, bit-for-bit (see _make_epoch_kernel); amortizes the fixed
    per-iteration cost. A step count not divisible by K is zero-padded to a
    whole iteration and the padded tail sub-steps are masked out (loss row
    0, lr 0). Hot-path callers should pad CHEAPLY at the index level
    instead (repeat gather indices to a multiple of K steps — the scan body
    does) and pass `valid_steps` = the true step count: the wrapper then
    skips its whole-array zero-concat fallback, masks the tail the same
    way, and returns exactly `valid_steps` losses."""
    rows, dim = xp.shape
    assert dim == IN_DIM
    f32 = jnp.float32
    block = batch
    if block % 8 != 0:
        raise ValueError(f"pallas_epoch needs a batch divisible by 8 (the "
                         f"f32 sublane tile); got {block}")
    if block > EPOCH_KERNEL_MAX_BATCH:
        raise ValueError(
            f"pallas_epoch streams each step's batch as ONE VMEM block; "
            f"batch {block} > {EPOCH_KERNEL_MAX_BATCH} exceeds its budget "
            f"(double-buffered (B,784) inputs + resident weights and "
            f"block-sized activations — the uint8 input is materialized as "
            f"f32 in VMEM after the in-kernel normalize, so raw-uint8 "
            f"epochs share the cap). "
            f"Use the gridded per-step kernel (--kernel pallas) instead")
    nsteps = rows // block
    assert nsteps * block == rows, (rows, block)
    if rng_impl not in ("core", "threefry"):
        raise ValueError(f"rng_impl must be 'core' (TPU hardware PRNG) or "
                         f"'threefry' (in-kernel reference RNG); got "
                         f"{rng_impl!r}")
    if masks is not None and rng_impl != "core":
        raise ValueError("pass either masks= (pre-drawn) or "
                         "rng_impl='threefry' (in-kernel draw), not both")
    rng = "masks" if masks is not None else rng_impl
    if rng == "core" and interpret is True:
        # (interpret=True is the PLAIN Pallas interpreter; a
        # pltpu.InterpretParams instance selects the TPU-semantics
        # simulator, which does model the core PRNG — and remote DMAs,
        # see below — so it deliberately passes this check.)
        raise ValueError("the core-PRNG epoch kernel has no interpreter "
                         "lowering; pass explicit `masks` or "
                         "rng_impl='threefry' to interpret")
    if rng == "threefry":
        seed = jnp.asarray(seed)
        if seed.ndim != 2 or seed.shape[1] != 2 or seed.dtype not in (
                jnp.int32, jnp.uint32):
            raise ValueError(
                f"rng_impl='threefry' takes per-step key words: seed must "
                f"be an (nsteps, 2) int32/uint32 array of "
                f"jax.random.key_data rows; got "
                f"{seed.shape if hasattr(seed, 'shape') else seed!r} "
                f"{seed.dtype if hasattr(seed, 'dtype') else ''}")
    dp = axis_size > 1
    if dp and axis_name is None:
        raise ValueError("epoch_fused_sgd: axis_size > 1 needs axis_name "
                         "(the shard_map mesh axis of the DP ring)")
    if dp and interpret is True:
        # The PLAIN Pallas interpreter has no lowering for remote DMAs /
        # cross-chip semaphores. A pltpu.InterpretParams instance passes:
        # the TPU-semantics simulator models both, and CI executes the
        # real DP ring kernel under it (tests/test_pallas_step.py).
        # Caveat (the diagnosed round-4 "hang"): the simulator blocks one
        # host worker thread per live kernel, and the ring's entry
        # barrier needs ALL replicas' kernels live at once — when the
        # ring occupies EVERY device of the host pool there is no worker
        # left for the simulator's coordination and the run deadlocks at
        # ~0% CPU (measured: an 8-device ring starves an 8-device pool;
        # n<=7 of 8 executes, and 8 of a 9-device pool executes).
        # Workaround: provision ONE SPARE host device beyond the mesh
        # (xla_force_host_platform_device_count = mesh + 1), as
        # __graft_entry__.dryrun_multichip and the 8-replica simulator
        # test do.
        raise ValueError(
            "the DP epoch kernel's ICI ring allreduce (remote DMAs + "
            "cross-chip semaphores) has no plain-interpreter lowering; "
            "pass interpret=pltpu.InterpretParams() (the TPU-semantics "
            "simulator) or use kernel='pallas' for interpreted DP")
    if ring not in ("auto", "allgather", "reduce_scatter"):
        raise ValueError(f"ring must be 'auto', 'allgather' or "
                         f"'reduce_scatter'; got {ring!r}")
    if not dp and ring != "auto":
        raise ValueError(
            f"ring={ring!r} selects the DP ring allreduce strategy, but "
            f"axis_size={axis_size} runs the serial kernel (no ring) — a "
            f"forced strategy here would silently measure the wrong "
            f"program; drop ring or pass axis_size/axis_name")
    if dp and ring == "auto":
        ring = ("allgather" if axis_size <= EPOCH_KERNEL_MAX_DEVICES
                else "reduce_scatter")
    if dp and ring == "allgather" and axis_size > EPOCH_KERNEL_MAX_DEVICES:
        raise ValueError(
            f"ring='allgather' keeps one {EPOCH_COMM_ROWS}x128 f32 comm "
            f"slot per replica in VMEM for the fixed-order ring sum; "
            f"{axis_size} replicas > {EPOCH_KERNEL_MAX_DEVICES} exceeds the "
            f"budget. Use ring='reduce_scatter' (constant VMEM; the 'auto' "
            f"default) on larger meshes")
    K = steps_per_iter
    if K not in (1, 2, 4, 8):
        raise ValueError(
            f"steps_per_iter must be 1, 2, 4 or 8 (the K sub-step loss rows "
            f"of a grid iteration must stay inside one 8-row loss tile); "
            f"got {K}")
    if dp and K != 1:
        raise ValueError(
            "steps_per_iter > 1 is single-replica only: the DP ring "
            "allreduce handshake is per grid iteration, not per sub-step. "
            "Use steps_per_iter=1 on DP meshes")
    if K * block > EPOCH_KERNEL_MAX_BATCH:
        raise ValueError(
            f"steps_per_iter={K} streams a ({K}*{block}, 784) input block "
            f"per grid iteration; {K * block} rows > "
            f"{EPOCH_KERNEL_MAX_BATCH} exceeds the VMEM stream budget")
    if valid_steps is None:
        valid_steps = nsteps
    elif not 0 < valid_steps <= nsteps:
        raise ValueError(
            f"valid_steps={valid_steps} must be in [1, {nsteps}] (the "
            f"number of steps present in xp)")
    grid_n = -(-nsteps // K)
    padded_steps = grid_n * K
    if rng == "threefry" and padded_steps > EPOCH_KERNEL_MAX_RNG_STEPS:
        raise ValueError(
            f"rng_impl='threefry' keeps the whole (padded_steps, 2) int32 "
            f"per-step key table SMEM-resident; {padded_steps} steps "
            f"({padded_steps * 8} bytes) > {EPOCH_KERNEL_MAX_RNG_STEPS} "
            f"exceeds the SMEM key-table budget "
            f"({EPOCH_KERNEL_MAX_RNG_STEPS * 8 // 1024} KB). Split the run "
            f"into shorter epochs, or use rng_impl='core' (one SMEM seed "
            f"scalar) / pre-drawn masks")
    pad_steps = padded_steps - nsteps
    if pad_steps:
        # Fallback for direct ragged callers: zero-pad the tail to a whole
        # grid iteration; the kernel masks the padded sub-steps out (loss
        # row 0, lr 0 — zeros are finite inputs). This concatenates the
        # whole epoch arrays — hot paths pre-pad at the index level and
        # pass valid_steps instead (see docstring).
        zrows = pad_steps * block
        xp = jnp.concatenate(
            [xp, jnp.zeros((zrows, IN_DIM), xp.dtype)], axis=0)
        yp = jnp.concatenate([yp, jnp.zeros((zrows,), yp.dtype)], axis=0)
        if masks is not None:
            masks = jnp.concatenate(
                [masks, jnp.zeros((zrows, HIDDEN1), masks.dtype)], axis=0)
        if rng == "threefry":
            # zero key words for the padded tail sub-steps — their masks
            # are drawn but the update is lr=0-masked in the kernel
            seed = jnp.concatenate(
                [seed, jnp.zeros((pad_steps, 2), seed.dtype)], axis=0)
    uint8_in = xp.dtype == jnp.uint8
    vmem = partial(pl.BlockSpec, memory_space=pltpu.VMEM)
    resident = lambda shape: vmem(shape, lambda i: (0, 0))  # noqa: E731
    if rng == "threefry":
        if seed.shape[0] != padded_steps:
            raise ValueError(
                f"rng_impl='threefry' needs one key-word row per step: seed "
                f"has {seed.shape[0]} rows for {nsteps} steps")
        third = seed.astype(jnp.int32)
        # The WHOLE per-step key table rides resident in SMEM (padded_steps
        # x 2 int32 — ~4 KB for a real epoch) and the kernel indexes it by
        # global step. A per-iteration (K, 2) streamed block would violate
        # Mosaic's block-shape rule (second-to-minor dim must be divisible
        # by 8 or equal the array dim — K is 1..8 against S rows), which
        # the interpreter never checks: exactly the class of
        # hardware-only lowering error tests/test_export_lowering.py now
        # pins for every epoch-kernel variant.
        third_spec = pl.BlockSpec((padded_steps, 2), lambda i: (0, 0),
                                  memory_space=pltpu.SMEM)  # step key table
    elif rng == "core":
        third = jnp.asarray(seed, jnp.int32).reshape((1,))
        third_spec = pl.BlockSpec((1,), lambda i: (0,),
                                  memory_space=pltpu.SMEM)  # seed
    else:
        assert masks.shape == (xp.shape[0], HIDDEN1), masks.shape
        third = masks.astype(f32)
        third_spec = vmem((K * block, HIDDEN1), lambda i: (i, 0))  # masks
    w_shapes = (
        jax.ShapeDtypeStruct((IN_DIM, HIDDEN1), f32),
        jax.ShapeDtypeStruct((1, HIDDEN1), f32),
        jax.ShapeDtypeStruct((HIDDEN1, HIDDEN2), f32),
        jax.ShapeDtypeStruct((1, HIDDEN2), f32),
        jax.ShapeDtypeStruct((HIDDEN2, PADDED_CLASSES), f32),
    )
    nblocks8 = -(-padded_steps // 8)
    out_shapes = (jax.ShapeDtypeStruct((nblocks8 * 8, 128), f32),) + w_shapes
    if dp and ring == "reduce_scatter":
        C = _rs_chunk_rows(axis_size)
        scratch_shapes = [
            pltpu.VMEM((axis_size * C, 128), f32),       # flat padded grads
            pltpu.VMEM((axis_size - 1, C, 128), f32),    # per-hop recv slots
            pltpu.SemaphoreType.DMA((2 * (axis_size - 1),)),  # send: RS+AG
            pltpu.SemaphoreType.DMA((2 * (axis_size - 1),)),  # recv: RS+AG
            pltpu.SemaphoreType.REGULAR,                 # left ready
            pltpu.SemaphoreType.REGULAR,                 # right ready
        ]
        compiler_params = tpu_compiler_params(
            dimension_semantics=("arbitrary",),
            collective_id=7, has_side_effects=True)
    elif dp:
        scratch_shapes = [
            pltpu.VMEM((axis_size, EPOCH_COMM_ROWS, 128), f32),  # ring slots
            pltpu.SemaphoreType.DMA((axis_size - 1,)),           # send, /hop
            pltpu.SemaphoreType.DMA((axis_size - 1,)),           # recv, /hop
            pltpu.SemaphoreType.REGULAR,                         # left ready
            pltpu.SemaphoreType.REGULAR,                         # right ready
        ]
        compiler_params = tpu_compiler_params(
            dimension_semantics=("arbitrary",),
            collective_id=7, has_side_effects=True)
    else:
        scratch_shapes = []
        compiler_params = tpu_compiler_params(
            dimension_semantics=("arbitrary",))  # steps are sequential
    loss, w1, b1, w2, b2, w3 = pl.pallas_call(
        _make_epoch_kernel(block, lr, rng=rng,
                           uint8_in=uint8_in, axis_name=axis_name,
                           n_devices=axis_size, compute_bf16=compute_bf16,
                           steps_per_iter=K,
                           nsteps_total=(valid_steps
                                         if padded_steps != valid_steps
                                         else None),
                           ring_rs=dp and ring == "reduce_scatter"),
        grid=(grid_n,),
        compiler_params=compiler_params,
        scratch_shapes=scratch_shapes,
        out_shape=out_shapes,
        in_specs=[
            vmem((K * block, IN_DIM), lambda i: (i, 0)),      # x block
            vmem((K * block, 1), lambda i: (i, 0)),           # y block
            third_spec,                                       # seed | masks
            resident((IN_DIM, HIDDEN1)),                      # w1 in
            resident((1, HIDDEN1)),
            resident((HIDDEN1, HIDDEN2)),
            resident((1, HIDDEN2)),
            resident((HIDDEN2, PADDED_CLASSES)),
        ],
        out_specs=(
            # iteration i's K loss rows live in tile (i*K)//8 (K divides 8)
            vmem((8, 128), lambda i: ((i * K) // 8, 0)),      # per-step loss
            resident((IN_DIM, HIDDEN1)),                      # w1 out
            resident((1, HIDDEN1)),
            resident((HIDDEN1, HIDDEN2)),
            resident((1, HIDDEN2)),
            resident((HIDDEN2, PADDED_CLASSES)),
        ),
        interpret=interpret,
    )(
        xp if uint8_in else xp.astype(f32),
        yp.astype(jnp.int32)[:, None],
        third,
        params["fc1"]["w"].astype(f32),
        params["fc1"]["b"].astype(f32)[None, :],
        params["fc2"]["w"].astype(f32),
        params["fc2"]["b"].astype(f32)[None, :],
        pad_fc3(params["fc3"]["w"].astype(f32)),
    )
    new_params = {
        "fc1": {"w": w1, "b": b1[0]},
        "fc2": {"w": w2, "b": b2[0]},
        "fc3": {"w": w3[:, :NUM_CLASSES]},
    }
    return new_params, loss[:valid_steps, 0]


def epoch_sgd_reference(params, xp, yp, masks, lr: float, batch: int,
                        compute_bf16: bool = False):
    """Pure-JAX oracle for the epoch kernel's step recurrence: same inputs
    as epoch_fused_sgd(masks=...), implemented as a lax.scan of
    value_and_grad steps (`compute_bf16` mirrors the kernel's bf16-operand
    matmuls via a custom vjp-free restatement below). Runs on any backend —
    CI asserts the (interpreted) masked kernel and the run_epochal wrapper
    against it, so the epoch path has coverage when the Mosaic-only tests
    skip. Matches the kernel to float-rounding (different op/reduction
    order), not bitwise."""
    from .loss import cross_entropy
    from .sgd import sgd_step

    rows = xp.shape[0]
    nsteps = rows // batch
    assert nsteps * batch == rows, (rows, batch)
    f32 = jnp.float32
    xs = xp.reshape(nsteps, batch, IN_DIM)
    ys = yp.reshape(nsteps, batch).astype(jnp.int32)
    ms = masks.reshape(nsteps, batch, HIDDEN1).astype(f32)

    def step(p, xym):
        xb, yb, mb = xym
        if xb.dtype == jnp.uint8:
            xb = xb.astype(f32)
            xb = xb / f32(255.0)
            xb = xb - f32(MNIST_MEAN)
            xb = xb / f32(MNIST_STD)
        else:
            xb = xb.astype(f32)

        if compute_bf16:
            loss, grads = step_reference_bf16(p, xb, yb, mb)
            return sgd_step(p, grads, lr), loss

        def loss_fn(pp):
            z1 = xb @ pp["fc1"]["w"] + pp["fc1"]["b"]
            d1 = jnp.maximum(z1, 0.0) * mb      # pre-scaled inverted dropout
            z2 = d1 @ pp["fc2"]["w"] + pp["fc2"]["b"]
            h2 = jnp.maximum(z2, 0.0)
            return cross_entropy(h2 @ pp["fc3"]["w"], yb)

        loss, grads = jax.value_and_grad(loss_fn)(p)
        return sgd_step(p, grads, lr), loss

    return jax.lax.scan(step, params, (xs, ys, ms))


def step_reference_bf16(params, xb, yb, mb):
    """Pure-JAX oracle of ONE bf16-matmul train step: explicit fwd/bwd
    restating the kernels' exact cast points — bf16 operands into every
    dot_general, f32 accumulation, f32 elementwise/grads (autodiff of a cast
    chain would not place the bwd casts where the hand-written kernels do).
    Shared by the epoch oracle above and the per-step kernel's CI tests.
    Returns (mean_loss, grads pytree)."""
    from .loss import cross_entropy

    f32 = jnp.float32
    mm_dt = jnp.bfloat16
    batch = xb.shape[0]

    def _mm(a, b, dims):
        return jax.lax.dot_general(a.astype(mm_dt), b.astype(mm_dt), dims,
                                   preferred_element_type=f32)

    fwd = (((1,), (0,)), ((), ()))
    w1, b1 = params["fc1"]["w"], params["fc1"]["b"]
    w2, b2 = params["fc2"]["w"], params["fc2"]["b"]
    w3 = params["fc3"]["w"]
    z1 = _mm(xb, w1, fwd) + b1
    h1 = jnp.maximum(z1, 0.0)
    d1 = h1 * mb
    z2 = _mm(d1, w2, fwd) + b2
    h2 = jnp.maximum(z2, 0.0)
    logits = _mm(h2, w3, fwd)
    loss = cross_entropy(logits, yb)
    oh = jax.nn.one_hot(yb, logits.shape[1], dtype=f32)
    dlogits = (jax.nn.softmax(logits, axis=1) - oh) / batch
    gw3 = _mm(h2, dlogits, (((0,), (0,)), ((), ())))
    dh2 = _mm(dlogits, w3, (((1,), (1,)), ((), ())))
    dz2 = dh2 * (z2 > 0.0).astype(f32)
    gw2 = _mm(d1, dz2, (((0,), (0,)), ((), ())))
    gb2 = dz2.sum(axis=0)
    dd1 = _mm(dz2, w2, (((1,), (1,)), ((), ())))
    dz1 = (dd1 * mb) * (z1 > 0.0).astype(f32)
    gw1 = _mm(xb, dz1, (((0,), (0,)), ((), ())))
    gb1 = dz1.sum(axis=0)
    return loss, {"fc1": {"w": gw1, "b": gb1},
                  "fc2": {"w": gw2, "b": gb2},
                  "fc3": {"w": gw3}}


def dropout_mask(key: jax.Array, batch: int, *, train: bool = True):
    """The pre-scaled mask the kernel consumes, drawn EXACTLY like
    models/mlp.py's dropout (same bernoulli stream for the same key), so the
    fused step reproduces the unfused step bit-for-bit in RNG."""
    keep = 1.0 - DROPOUT_RATE
    if not train:
        return jnp.ones((batch, HIDDEN1), jnp.float32)
    mask = jax.random.bernoulli(key, keep, (batch, HIDDEN1))
    return mask.astype(jnp.float32) / keep


def make_pallas_train_step(lr: float, *, interpret: bool = False,
                           dtype: str = "float32"):
    """Drop-in replacement for train.loop.make_train_step: one jitted
    (params, key, x, y) -> (params', key', loss) whose fwd+bwd is the fused
    kernel; the SGD update fuses into the surrounding jit. Same
    jax.random.split chain as the unfused step -> same dropout masks.
    dtype='bfloat16' selects the kernel's bf16-matmul mode (x cast here —
    the kernel keys its mode off the batch dtype)."""
    from .sgd import sgd_step

    compute_dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, key, x, y):
        key, sub = jax.random.split(key)
        mask = dropout_mask(sub, x.shape[0])
        loss, grads = fused_loss_and_grads(params, x.astype(compute_dt), y,
                                           mask, interpret=interpret)
        return sgd_step(params, grads, lr), key, loss

    return step


def make_pallas_dp_train_step(mesh, lr: float, *, interpret: bool = False,
                              dtype: str = "float32", comm: str = "pmean",
                              bf16_rounding: str = "nearest"):
    """SPMD data-parallel fused step over the 'dp' mesh — the
    parallel.ddp.make_dp_train_step shape (per-replica kernel, grads through
    the selected comm strategy) with the Pallas kernel as the local compute.
    dtype='bfloat16' as in make_pallas_train_step; `comm` as in
    parallel/collectives.py (pmean / sharded / bf16)."""
    from jax.sharding import PartitionSpec as P
    from ..compat import shard_map
    from ..parallel import collectives
    from ..parallel.mesh import DATA_AXIS
    from .sgd import sgd_step

    collectives.validate_comm(comm)
    collectives.validate_bf16_rounding(bf16_rounding, comm)
    if comm == "int8":
        # the int8 strategy threads error-feedback residual state through
        # the step carry; this fused-kernel step has the plain
        # (params, key, x, y) shape — keep the XLA step for int8
        raise ValueError(
            "comm='int8' carries error-feedback state the fused Pallas DP "
            "step does not thread; use kernel='xla' "
            "(parallel.ddp.make_dp_train_step) for the int8 strategy")
    compute_dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    n_dev = int(mesh.devices.size)

    def _shard_fn(params, sub, x, y):
        rkey = jax.random.fold_in(sub, jax.lax.axis_index(DATA_AXIS))
        mask = dropout_mask(rkey, x.shape[0])
        loss, grads = fused_loss_and_grads(params, x.astype(compute_dt), y,
                                           mask, interpret=interpret)
        loss = jax.lax.pmean(loss, DATA_AXIS)
        if comm == "pmean":
            grads = jax.lax.pmean(grads, DATA_AXIS)  # the DDP allreduce-mean
            return grads, loss
        rnd = (jax.random.fold_in(rkey, 7)
               if bf16_rounding == "stochastic" else None)
        params = collectives.apply_gradients(params, grads, lr, DATA_AXIS,
                                             comm, n_dev, rounding_key=rnd)
        return params, loss

    # check_vma=False: grads come out of the kernel, not an autodiff
    # transpose, so shard_map's replication tracking (the reason ddp.py
    # needs _pvary) has nothing to protect here — and pallas_call's
    # out_shape structs carry no vma for it to check.
    sharded = shard_map(
        _shard_fn, mesh=mesh,
        in_specs=(P(), P(), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(), P()), check_vma=False)

    @partial(jax.jit, donate_argnums=(0, 1))
    def jitted(params, key, x, y):
        key, sub = jax.random.split(key)
        out, loss = sharded(params, sub, x, y)
        if comm == "pmean":
            return sgd_step(params, out, lr), key, loss
        return out, key, loss

    def step(params, key, x, y):
        return jitted(params, key, x, y)

    # same telemetry metadata contract as parallel.ddp.make_dp_train_step
    step.ddp_comm = comm
    step.ddp_mesh = mesh
    step.ddp_devices = n_dev
    step.comm_state = False
    return step
