from .loss import cross_entropy, accuracy
from .sgd import sgd_step

__all__ = ["cross_entropy", "accuracy", "sgd_step"]
