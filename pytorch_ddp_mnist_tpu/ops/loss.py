"""Loss and metric ops.

Parity target: torch.nn.CrossEntropyLoss() with default mean reduction, as the
reference uses (ddp_tutorial_multi_gpu.py:76,93) — logits in, integer class
targets in, softmax cross entropy averaged over the batch.

The reference never computes accuracy anywhere (SURVEY.md §5.5); `accuracy` is
the added capability BASELINE.md's acceptance targets require.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean softmax cross-entropy. logits (B, C) float, labels (B,) int."""
    logz = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(logz, labels[:, None].astype(jnp.int32), axis=-1)
    return -jnp.mean(picked)


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Fraction of argmax predictions matching labels."""
    pred = jnp.argmax(logits, axis=-1)
    return jnp.mean((pred == labels.astype(pred.dtype)).astype(jnp.float32))
