from .sampler import ShardedSampler
from .mesh import make_mesh, data_parallel_mesh

__all__ = ["ShardedSampler", "make_mesh", "data_parallel_mesh"]
