from .sampler import ShardedSampler
from .mesh import make_mesh, data_parallel_mesh
from .collectives import STRATEGIES as COMM_STRATEGIES

__all__ = ["ShardedSampler", "make_mesh", "data_parallel_mesh",
           "COMM_STRATEGIES"]
