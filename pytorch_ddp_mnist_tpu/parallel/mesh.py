"""Device-mesh construction for SPMD data parallelism (and beyond).

The reference's parallel topology is flat ranks over NCCL/Gloo
(ddp_tutorial_multi_gpu.py:133-134). The TPU-native analog is a
jax.sharding.Mesh: collectives are emitted by the SPMD partitioner and ride
ICI within a slice / DCN across slices, instead of a hand-driven process
group. The mesh is the single topology object the rest of the framework
consumes — samplers key off its size, train steps shard over its axes.

Topology-aware layout: on real hardware the physical order of devices
matters — XLA's ring allreduce wants neighbors in the mesh to be neighbors
on the ICI torus, and on multi-slice/multi-host jobs the slower-varying mesh
dimension must map to DCN (cross-host network) while faster-varying
dimensions stay on ICI. `jax.experimental.mesh_utils` owns that mapping
(`create_device_mesh` consults the TPU coordinates; `create_hybrid_device_mesh`
factors the mesh into a DCN outer product of per-slice ICI meshes), so we
delegate to it and keep the plain process-major reshape as the fallback for
backends mesh_utils cannot introspect.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np
import jax
from jax.sharding import Mesh


DATA_AXIS = "dp"


def _topology_device_array(axis_sizes, devices):
    """Physical-topology-aware device array via mesh_utils, or None.

    Single-granule jobs use `create_device_mesh` (ICI-coordinate ordering on
    TPU; identity order elsewhere). Jobs spanning multiple processes/slices
    factor each mesh axis as (DCN granules) x (devices per granule) so that
    the inter-granule hops land on the slowest-varying stride — SURVEY.md §7
    step 5's DCN-aware layout.
    """
    try:
        from jax.experimental import mesh_utils
    except ImportError:
        return None  # fall back to process-major reshape
    # The DCN granule must be the SAME unit create_hybrid_device_mesh groups
    # by: TPU runtimes set slice_index (all chips in one slice share an ICI
    # torus even across hosts, so a single-slice multi-host pod is NOT a
    # hybrid topology); backends without slice_index (CPU pods in the
    # multi-process tests) fall back to process granules.
    if hasattr(devices[0], "slice_index"):
        process_is_granule = False
        n_granules = len({d.slice_index for d in devices})
    else:
        process_is_granule = True
        n_granules = len({getattr(d, "process_index", 0) for d in devices})
    shape = tuple(axis_sizes)
    try:
        if n_granules > 1:
            # Factor the FIRST axis across granules: dp jobs shard data over
            # granules first (DCN), then within each granule's chips (ICI).
            if shape[0] % n_granules != 0:
                import warnings
                warnings.warn(
                    f"mesh axis 0 (size {shape[0]}) is not divisible by the "
                    f"{n_granules} DCN granules (slices/processes); falling "
                    f"back to process-major device order — ring collectives "
                    f"may take DCN-crossing hops", RuntimeWarning)
                return None
            dcn_shape = (n_granules,) + (1,) * (len(shape) - 1)
            ici_shape = (shape[0] // n_granules,) + shape[1:]
            return mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=devices,
                process_is_granule=process_is_granule,
                allow_split_physical_axes=True)
        return mesh_utils.create_device_mesh(shape, devices=devices,
                                             allow_split_physical_axes=True)
    except Exception as e:
        # A broken topology path must surface, not silently degrade to a
        # process-major mesh with DCN-crossing ring hops.
        import warnings
        warnings.warn(
            f"mesh_utils topology layout failed ({type(e).__name__}: {e}); "
            f"falling back to process-major device order", RuntimeWarning)
        return None


def make_mesh(axis_sizes: Sequence[int], axis_names: Sequence[str],
              devices=None) -> Mesh:
    """Build a Mesh of the given logical shape over `devices`.

    Devices default to all addressable devices; the array layout is chosen by
    mesh_utils when the backend exposes a physical topology (TPU ICI
    coordinates, multi-host process granules), falling back to process-major
    order (jax.devices()) — where the leading axis still maps hosts -> DCN
    and trailing axes -> ICI, the layout XLA's collectives want.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    n = int(np.prod(axis_sizes))
    if n != len(devices):
        raise ValueError(
            f"mesh shape {tuple(axis_sizes)} wants {n} devices, have {len(devices)}")
    dev_array = _topology_device_array(axis_sizes, devices)
    if dev_array is None:
        dev_array = np.asarray(devices).reshape(tuple(axis_sizes))
    return Mesh(dev_array, tuple(axis_names))


def data_parallel_mesh(devices=None) -> Mesh:
    """1-D mesh over every device, axis 'dp' — the DDP-analog topology."""
    devices = list(jax.devices()) if devices is None else list(devices)
    return make_mesh([len(devices)], [DATA_AXIS], devices)
