"""Device-mesh construction for SPMD data parallelism (and beyond).

The reference's parallel topology is flat ranks over NCCL/Gloo
(ddp_tutorial_multi_gpu.py:133-134). The TPU-native analog is a
jax.sharding.Mesh: collectives are emitted by the SPMD partitioner and ride
ICI within a slice / DCN across slices, instead of a hand-driven process
group. The mesh is the single topology object the rest of the framework
consumes — samplers key off its size, train steps shard over its axes.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np
import jax
from jax.sharding import Mesh


DATA_AXIS = "dp"


def make_mesh(axis_sizes: Sequence[int], axis_names: Sequence[str],
              devices=None) -> Mesh:
    """Build a Mesh of the given logical shape over `devices`.

    Devices default to all addressable devices in process-major order
    (jax.devices()), so on multi-host pods the leading axis naturally maps
    hosts -> DCN and trailing axes -> ICI, the layout XLA's collectives want.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    n = int(np.prod(axis_sizes))
    if n != len(devices):
        raise ValueError(
            f"mesh shape {tuple(axis_sizes)} wants {n} devices, have {len(devices)}")
    dev_array = np.asarray(devices).reshape(tuple(axis_sizes))
    return Mesh(dev_array, tuple(axis_names))


def data_parallel_mesh(devices=None) -> Mesh:
    """1-D mesh over every device, axis 'dp' — the DDP-analog topology."""
    devices = list(jax.devices()) if devices is None else list(devices)
    return make_mesh([len(devices)], [DATA_AXIS], devices)
