"""Gradient-communication strategies for the DDP step — one interface,
three selectable programs.

The reference's DDP step (ddp_tutorial_multi_gpu.py:94) allreduce-means the
full float32 gradient every step and then runs the SGD update REDUNDANTLY on
every rank. That shape is the baseline here (`pmean`), and two measured
alternatives sit behind the same switch:

  * `pmean`    — the naive baseline: one full-gradient f32
    `jax.lax.pmean`, replicated SGD update on every device. Exact DDP
    semantics; the bitwise anchor every other strategy is pinned against.
  * `sharded`  — the reduce-scatter → sharded-update → all-gather pattern
    of "Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
    Training" (arXiv:2004.13336, PAPERS.md): gradients are flattened into
    device-count-aligned buckets, each bucket is `psum_scatter`ed so every
    device owns 1/N of the mean gradient, the SGD update runs ONLY on that
    shard (`ops.sgd.sgd_step_flat` — update FLOPs and HBM traffic cut by
    1/N), and the fresh params are `all_gather`ed back. Same reduction
    tree as an allreduce, so parity with `pmean` holds to f32
    reduction-order tolerance (pinned at rtol 1e-6 by test).
  * `bf16`     — compressed allreduce in the EQuARX spirit
    (arXiv:2506.17615): gradients are cast to bfloat16 before the reduce,
    so the wire carries HALF the bytes AND the allreduce sums in bf16;
    the mean, SGD update, and master params stay float32. Optional
    stochastic rounding of the cast (`stochastic_round_bf16`,
    `bf16_rounding="stochastic"` / CLI `--bf16_rounding`) de-biases the
    quantization. Numeric drift vs `pmean` is bounded and pinned by test
    (note the bf16 REDUCTION error grows with device count — re-pin the
    bound before leaning on it past ~dozens of replicas).

All three run inside a `shard_map` body over the 'dp' axis; `parallel/ddp.py`
and `train/scan.py` select them via `comm=` / the CLI's `--ddp_comm`, and
`bench.py --mode ddp` measures all three on the same mesh.

Wire-byte accounting (`bytes_on_wire`) uses the ring-collective cost model:
per device per step, a ring allreduce of M bytes moves 2*(N-1)/N*M, a
reduce-scatter or all-gather moves (N-1)/N*M. Under that model `sharded`
moves the same bytes as `pmean` (RS grads + AG params == allreduce) — its
win is the 1/N update and HBM traffic, plus near-halved bytes wherever XLA
lowers small allreduces as all-gather + local reduce — while `bf16` halves
the wire outright. docs/PERF.md §DDP gradient communication carries the
worked numbers for the 118,272-param MLP.
"""

from __future__ import annotations

import time

import numpy as np
import jax
import jax.numpy as jnp

from ..ops.sgd import sgd_step, sgd_step_flat

STRATEGIES = ("pmean", "sharded", "bf16")

# Bucket granularity for the sharded-update flatten: leaves are packed
# greedily into buckets of at most this many elements (16 MiB of f32 —
# the torch-DDP 25 MB bucket idea, sized down for TPU-core VMEM comfort).
# The 118k-param MLP packs into ONE bucket; the knob exists so the
# machinery is general and the multi-bucket path stays testable.
DEFAULT_BUCKET_ELEMS = 4 * 1024 * 1024


def validate_comm(comm: str) -> None:
    """Reject unknown strategies by name — the single source of truth the
    CLI, bench, and step builders all funnel through."""
    if comm not in STRATEGIES:
        raise ValueError(f"unknown DDP comm strategy {comm!r}; "
                         f"choose one of {STRATEGIES}")


def validate_bf16_rounding(bf16_rounding: str, comm: str) -> None:
    """The bf16 strategy's rounding mode knob: 'nearest' (default — the
    plain round-to-nearest-even cast) or 'stochastic'
    (stochastic_round_bf16, unbiased in expectation). Rejected by name on
    any other strategy rather than silently ignored (the unroll lesson)."""
    if bf16_rounding not in ("nearest", "stochastic"):
        raise ValueError(f"bf16_rounding must be 'nearest' or 'stochastic';"
                         f" got {bf16_rounding!r}")
    if bf16_rounding == "stochastic" and comm != "bf16":
        raise ValueError(
            f"bf16_rounding='stochastic' rounds the bf16 strategy's wire "
            f"cast; comm={comm!r} never casts — use comm='bf16'")


def _leaf_buckets(leaves, bucket_elems: int):
    """Greedy static partition of leaf INDICES into buckets of at most
    `bucket_elems` elements (a leaf larger than the budget gets its own
    bucket). Pure host math over static shapes — identical on every
    device, so the bucketization itself never needs communication."""
    buckets, cur = [[]], 0
    for i, leaf in enumerate(leaves):
        size = int(np.prod(leaf.shape)) if leaf.shape else 1
        if buckets[-1] and cur + size > bucket_elems:
            buckets.append([])
            cur = 0
        buckets[-1].append(i)
        cur += size
    return buckets


def padded_size(n: int, n_devices: int) -> int:
    """`n` rounded up to a multiple of `n_devices` (the reduce-scatter
    alignment pad)."""
    return -(-n // n_devices) * n_devices


def bytes_on_wire(params_or_count, n_devices: int, comm: str, *,
                  bucket_elems: int = DEFAULT_BUCKET_ELEMS) -> int:
    """Analytic per-device per-step wire bytes under the ring-collective
    cost model (module docstring). `params_or_count` is the params pytree
    (bucket padding is then exact) or a plain element count.

    1-device meshes communicate nothing (the pmean is the identity)."""
    validate_comm(comm)
    n = int(n_devices)
    if n <= 1:
        return 0
    if isinstance(params_or_count, (int, np.integer)):
        n_params = int(params_or_count)
        padded = padded_size(n_params, n)
    else:
        leaves = jax.tree_util.tree_leaves(params_or_count)
        n_params = sum(int(np.prod(l.shape)) if l.shape else 1
                       for l in leaves)
        padded = sum(padded_size(
            sum(int(np.prod(leaves[i].shape)) if leaves[i].shape else 1
                for i in bucket), n)
            for bucket in _leaf_buckets(leaves, bucket_elems))
    ring = (n - 1) / n
    if comm == "pmean":
        return int(2 * ring * 4 * n_params)        # f32 allreduce
    if comm == "sharded":
        # RS of grads + AG of params, both over the padded buckets.
        return int(2 * ring * 4 * padded)
    return int(2 * ring * 2 * n_params)            # bf16 allreduce


def stochastic_round_bf16(key: jax.Array, x: jax.Array) -> jax.Array:
    """Stochastically round an f32 array to bfloat16: add uniform random
    bits below the bf16 mantissa cut, then truncate. Unbiased in
    expectation (E[round(x)] == x), unlike round-to-nearest-even which
    systematically loses sub-ulp gradient mass — the EQuARX de-biasing
    trick, exposed for the `bf16` strategy's opt-in rounding mode."""
    bits = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    noise = jax.random.bits(key, x.shape, jnp.uint32) & jnp.uint32(0xFFFF)
    rounded = (bits + noise) & jnp.uint32(0xFFFF0000)
    return jax.lax.bitcast_convert_type(rounded, jnp.float32).astype(
        jnp.bfloat16)


def bf16_allreduce_mean(grads, axis_name: str, n_devices: int, *,
                        rounding_key: jax.Array | None = None):
    """Compressed allreduce-mean: cast each gradient leaf to bf16 (the wire
    carries 2 bytes/element; the `psum` itself also reduces in bf16 — that
    is where the wire saving comes from), then take the mean in FLOAT32 so
    the SGD update and master params stay full precision. `rounding_key`
    opts into stochastic rounding of the cast (one subkey per leaf)."""
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if rounding_key is not None:
        keys = jax.random.split(rounding_key, len(leaves))
        cast = [stochastic_round_bf16(k, g) for k, g in zip(keys, leaves)]
    else:
        cast = [g.astype(jnp.bfloat16) for g in leaves]
    reduced = [jax.lax.psum(g, axis_name).astype(jnp.float32) / n_devices
               for g in cast]
    return jax.tree_util.tree_unflatten(treedef, reduced)


def sharded_update(params, grads, lr: float, axis_name: str,
                   n_devices: int, *,
                   bucket_elems: int = DEFAULT_BUCKET_ELEMS):
    """reduce-scatter → sharded SGD → all-gather, per bucket (the
    arXiv:2004.13336 pattern; module docstring).

    Must run inside a shard_map body over `axis_name` with per-device
    (device-varying) `grads` and replicated `params`; returns the fresh
    params, identical on every device (the all-gather re-replicates)."""
    p_leaves, treedef = jax.tree_util.tree_flatten(params)
    g_leaves = jax.tree_util.tree_leaves(grads)
    assert len(p_leaves) == len(g_leaves), "params/grads tree mismatch"
    me = jax.lax.axis_index(axis_name)
    new_leaves: list = [None] * len(p_leaves)
    for bucket in _leaf_buckets(p_leaves, bucket_elems):
        flat_g = jnp.concatenate(
            [g_leaves[i].reshape(-1).astype(jnp.float32) for i in bucket])
        flat_p = jnp.concatenate([p_leaves[i].reshape(-1) for i in bucket])
        n = flat_p.size
        shard = padded_size(n, n_devices) // n_devices
        pad = shard * n_devices - n
        if pad:
            flat_g = jnp.concatenate([flat_g, jnp.zeros(pad, flat_g.dtype)])
            flat_p = jnp.concatenate([flat_p, jnp.zeros(pad, flat_p.dtype)])
        # Each device leaves the reduce-scatter owning 1/N of the SUM;
        # the /N makes it the DDP mean. The update then touches only this
        # device's shard — 1/N of the FLOPs and HBM traffic of the
        # redundant replicated update.
        g_shard = jax.lax.psum_scatter(
            flat_g, axis_name, scatter_dimension=0, tiled=True) / n_devices
        p_shard = jax.lax.dynamic_slice(flat_p, (me * shard,), (shard,))
        fresh = sgd_step_flat(p_shard, g_shard, lr)
        flat_new = jax.lax.all_gather(fresh, axis_name, tiled=True)
        off = 0
        for i in bucket:
            size = p_leaves[i].size
            new_leaves[i] = flat_new[off:off + size].reshape(
                p_leaves[i].shape)
            off += size
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def apply_gradients(params, grads, lr: float, axis_name: str, comm: str,
                    n_devices: int, *,
                    rounding_key: jax.Array | None = None,
                    bucket_elems: int = DEFAULT_BUCKET_ELEMS):
    """The one entry point: local per-device `grads` in, fresh replicated
    params out, via the selected communication strategy. Runs inside a
    shard_map body over `axis_name`."""
    validate_comm(comm)
    if comm == "sharded":
        return sharded_update(params, grads, lr, axis_name, n_devices,
                              bucket_elems=bucket_elems)
    if comm == "bf16":
        mean = bf16_allreduce_mean(grads, axis_name, n_devices,
                                   rounding_key=rounding_key)
    else:
        mean = jax.lax.pmean(grads, axis_name)
    return sgd_step(params, mean, lr)


# ---------------------------------------------------------------------------
# The comm probe: an isolated, timeable program of JUST the gradient
# communication a strategy performs. The in-step collective overlaps with
# compute inside one XLA program and is not host-observable without the
# profiler; the probe runs the same collective pattern on a params-shaped
# tree so `ddp.collective_s` reports an honest isolated comms cost.
# ---------------------------------------------------------------------------


def make_comm_probe(mesh, comm: str):
    """Jitted (params-shaped tree) -> reduced tree program of the
    strategy's communication pattern over `mesh`'s 'dp' axis."""
    from jax.sharding import PartitionSpec as P
    from ..compat import shard_map
    from .mesh import DATA_AXIS
    validate_comm(comm)
    n_dev = int(mesh.devices.size)

    def body(tree):
        if comm == "sharded":
            # RS + sharded touch + AG — the sharded strategy's wire pattern
            # (the O(1/N) update itself is deliberately included: it is
            # negligible by construction, which the probe demonstrates).
            return sharded_update(tree, tree, 0.0, DATA_AXIS, n_dev)
        if comm == "bf16":
            return bf16_allreduce_mean(tree, DATA_AXIS, n_dev)
        return jax.lax.pmean(tree, DATA_AXIS)

    sharded_body = shard_map(body, mesh=mesh, in_specs=(P(),),
                             out_specs=P(), check_vma=False)
    return jax.jit(sharded_body)


def measure_collective_seconds(probe, params, reps: int = 3) -> list:
    """Run a `make_comm_probe` program `reps` times and return per-rep
    wall seconds (each rep blocked to completion). The first call compiles;
    callers warm the probe once before timing — this helper does that
    itself, so the returned list holds steady-state reps only."""
    jax.block_until_ready(probe(params))      # compile + warm
    out = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(probe(params))
        out.append(time.perf_counter() - t0)
    return out
