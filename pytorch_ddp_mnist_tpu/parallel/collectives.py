"""Gradient-communication strategies for the DDP step — one interface,
four selectable programs, optionally bucket-pipelined.

The reference's DDP step (ddp_tutorial_multi_gpu.py:94) allreduce-means the
full float32 gradient every step and then runs the SGD update REDUNDANTLY on
every rank. That shape is the baseline here (`pmean`), and three measured
alternatives sit behind the same switch:

  * `pmean`    — the naive baseline: one full-gradient f32
    `jax.lax.pmean`, replicated SGD update on every device. Exact DDP
    semantics; the bitwise anchor every other strategy is pinned against.
  * `sharded`  — the reduce-scatter → sharded-update → all-gather pattern
    of "Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
    Training" (arXiv:2004.13336, PAPERS.md): gradients are flattened into
    device-count-aligned buckets, each bucket is `psum_scatter`ed so every
    device owns 1/N of the mean gradient, the SGD update runs ONLY on that
    shard (`ops.sgd.sgd_step_flat` — update FLOPs and HBM traffic cut by
    1/N), and the fresh params are `all_gather`ed back. Same reduction
    tree as an allreduce, so parity with `pmean` holds to f32
    reduction-order tolerance (pinned at rtol 1e-6 by test).
  * `bf16`     — compressed allreduce in the EQuARX spirit
    (arXiv:2506.17615): gradients are cast to bfloat16 before the reduce,
    so the wire carries HALF the bytes AND the allreduce sums in bf16;
    the mean, SGD update, and master params stay float32. Optional
    stochastic rounding of the cast (`stochastic_round_bf16`,
    `bf16_rounding="stochastic"` / CLI `--bf16_rounding`) de-biases the
    quantization. Numeric drift vs `pmean` is bounded and pinned by test
    (note the bf16 REDUCTION error grows with device count — re-pin the
    bound before leaning on it past ~dozens of replicas).
  * `int8`     — block-scaled int8 quantized allreduce with per-device
    ERROR-FEEDBACK residuals (EQuARX proper, arXiv:2506.17615): each
    device adds last step's quantization error back into its local
    gradient, quantizes per `quant_block`-element block (int8 values + one
    f32 scale per block, ~1/4 the f32 bytes), and the quantization rides
    BOTH collective phases — an all_to_all reduce-scatter of the quantized
    payload, a local f32 dequant-sum, then a re-quantized all_gather of
    the mean shard — so the wire never carries f32. Every device applies
    the same dequantized mean (params stay replicated); the local quant
    error AND each device's own mean-shard quant error accumulate into
    the residual, which the step carry threads to the next step
    (`carries_state` / `int8_apply_gradients`). Drift vs `pmean` is
    bounded and pinned by test; with error feedback the quantization bias
    cancels across steps instead of compounding.

All four run inside a `shard_map` body over the 'dp' axis; `parallel/ddp.py`
and `train/scan.py` select them via `comm=` / the CLI's `--ddp_comm`, and
`bench.py --mode ddp` measures them on the same mesh.

`overlap=True` additionally BUCKET-PIPELINES the pmean/bf16 strategies
(arXiv:1711.00705's overlap design, the torch-DDP bucket idea): instead of
one whole-tree collective that cannot start until every gradient leaf
exists, the leaves are packed into `bucket_elems` buckets and each bucket
gets its OWN collective whose only data dependency is that bucket's
gradients — XLA's latency-hiding scheduler is then free to run bucket k's
collective while bucket j's backward matmuls still execute, instead of
serializing all comm behind all compute. `sharded` and `int8` are
bucket-structured by construction, so `overlap=True` composes with them as
the identity. `pmean` with `overlap=False` stays the UNTOUCHED exact-DDP
baseline program (the bitwise anchor).

Wire-byte accounting (`bytes_on_wire`) uses the ring-collective cost model:
per device per step, a ring allreduce of M bytes moves 2*(N-1)/N*M, a
reduce-scatter or all-gather moves (N-1)/N*M. Under that model `sharded`
moves the same bytes as `pmean` (RS grads + AG params == allreduce) — its
win is the 1/N update and HBM traffic, plus near-halved bytes wherever XLA
lowers small allreduces as all-gather + local reduce — `bf16` halves the
wire outright, and `int8` cuts it to (1 + 4/quant_block)/4 of f32 (~25% at
the default 256 block: 1 byte/element + one f32 scale per block, both
phases quantized). docs/PERF.md §DDP gradient communication carries the
worked numbers per model size.
"""

from __future__ import annotations

import time

import numpy as np
import jax
import jax.numpy as jnp

from ..ops.sgd import sgd_step, sgd_step_flat

STRATEGIES = ("pmean", "sharded", "bf16", "int8")

# Bucket granularity for the sharded-update flatten: leaves are packed
# greedily into buckets of at most this many elements (16 MiB of f32 —
# the torch-DDP 25 MB bucket idea, sized down for TPU-core VMEM comfort).
# The 118k-param MLP packs into ONE bucket; the knob exists so the
# machinery is general and the multi-bucket path stays testable.
DEFAULT_BUCKET_ELEMS = 4 * 1024 * 1024

# int8 scaling-block granularity: one f32 scale per this many elements
# (EQuARX's block scaling — small enough that one outlier gradient can't
# flatten a whole tensor's resolution, large enough that the scale
# overhead stays 4/256 ≈ 1.6% of the wire).
QUANT_BLOCK = 256


def validate_comm(comm: str) -> None:
    """Reject unknown strategies by name — the single source of truth the
    CLI, bench, and step builders all funnel through."""
    if comm not in STRATEGIES:
        raise ValueError(f"unknown DDP comm strategy {comm!r}; "
                         f"choose one of {STRATEGIES}")


def step_cost_label(comm: str, overlap: bool = False,
                    form: str = "step") -> str:
    """The ONE naming convention for a DDP program in the forensics layer:
    `ddp.<form>.<comm>[+overlap]`. Shared by `parallel/ddp.py` (every
    built step carries it as `.cost_label`), `telemetry/costs.py` (cost
    records and compile attribution key on it), and the OOM forensics
    dump — one function so the label a crash names is the label the cost
    table holds."""
    validate_comm(comm)
    return f"ddp.{form}.{comm}" + ("+overlap" if overlap else "")


def validate_bf16_rounding(bf16_rounding: str, comm: str) -> None:
    """The bf16 strategy's rounding mode knob: 'nearest' (default — the
    plain round-to-nearest-even cast) or 'stochastic'
    (stochastic_round_bf16, unbiased in expectation). Rejected by name on
    any other strategy rather than silently ignored (the unroll lesson)."""
    if bf16_rounding not in ("nearest", "stochastic"):
        raise ValueError(f"bf16_rounding must be 'nearest' or 'stochastic';"
                         f" got {bf16_rounding!r}")
    if bf16_rounding == "stochastic" and comm != "bf16":
        raise ValueError(
            f"bf16_rounding='stochastic' rounds the bf16 strategy's wire "
            f"cast; comm={comm!r} never casts — use comm='bf16'")


def validate_int8_options(quant_block: "int | None", error_feedback: bool,
                          comm: str) -> None:
    """The int8 strategy's knobs, rejected BY NAME on any other strategy
    rather than silently ignored (the unroll lesson, mirror of
    `validate_bf16_rounding`): `quant_block` sizes the scaling blocks,
    `error_feedback` carries the quantization residuals in the step
    state. `quant_block=None` is the "unset" sentinel every caller
    resolves to QUANT_BLOCK — valid on every strategy, so retuning
    QUANT_BLOCK can never make default invocations start failing."""
    if quant_block is not None and (
            not isinstance(quant_block, (int, np.integer))
            or quant_block < 8):
        raise ValueError(
            f"quant_block must be an int >= 8 (one f32 scale per block); "
            f"got {quant_block!r}")
    if comm != "int8":
        if quant_block is not None and int(quant_block) != QUANT_BLOCK:
            raise ValueError(
                f"quant_block={quant_block} sizes the int8 strategy's "
                f"scaling blocks; comm={comm!r} never quantizes to int8 — "
                f"use comm='int8'")
        if error_feedback is not True:
            raise ValueError(
                f"error_feedback={error_feedback!r} carries the int8 "
                f"strategy's quantization residuals; comm={comm!r} has no "
                f"quantization error to feed back — use comm='int8'")


def carries_state(comm: str, error_feedback: bool = True) -> bool:
    """Whether the strategy threads per-device error-feedback state through
    the step carry — the one arity question every caller (step builders,
    train loops, checkpointing, bench) funnels through."""
    return comm == "int8" and bool(error_feedback)


def _leaf_buckets(leaves, bucket_elems: int):
    """Greedy static partition of leaf INDICES into buckets of at most
    `bucket_elems` elements (a leaf larger than the budget gets its own
    bucket). Pure host math over static shapes — identical on every
    device, so the bucketization itself never needs communication."""
    buckets, cur = [[]], 0
    for i, leaf in enumerate(leaves):
        size = int(np.prod(leaf.shape)) if leaf.shape else 1
        if buckets[-1] and cur + size > bucket_elems:
            buckets.append([])
            cur = 0
        buckets[-1].append(i)
        cur += size
    return buckets


def padded_size(n: int, n_devices: int) -> int:
    """`n` rounded up to a multiple of `n_devices` (the reduce-scatter
    alignment pad)."""
    return -(-n // n_devices) * n_devices


def _leaf_size(leaf) -> int:
    return int(np.prod(leaf.shape)) if leaf.shape else 1


def _count_leaf(n: int) -> np.ndarray:
    """Shape-only stand-in for a flat leaf of `n` elements — the bucket
    layout math reads nothing but `.shape`, so a stride-0 broadcast view
    serves without materializing n floats."""
    return np.broadcast_to(np.float32(0), (int(n),))


def _bucket_layout(leaves, bucket_elems: int, align: int):
    """[(leaf_indices, n_real, padded)] per bucket: the greedy
    `_leaf_buckets` partition with each bucket's element count rounded up
    to a multiple of `align`. Pure host math over static shapes. `align`
    encodes the strategy's constraint: 1 for the flat pmean/bf16 bucket
    collectives (no alignment needed), n_devices for the reduce-scatter
    shards, n_devices*quant_block for int8 (every device's shard must hold
    whole scaling blocks)."""
    out = []
    for bucket in _leaf_buckets(leaves, bucket_elems):
        n_real = sum(_leaf_size(leaves[i]) for i in bucket)
        out.append((bucket, n_real, padded_size(n_real, align)))
    return out


def comm_state_elems(params_or_count, n_devices: int, *,
                     bucket_elems: int = DEFAULT_BUCKET_ELEMS,
                     quant_block: int = QUANT_BLOCK) -> int:
    """Per-device length of the int8 error-feedback residual vector: the
    sum of the strategy's padded bucket sizes (each a multiple of
    n_devices*quant_block). The residual state is a (n_devices, this)
    float32 array, device-sharded on dim 0."""
    if isinstance(params_or_count, (int, np.integer)):
        leaves = [_count_leaf(int(params_or_count))]
    else:
        leaves = jax.tree_util.tree_leaves(params_or_count)
    return sum(padded for (_b, _n, padded) in
               _bucket_layout(leaves, bucket_elems,
                              int(n_devices) * int(quant_block)))


def comm_state_zeros(params, n_devices: int, *,
                     bucket_elems: int = DEFAULT_BUCKET_ELEMS,
                     quant_block: int = QUANT_BLOCK) -> np.ndarray:
    """Host-side zero-initialized error-feedback residual for a fresh run
    (a resumed run restores the checkpointed one instead)."""
    return np.zeros((int(n_devices),
                     comm_state_elems(params, n_devices,
                                      bucket_elems=bucket_elems,
                                      quant_block=quant_block)), np.float32)


def place_comm_state(mesh, params, host=None, *,
                     bucket_elems: int = DEFAULT_BUCKET_ELEMS,
                     quant_block: int = QUANT_BLOCK):
    """Device placement of the residual state: a (n_devices, elems) f32
    array sharded over the 'dp' axis (each device owns ITS residual — the
    quantization error is per-device local state, unlike the replicated
    params). `host=None` starts from zeros; a restored checkpoint passes
    its saved array (shape-checked by name — a mesh of a different size
    cannot silently reinterpret another world's residuals)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from .mesh import DATA_AXIS
    n = int(mesh.devices.size)
    if host is None:
        if params is None:
            raise ValueError("place_comm_state needs either a params tree "
                             "(to size a fresh zero state) or a restored "
                             "host array")
        host = comm_state_zeros(params, n, bucket_elems=bucket_elems,
                                quant_block=quant_block)
    else:
        host = np.asarray(host, np.float32)
        want_shape = (
            comm_state_zeros(params, n, bucket_elems=bucket_elems,
                             quant_block=quant_block).shape
            if params is not None else None)
        if ((want_shape is not None and host.shape != want_shape)
                or host.ndim != 2 or host.shape[0] != n):
            raise ValueError(
                f"error-feedback state of shape {host.shape} does not fit "
                f"this run (expected "
                f"{want_shape or ('(' + str(n) + ', elems)')} for {n} "
                f"device(s), quant_block={quant_block}) — it was saved "
                f"under a different mesh size or quantization geometry")
    s = NamedSharding(mesh, P(DATA_AXIS))
    return jax.make_array_from_callback(host.shape, s,
                                        lambda idx, _h=host: _h[idx])


def bytes_on_wire(params_or_count, n_devices: int, comm: str, *,
                  bucket_elems: int = DEFAULT_BUCKET_ELEMS,
                  quant_block: int = QUANT_BLOCK) -> int:
    """Analytic per-device per-step wire bytes under the ring-collective
    cost model (module docstring). `params_or_count` is the params pytree
    (bucket padding is then exact) or a plain element count.

    1-device meshes communicate nothing (the pmean is the identity)."""
    validate_comm(comm)
    n = int(n_devices)
    if n <= 1:
        return 0
    if isinstance(params_or_count, (int, np.integer)):
        n_params = int(params_or_count)
        leaves = [_count_leaf(n_params)]
    else:
        leaves = jax.tree_util.tree_leaves(params_or_count)
        n_params = sum(_leaf_size(l) for l in leaves)
    ring = (n - 1) / n
    if comm == "pmean":
        return int(2 * ring * 4 * n_params)        # f32 allreduce
    if comm == "sharded":
        # RS of grads + AG of params, both over the padded buckets.
        padded = sum(p for (_b, _n, p) in
                     _bucket_layout(leaves, bucket_elems, n))
        return int(2 * ring * 4 * padded)
    if comm == "int8":
        # Both phases carry the quantized format — 1 int8 byte/element +
        # one f32 scale per quant_block — over the int8-padded buckets:
        # all_to_all RS moves (N-1)/N of the local payload, the AG of the
        # re-quantized mean moves (N-1)/N of the same size again.
        padded = sum(p for (_b, _n, p) in
                     _bucket_layout(leaves, bucket_elems,
                                    n * int(quant_block)))
        payload = padded + 4 * (padded // int(quant_block))
        return int(2 * ring * payload)
    return int(2 * ring * 2 * n_params)            # bf16 allreduce


def collective_schedule(params_or_count, n_devices: int, comm: str, *,
                        overlap: bool = False,
                        bucket_elems: int = DEFAULT_BUCKET_ELEMS,
                        quant_block: int = QUANT_BLOCK) -> list:
    """The static half of the per-rank collective journal
    (telemetry/cluster.py): the ordered list of PAYLOAD collectives one
    step of this strategy issues, as dicts
    `{kind, dtype, axis, elems, bytes, bucket}` — kinds/counts/bytes from
    the SAME bucket math the strategies run, so the journal a rank writes
    is the program the auditor proved (the `journal-schedule` contract in
    statics/jaxpr_audit.py pins this list against the walked jaxpr,
    entry for entry).

    `bytes` is the ring-model per-device wire cost of that ONE collective
    (allreduce 2(N-1)/N*M, RS/A2A/AG (N-1)/N*M); the entries sum to
    `bytes_on_wire` exactly. Control-plane scalars (the loss pmean, the
    health aux vector) are excluded by the same rule the auditor applies
    (<= SMALL_ELEMS elements is not payload). 1-device meshes keep the
    schedule's SHAPE (seq numbering must not depend on world size) with
    zero bytes — the ring moves nothing."""
    from .mesh import DATA_AXIS
    validate_comm(comm)
    n = int(n_devices)
    ring = (n - 1) / n if n > 1 else 0.0
    if isinstance(params_or_count, (int, np.integer)):
        leaves = [_count_leaf(int(params_or_count))]
    else:
        leaves = jax.tree_util.tree_leaves(params_or_count)

    def entry(kind, dtype, elems, nbytes, bucket):
        return {"kind": kind, "dtype": dtype, "axis": DATA_AXIS,
                "elems": int(elems), "bytes": int(round(nbytes)),
                "bucket": int(bucket)}

    out = []
    if comm in ("pmean", "bf16"):
        itemsize = 4 if comm == "pmean" else 2
        dtype = "float32" if comm == "pmean" else "bfloat16"
        if not overlap:
            # one whole-leaf allreduce per parameter leaf
            for i, leaf in enumerate(leaves):
                elems = _leaf_size(leaf)
                out.append(entry("allreduce", dtype, elems,
                                 2 * ring * elems * itemsize, i))
        else:
            for b, (_bucket, _n_real, padded) in enumerate(
                    _bucket_layout(leaves, bucket_elems, 1)):
                out.append(entry("allreduce", dtype, padded,
                                 2 * ring * padded * itemsize, b))
    elif comm == "sharded":
        for b, (_bucket, _n_real, padded) in enumerate(
                _bucket_layout(leaves, bucket_elems, max(n, 1))):
            out.append(entry("reduce_scatter", "float32", padded,
                             ring * padded * 4, b))
            out.append(entry("all_gather", "float32", padded,
                             ring * padded * 4, b))
    else:  # int8: quantized payload + block scales ride BOTH phases
        qb = int(quant_block)
        for b, (_bucket, _n_real, padded) in enumerate(
                _bucket_layout(leaves, bucket_elems, max(n, 1) * qb)):
            blocks = padded // qb
            out.append(entry("all_to_all", "int8", padded,
                             ring * padded, b))
            out.append(entry("all_to_all", "float32", blocks,
                             ring * blocks * 4, b))
            out.append(entry("all_gather", "int8", padded,
                             ring * padded, b))
            out.append(entry("all_gather", "float32", blocks,
                             ring * blocks * 4, b))
    return out


def stochastic_round_bf16(key: jax.Array, x: jax.Array) -> jax.Array:
    """Stochastically round an f32 array to bfloat16: add uniform random
    bits below the bf16 mantissa cut, then truncate. Unbiased in
    expectation (E[round(x)] == x), unlike round-to-nearest-even which
    systematically loses sub-ulp gradient mass — the EQuARX de-biasing
    trick, exposed for the `bf16` strategy's opt-in rounding mode."""
    bits = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    noise = jax.random.bits(key, x.shape, jnp.uint32) & jnp.uint32(0xFFFF)
    rounded = (bits + noise) & jnp.uint32(0xFFFF0000)
    return jax.lax.bitcast_convert_type(rounded, jnp.float32).astype(
        jnp.bfloat16)


def bf16_allreduce_mean(grads, axis_name: str, n_devices: int, *,
                        rounding_key: jax.Array | None = None):
    """Compressed allreduce-mean: cast each gradient leaf to bf16 (the wire
    carries 2 bytes/element; the `psum` itself also reduces in bf16 — that
    is where the wire saving comes from), then take the mean in FLOAT32 so
    the SGD update and master params stay full precision. `rounding_key`
    opts into stochastic rounding of the cast (one subkey per leaf)."""
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if rounding_key is not None:
        keys = jax.random.split(rounding_key, len(leaves))
        cast = [stochastic_round_bf16(k, g) for k, g in zip(keys, leaves)]
    else:
        cast = [g.astype(jnp.bfloat16) for g in leaves]
    reduced = [jax.lax.psum(g, axis_name).astype(jnp.float32) / n_devices
               for g in cast]
    return jax.tree_util.tree_unflatten(treedef, reduced)


def quantize_block_int8(flat: jax.Array, quant_block: int):
    """Block-scaled int8 quantization of a flat f32 vector whose length is
    a multiple of `quant_block`: per block, scale = max|x| / 127 (f32) and
    q = round(x / scale) ∈ [-127, 127]. An all-zero block keeps scale 0
    (dequantizes to exact zeros). Returns (q int8 (n,), scales f32
    (n/quant_block,))."""
    blocks = flat.reshape(-1, quant_block)
    scale = jnp.max(jnp.abs(blocks), axis=1) / jnp.float32(127.0)
    safe = jnp.where(scale > 0, scale, jnp.float32(1.0))
    q = jnp.round(blocks / safe[:, None]).astype(jnp.int8)
    return q.reshape(-1), scale


def dequantize_block_int8(q: jax.Array, scale: jax.Array,
                          quant_block: int) -> jax.Array:
    """Inverse of `quantize_block_int8`: q * its block's scale, f32."""
    return (q.astype(jnp.float32).reshape(-1, quant_block)
            * scale[:, None]).reshape(-1)


def int8_allreduce_mean(flat_g: jax.Array, resid, axis_name: str,
                        n_devices: int, quant_block: int):
    """Block-scaled int8 quantized allreduce-mean of ONE padded flat
    gradient bucket, with optional error feedback. Must run inside a
    shard_map body over `axis_name`; `flat_g` is this device's local
    gradient (length a multiple of n_devices*quant_block), `resid` its
    carried residual slice of the same length (None = error feedback off).

    The quantization rides BOTH phases (the wire never carries f32):
      1. reduce-scatter via all_to_all of the int8 payload + block scales:
         each device receives every peer's quantized chunk for ITS shard
         and dequant-sums them in f32 — it now owns the exact-to-int8 mean
         of 1/N of the vector;
      2. the mean shard is RE-quantized (fresh scales) and all_gathered,
         so every device applies the identical dequantized mean (params
         stay replicated).

    Error feedback: the local quantization error (g_eff - dequant(q))
    lands in the residual everywhere, and each device additionally
    reclaims the phase-2 error of its OWN mean shard, scaled by
    n_devices — the residual re-enters next step's gradient MEAN, so an
    owner-held correction is diluted 1/N on the way back and must be
    pre-amplified for every element's mean-quantization error to be
    corrected in full by exactly one device.
    Returns (mean f32, new_resid | None)."""
    g_eff = flat_g + resid if resid is not None else flat_g
    q, s = quantize_block_int8(g_eff, quant_block)
    new_resid = (g_eff - dequantize_block_int8(q, s, quant_block)
                 if resid is not None else None)
    if n_devices == 1:
        # single device: the "mean" IS the dequantized local payload (both
        # collective phases are the identity; no second quantization)
        return dequantize_block_int8(q, s, quant_block), new_resid
    shard = flat_g.size // n_devices
    blocks_per_shard = shard // quant_block
    # phase 1: all_to_all reduce-scatter of the quantized payload — row j
    # of the result is device j's chunk for THIS device's shard
    qr = jax.lax.all_to_all(q.reshape(n_devices, shard), axis_name,
                            split_axis=0, concat_axis=0, tiled=True)
    sr = jax.lax.all_to_all(s.reshape(n_devices, blocks_per_shard),
                            axis_name, split_axis=0, concat_axis=0,
                            tiled=True)
    deq = (qr.astype(jnp.float32).reshape(n_devices, blocks_per_shard,
                                          quant_block)
           * sr[:, :, None])
    mean_shard = deq.sum(axis=0).reshape(-1) / n_devices
    # phase 2: re-quantize the mean shard and all_gather it
    qm, sm = quantize_block_int8(mean_shard, quant_block)
    if new_resid is not None:
        me = jax.lax.axis_index(axis_name)
        err = mean_shard - dequantize_block_int8(qm, sm, quant_block)
        cur = jax.lax.dynamic_slice(new_resid, (me * shard,), (shard,))
        new_resid = jax.lax.dynamic_update_slice(
            new_resid, cur + err * n_devices, (me * shard,))
    qg = jax.lax.all_gather(qm, axis_name, tiled=True)
    sg = jax.lax.all_gather(sm, axis_name, tiled=True)
    return dequantize_block_int8(qg, sg, quant_block), new_resid


def _bucketized_apply(params, grads, lr: float, axis_name: str, comm: str,
                      n_devices: int, *, bucket_elems: int,
                      quant_block: int, resid, rounding_key):
    """The bucket-pipelined apply shared by `overlap=True` (pmean/bf16)
    and the always-bucketized int8 strategy: per bucket, one flat
    collective whose only dependency is that bucket's gradient leaves,
    then the bucket's SGD update — XLA overlaps bucket k's collective with
    bucket j's backward (module docstring). Returns
    (new_params, new_resid | None); `resid` is this device's flat residual
    vector (int8 error feedback) or None."""
    p_leaves, treedef = jax.tree_util.tree_flatten(params)
    g_leaves = jax.tree_util.tree_leaves(grads)
    assert len(p_leaves) == len(g_leaves), "params/grads tree mismatch"
    align = n_devices * quant_block if comm == "int8" else 1
    new_leaves: list = [None] * len(p_leaves)
    resid_parts: list = []
    off = 0
    for b, (bucket, n_real, padded) in enumerate(
            _bucket_layout(p_leaves, bucket_elems, align)):
        flat_g = jnp.concatenate(
            [g_leaves[i].reshape(-1).astype(jnp.float32) for i in bucket])
        if padded > n_real:
            flat_g = jnp.concatenate(
                [flat_g, jnp.zeros(padded - n_real, flat_g.dtype)])
        if comm == "int8":
            r = resid[off:off + padded] if resid is not None else None
            mean, new_r = int8_allreduce_mean(flat_g, r, axis_name,
                                              n_devices, quant_block)
            if new_r is not None:
                resid_parts.append(new_r)
        elif comm == "bf16":
            if rounding_key is not None:
                cast = stochastic_round_bf16(
                    jax.random.fold_in(rounding_key, b), flat_g)
            else:
                cast = flat_g.astype(jnp.bfloat16)
            mean = (jax.lax.psum(cast, axis_name).astype(jnp.float32)
                    / n_devices)
        else:  # pmean: the same f32 allreduce-mean, one bucket at a time
            mean = jax.lax.psum(flat_g, axis_name) / n_devices
        loff = 0
        for i in bucket:
            size = p_leaves[i].size
            leaf = p_leaves[i].reshape(-1)
            new_leaves[i] = sgd_step_flat(
                leaf, mean[loff:loff + size], lr).reshape(p_leaves[i].shape)
            loff += size
        off += padded
    new_resid = jnp.concatenate(resid_parts) if resid_parts else None
    return jax.tree_util.tree_unflatten(treedef, new_leaves), new_resid


def int8_apply_gradients(params, grads, lr: float, axis_name: str,
                         n_devices: int, *, resid=None,
                         bucket_elems: int = DEFAULT_BUCKET_ELEMS,
                         quant_block: int = QUANT_BLOCK):
    """The int8 strategy's entry point — separate from `apply_gradients`
    because it threads STATE: local per-device `grads` (and this device's
    flat residual vector, or None with error feedback off) in,
    (replicated fresh params, new residual | None) out. Runs inside a
    shard_map body over `axis_name`."""
    return _bucketized_apply(params, grads, lr, axis_name, "int8",
                             n_devices, bucket_elems=bucket_elems,
                             quant_block=quant_block, resid=resid,
                             rounding_key=None)


def sharded_update(params, grads, lr: float, axis_name: str,
                   n_devices: int, *,
                   bucket_elems: int = DEFAULT_BUCKET_ELEMS):
    """reduce-scatter → sharded SGD → all-gather, per bucket (the
    arXiv:2004.13336 pattern; module docstring).

    Must run inside a shard_map body over `axis_name` with per-device
    (device-varying) `grads` and replicated `params`; returns the fresh
    params, identical on every device (the all-gather re-replicates)."""
    p_leaves, treedef = jax.tree_util.tree_flatten(params)
    g_leaves = jax.tree_util.tree_leaves(grads)
    assert len(p_leaves) == len(g_leaves), "params/grads tree mismatch"
    me = jax.lax.axis_index(axis_name)
    new_leaves: list = [None] * len(p_leaves)
    for bucket in _leaf_buckets(p_leaves, bucket_elems):
        flat_g = jnp.concatenate(
            [g_leaves[i].reshape(-1).astype(jnp.float32) for i in bucket])
        flat_p = jnp.concatenate([p_leaves[i].reshape(-1) for i in bucket])
        n = flat_p.size
        shard = padded_size(n, n_devices) // n_devices
        pad = shard * n_devices - n
        if pad:
            flat_g = jnp.concatenate([flat_g, jnp.zeros(pad, flat_g.dtype)])
            flat_p = jnp.concatenate([flat_p, jnp.zeros(pad, flat_p.dtype)])
        # Each device leaves the reduce-scatter owning 1/N of the SUM;
        # the /N makes it the DDP mean. The update then touches only this
        # device's shard — 1/N of the FLOPs and HBM traffic of the
        # redundant replicated update.
        g_shard = jax.lax.psum_scatter(
            flat_g, axis_name, scatter_dimension=0, tiled=True) / n_devices
        p_shard = jax.lax.dynamic_slice(flat_p, (me * shard,), (shard,))
        fresh = sgd_step_flat(p_shard, g_shard, lr)
        flat_new = jax.lax.all_gather(fresh, axis_name, tiled=True)
        off = 0
        for i in bucket:
            size = p_leaves[i].size
            new_leaves[i] = flat_new[off:off + size].reshape(
                p_leaves[i].shape)
            off += size
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def apply_gradients(params, grads, lr: float, axis_name: str, comm: str,
                    n_devices: int, *,
                    rounding_key: jax.Array | None = None,
                    bucket_elems: int = DEFAULT_BUCKET_ELEMS,
                    overlap: bool = False):
    """The stateless entry point: local per-device `grads` in, fresh
    replicated params out, via the selected communication strategy. Runs
    inside a shard_map body over `axis_name`. `overlap=True` selects the
    bucket-pipelined program for pmean/bf16 (one collective per
    `bucket_elems` bucket instead of a whole-tree barrier); `sharded` is
    bucket-structured already, so overlap composes as the identity.

    `comm='int8'` threads error-feedback state and therefore has its own
    entry (`int8_apply_gradients`), rejected here by name."""
    validate_comm(comm)
    if comm == "int8":
        raise ValueError(
            "comm='int8' carries error-feedback residual state through the "
            "step — use int8_apply_gradients (params, resid in; params', "
            "resid' out), not the stateless apply_gradients")
    if comm == "sharded":
        return sharded_update(params, grads, lr, axis_name, n_devices,
                              bucket_elems=bucket_elems)
    if overlap:
        new_params, _ = _bucketized_apply(
            params, grads, lr, axis_name, comm, n_devices,
            bucket_elems=bucket_elems, quant_block=QUANT_BLOCK,
            resid=None, rounding_key=rounding_key)
        return new_params
    if comm == "bf16":
        mean = bf16_allreduce_mean(grads, axis_name, n_devices,
                                   rounding_key=rounding_key)
    else:
        mean = jax.lax.pmean(grads, axis_name)
    return sgd_step(params, mean, lr)


# ---------------------------------------------------------------------------
# The comm probe: an isolated, timeable program of JUST the gradient
# communication a strategy performs. The in-step collective overlaps with
# compute inside one XLA program and is not host-observable without the
# profiler; the probe runs the same collective pattern on a params-shaped
# tree so `ddp.collective_s` reports an honest isolated comms cost.
# ---------------------------------------------------------------------------


def make_comm_probe(mesh, comm: str, *,
                    quant_block: int = QUANT_BLOCK,
                    bucket_elems: int = DEFAULT_BUCKET_ELEMS):
    """Jitted (params-shaped tree) -> reduced tree program of the
    strategy's communication pattern over `mesh`'s 'dp' axis."""
    from jax.sharding import PartitionSpec as P
    from ..compat import shard_map
    from .mesh import DATA_AXIS
    validate_comm(comm)
    n_dev = int(mesh.devices.size)

    def body(tree):
        if comm == "sharded":
            # RS + sharded touch + AG — the sharded strategy's wire pattern
            # (the O(1/N) update itself is deliberately included: it is
            # negligible by construction, which the probe demonstrates).
            return sharded_update(tree, tree, 0.0, DATA_AXIS, n_dev)
        if comm == "bf16":
            return bf16_allreduce_mean(tree, DATA_AXIS, n_dev)
        if comm == "int8":
            # quantize + both quantized phases + dequant (error feedback
            # off: the residual bookkeeping is elementwise VPU work the
            # step pays, but the PROBE isolates the wire pattern)
            new_tree, _ = _bucketized_apply(
                tree, tree, 0.0, DATA_AXIS, "int8", n_dev,
                bucket_elems=bucket_elems, quant_block=quant_block,
                resid=None, rounding_key=None)
            return new_tree
        return jax.lax.pmean(tree, DATA_AXIS)

    sharded_body = shard_map(body, mesh=mesh, in_specs=(P(),),
                             out_specs=P(), check_vma=False)
    return jax.jit(sharded_body)


def measure_collective_seconds(probe, params, reps: int = 3) -> list:
    """Run a `make_comm_probe` program `reps` times and return per-rep
    wall seconds (each rep blocked to completion). The first call compiles;
    callers warm the probe once before timing — this helper does that
    itself, so the returned list holds steady-state reps only."""
    jax.block_until_ready(probe(params))      # compile + warm
    out = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(probe(params))
        out.append(time.perf_counter() - t0)
    return out
