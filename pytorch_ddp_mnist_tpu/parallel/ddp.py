"""SPMD data-parallel train step — the DDP analog, the TPU way.

Reference semantics being reproduced (SURVEY.md §7 parity item 4; DDP wrap at
ddp_tutorial_multi_gpu.py:72, allreduce firing inside backward at :94):
  * params replicated on every device (DDP broadcasts rank-0 params at
    construction; here replication is a sharding annotation and the initial
    device_put replicates one host copy — same net effect);
  * per step, gradients are AVERAGED across replicas (DDP allreduce-mean);
  * the optimizer runs redundantly per replica on identical averaged grads;
  * each replica draws an INDEPENDENT dropout mask (torch ranks have
    independent RNG; naive SPMD replication would share one mask — we fold
    the device's mesh position into the key).

Instead of a hand-driven process group, the step is `shard_map` over a 1-D
'dp' mesh: the batch arrives device-sharded, each device computes local
grads, and a single `jax.lax.pmean` emits the XLA allreduce — which rides ICI
within a slice and DCN across slices, the NCCL-ring equivalent
(SURVEY.md §2.9-2.11 TPU-native equivalents). XLA overlaps it with the
surrounding compute the way DDP's bucketed backward does, without bucket
tuning knobs.

bfloat16: optional compute dtype for the fwd/bwd (MXU-native); params and the
SGD update stay float32 (master weights).

Gradient communication is strategy-selectable since round 9
(`comm=` / `--ddp_comm`): the pmean baseline above, the reduce-scatter →
sharded-update → all-gather pattern, or the bf16-compressed allreduce —
see parallel/collectives.py for the three programs and their cost model.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ..compat import pvary as _pvary, shard_map  # noqa: F401 (_pvary re-exported)
from ..models.mlp import mlp_apply
from ..ops.loss import cross_entropy
from ..ops.sgd import sgd_step
from .mesh import DATA_AXIS, data_parallel_mesh


def dp_mesh(devices=None) -> Mesh:
    return data_parallel_mesh(devices)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for (global_batch, ...) arrays: split dim 0 over 'dp'."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def _mesh_axis_size(mesh) -> int:
    """Device count of a Mesh OR an AbstractMesh (the export-lowering
    surface builds the step program over a deviceless mesh)."""
    try:
        return int(mesh.devices.size)
    except (AttributeError, ValueError):
        # AbstractMesh raises ValueError("does not implement devices")
        import numpy as np
        return int(np.prod(list(mesh.shape.values())))


def dp_step_program(mesh, lr: float, *, dtype: str = "float32",
                    comm: str = "pmean", bf16_rounding: str = "nearest",
                    health: bool = False, overlap: bool = False,
                    quant_block: int | None = None,
                    error_feedback: bool = True,
                    bucket_elems: int | None = None,
                    model: str = "mlp", param_scale: int = 1):
    """The un-jitted SPMD step program: (params, key, x, y) ->
    (params', key', loss) over `mesh` (a Mesh, or an AbstractMesh for
    client-side export lowering — tests/test_export_lowering.py).

    `comm` selects the gradient-communication strategy
    (parallel/collectives.py): 'pmean' (the reference-semantics baseline —
    full f32 allreduce-mean + replicated update), 'sharded' (bucketized
    reduce-scatter → 1/N sharded SGD → params all-gather), 'bf16'
    (compressed allreduce: bf16 wire + reduction, f32 mean/update), or
    'int8' (block-scaled quantized allreduce with error feedback).
    `bf16_rounding='stochastic'` opts the bf16 cast into unbiased
    stochastic rounding (per-step per-replica keys off the dropout chain).

    `overlap=True` bucket-pipelines the pmean/bf16 collectives (one
    collective per bucket instead of a whole-tree barrier; sharded/int8
    are bucketized by construction). pmean with overlap=False stays the
    UNTOUCHED baseline program — the bitwise anchor.

    `comm='int8'` with `error_feedback=True` (the default) threads the
    residual state: the program becomes (params, key, resid, x, y) ->
    (params', key', loss[, aux], resid') with `resid` a
    (n_devices, comm_state_elems) f32 array sharded over 'dp' (see
    `collectives.place_comm_state`). `quant_block` sizes the scaling
    blocks; both knobs are rejected by name off the int8 strategy.

    `model`/`param_scale` select the workload from models/zoo.py
    (the default is the untouched reference MLP).

    `health=True` folds the training-health auxiliary vector
    (`telemetry.health.device_health_aux`: global grad norm, finite flag,
    param norm) into the step's outputs — (params', key', loss, aux) —
    computed IN-program from values the step already holds, so the health
    watchdog's per-step signals ride the existing dispatch and the
    existing once-per-epoch fetch: zero extra host syncs (the invariant
    tests/test_health.py pins). The pmean strategy reports the exact norm
    of the averaged grads; the other strategies (which never materialize
    the averaged grads) pmean the local sum-of-squares instead — a
    scale-faithful proxy.
    """
    from . import collectives
    from ..models.zoo import resolve_model
    from ..telemetry.health import device_health_aux
    quant_block = (collectives.QUANT_BLOCK if quant_block is None
                   else quant_block)
    bucket_elems = (collectives.DEFAULT_BUCKET_ELEMS if bucket_elems is None
                    else bucket_elems)
    collectives.validate_comm(comm)
    collectives.validate_bf16_rounding(bf16_rounding, comm)
    collectives.validate_int8_options(quant_block, error_feedback, comm)
    apply_fn = resolve_model(model, param_scale).apply
    stateful = collectives.carries_state(comm, error_feedback)
    compute_dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    n_dev = _mesh_axis_size(mesh)

    def _local(params, x, y, rkey):
        logits = apply_fn(params, x.astype(compute_dt), train=True,
                          dropout_key=rkey)
        return cross_entropy(logits, y)

    if comm == "pmean" and not overlap:
        def _shard_fn(params, sub, x, y):
            # Mark params device-varying: each replica differentiates its
            # OWN copy, so the cotangent stays local and the allreduce
            # below is the ONLY cross-device grad reduction (without this,
            # shard_map's replicated-input transpose auto-psums grads — a
            # sum, not DDP's mean, and doubled up with ours).
            params = _pvary(params, DATA_AXIS)
            # Distinct dropout stream per replica — parity item 4.
            rkey = jax.random.fold_in(sub, jax.lax.axis_index(DATA_AXIS))
            loss, grads = jax.value_and_grad(_local)(params, x, y, rkey)
            grads = jax.lax.pmean(grads, DATA_AXIS)  # the DDP allreduce-mean
            loss = jax.lax.pmean(loss, DATA_AXIS)
            return grads, loss
    else:
        def _comm_apply(params, grads, rkey, resid_vec):
            """The selected strategy's (new_params, new_resid|None)."""
            if comm == "int8":
                return collectives.int8_apply_gradients(
                    params, grads, lr, DATA_AXIS, n_dev, resid=resid_vec,
                    bucket_elems=bucket_elems, quant_block=quant_block)
            # per-step per-replica rounding noise off the dropout chain
            # (distinct per replica so cast errors decorrelate in the sum)
            rnd = (jax.random.fold_in(rkey, 7)
                   if bf16_rounding == "stochastic" else None)
            return collectives.apply_gradients(
                params, grads, lr, DATA_AXIS, comm, n_dev,
                rounding_key=rnd, bucket_elems=bucket_elems,
                overlap=overlap), None

        def _shard_fn(params, sub, *rest):
            # Same local fwd/bwd as the pmean path (pvary note above);
            # only the grads' trip across the wire — and where the SGD
            # update runs — changes with the strategy.
            resid, (x, y) = ((rest[0], rest[1:]) if stateful
                             else (None, rest))
            params = _pvary(params, DATA_AXIS)
            rkey = jax.random.fold_in(sub, jax.lax.axis_index(DATA_AXIS))
            loss, grads = jax.value_and_grad(_local)(params, x, y, rkey)
            loss = jax.lax.pmean(loss, DATA_AXIS)
            new_params, new_resid = _comm_apply(
                params, grads, rkey,
                resid.reshape(-1) if resid is not None else None)
            out = (new_params, loss)
            if health:
                # the averaged grads never exist under these strategies;
                # pmean the local sum-of-squares inside the shard instead
                out += (device_health_aux(loss, grads, new_params,
                                          axis_name=DATA_AXIS),)
            if stateful:
                out += (new_resid.reshape(1, -1),)
            return out

    # check_vma only on the pmean path: the other bodies end in
    # all_gather/psum programs whose outputs are value-replicated but not
    # provably so to the static replication checker; their cross-strategy
    # parity (and therefore replication) is pinned by test instead.
    legacy_pmean = comm == "pmean" and not overlap
    n_out = 2 + (1 if (health and not legacy_pmean) else 0) \
        + (1 if stateful else 0)
    in_specs = [P(), P()]
    out_specs = [P()] * (n_out - (1 if stateful else 0))
    if stateful:
        # the residual is per-DEVICE local state (quantization error of
        # this device's own gradients), sharded over 'dp' — unlike the
        # replicated params
        in_specs.append(P(DATA_AXIS))
        out_specs.append(P(DATA_AXIS))
    in_specs += [P(DATA_AXIS), P(DATA_AXIS)]
    sharded = shard_map(
        _shard_fn, mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=tuple(out_specs), check_vma=legacy_pmean)

    if legacy_pmean:
        def program(params, key, x, y):
            key, sub = jax.random.split(key)
            grads, loss = sharded(params, sub, x, y)
            # Redundant-per-replica optimizer (DDP semantics): params and
            # grads are both replicated, XLA fuses this update into the
            # step program.
            new_params = sgd_step(params, grads, lr)
            if health:
                # grads here ARE the pmean'd global grads: the aux vector
                # carries the exact global grad norm, fused into the step
                return (new_params, key, loss,
                        device_health_aux(loss, grads, new_params))
            return new_params, key, loss
    elif stateful:
        def program(params, key, resid, x, y):
            key, sub = jax.random.split(key)
            out = sharded(params, sub, resid, x, y)
            # out = (params', loss[, aux], resid') -> the program's
            # public ordering keeps loss at index 2 and resid LAST
            return (out[0], key) + out[1:]
    else:
        def program(params, key, x, y):
            key, sub = jax.random.split(key)
            out = sharded(params, sub, x, y)
            return (out[0], key) + out[1:]

    return program


def make_dp_train_step(mesh: Mesh, lr: float, *, dtype: str = "float32",
                       comm: str = "pmean",
                       bf16_rounding: str = "nearest",
                       health: bool = False, overlap: bool = False,
                       quant_block: int | None = None,
                       error_feedback: bool = True,
                       bucket_elems: int | None = None,
                       model: str = "mlp", param_scale: int = 1):
    """Build the jitted SPMD step: (params, key, x, y) -> (params', key', loss).

    x: (global_batch, 784) sharded over 'dp'; params replicated; returned loss
    is the global batch mean (= mean of per-replica means at equal local batch,
    exactly DDP's effective loss). `comm` selects the gradient-communication
    strategy and `overlap` the bucket-pipelined scheduling (see
    dp_step_program / parallel/collectives.py); `model`/`param_scale` the
    workload (models/zoo.py). `health=True` appends the watchdog's
    in-program auxiliary vector to the outputs (see dp_step_program).

    `comm='int8'` with error feedback threads the residual: the step is
    then (params, key, x, y, resid) -> (params', key', loss[, aux],
    resid'); `.comm_state` is True and `.place_comm_state(host=None)`
    builds the device-sharded residual (zeros, or a restored checkpoint's
    array) — train/loop.py keys off these.

    The returned step carries metadata the train loop's telemetry reads:
    `.ddp_comm` (strategy), `.ddp_mesh`, `.ddp_devices`,
    `.ddp_quant_block`, `.ddp_bucket_elems`, `.ddp_overlap` — the
    `ddp.bytes_on_wire` / `ddp.collective_s` wiring in train/loop.py keys
    off these without the loop having to know about meshes — and
    `.health_aux` (whether the step returns the aux output).
    """
    from . import collectives
    program = dp_step_program(mesh, lr, dtype=dtype, comm=comm,
                              bf16_rounding=bf16_rounding, health=health,
                              overlap=overlap, quant_block=quant_block,
                              error_feedback=error_feedback,
                              bucket_elems=bucket_elems,
                              model=model, param_scale=param_scale)
    stateful = collectives.carries_state(comm, error_feedback)
    qb = collectives.QUANT_BLOCK if quant_block is None else quant_block
    be = (collectives.DEFAULT_BUCKET_ELEMS if bucket_elems is None
          else bucket_elems)
    if stateful:
        jitted = jax.jit(program, donate_argnums=(0, 1, 2))

        def step(params, key, x, y, resid):
            return jitted(params, key, resid, x, y)

        def place_comm_state(host=None, params=None):
            # params only needed for sizing a fresh zero state; restored
            # states carry their own shape (validated by name)
            return collectives.place_comm_state(
                mesh, params, host=host, bucket_elems=be, quant_block=qb)

        step.place_comm_state = place_comm_state
        # declared donation contract — the statics donation-aliasing
        # audit cross-checks the TRACED program against this tuple, so
        # silently dropping a donate_argnums entry fails by name
        step.donates = ("params", "key", "resid")
    else:
        jitted = jax.jit(program, donate_argnums=(0, 1))

        def step(params, key, x, y):
            return jitted(params, key, x, y)

        step.donates = ("params", "key")

    step.ddp_comm = comm
    step.ddp_mesh = mesh
    step.ddp_devices = _mesh_axis_size(mesh)
    step.health_aux = health
    step.comm_state = stateful
    step.ddp_quant_block = qb
    step.ddp_bucket_elems = be
    step.ddp_overlap = overlap
    # the program-forensics name (telemetry/costs.py): compile attribution
    # and OOM dumps key cost records on exactly this label
    step.cost_label = collectives.step_cost_label(comm, overlap)

    def collective_schedule(params):
        # the per-rank collective journal's static half (telemetry/
        # cluster.py): the ordered payload collectives ONE step of this
        # exact configuration issues — a thunk, not a list, because the
        # leaf sizes come from the live params tree the loop holds
        return collectives.collective_schedule(
            params, step.ddp_devices, comm, overlap=overlap,
            bucket_elems=be, quant_block=qb)

    step.collective_schedule = collective_schedule
    return step


def _check_batch_divisible(n_rows: int, n_shards: int, what: str) -> None:
    """A ragged final batch used to surface as an opaque XLA sharding error
    deep inside device_put/make_array; name the numbers instead. Loaders in
    this repo wrap-pad every batch to full size, so hitting this means a
    hand-built batch — the fix is the caller's choice (drop, pad, or pick a
    divisible batch size), not something to guess at silently here."""
    if n_rows % n_shards:
        raise ValueError(
            f"{what}: batch of {n_rows} rows does not divide over "
            f"{n_shards} device(s) of the 'dp' mesh — use a batch size "
            f"divisible by {n_shards}, or pad/drop the ragged final batch "
            f"(the BatchLoader/NetCDFShardLoader wrap-pad does this)")


def shard_batch(mesh: Mesh, batch):
    """Place a host batch pytree with leading-dim 'dp' sharding.

    Raises ValueError (naming batch size and device count) for a leading
    dim not divisible by the mesh size, instead of the opaque XLA sharding
    error that used to escape."""
    s = batch_sharding(mesh)
    n_shards = int(mesh.devices.size)
    for leaf in jax.tree_util.tree_leaves(batch):
        _check_batch_divisible(int(leaf.shape[0]), n_shards, "shard_batch")
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, s), batch)


def global_batch_from_local(mesh: Mesh, local_batch):
    """Assemble each process's LOCAL batch shard into a global dp-sharded
    jax.Array spanning the whole mesh.

    This is the multi-controller data plane: every process loads only the
    rows for its own devices (the PnetCDF independent-I/O analog — each rank
    reads just its sampler shard, mnist_pnetcdf_cpu_mp.py:32,46) and the
    runtime stitches the shards into one logical array for the SPMD step.
    In a single-process run it degrades to a plain sharded device_put.

    A local batch whose row count does not divide over this process's mesh
    devices raises a ValueError naming the sizes (the ragged-final-batch
    fix — previously an opaque XLA sharding error).
    """
    import numpy as np
    s = batch_sharding(mesh)
    local_shards = int(mesh.local_mesh.devices.size)
    for leaf in jax.tree_util.tree_leaves(local_batch):
        _check_batch_divisible(int(np.asarray(leaf).shape[0]), local_shards,
                               "global_batch_from_local (this process's "
                               "local shard)")
    return jax.tree_util.tree_map(
        lambda a: jax.make_array_from_process_local_data(s, np.asarray(a)),
        local_batch)


def replicate_state(mesh: Mesh, tree):
    """Place a host pytree fully replicated over the (possibly multi-process)
    mesh — the DDP construction-time param broadcast analog
    (ddp_tutorial_multi_gpu.py:72): every process passes the same host value
    (same seed), every device holds a copy."""
    import numpy as np
    rep = replicated(mesh)

    def leaf(a):
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jax.dtypes.prng_key):
            data = np.asarray(jax.random.key_data(a))
            g = jax.make_array_from_callback(
                data.shape, rep, lambda idx: data[idx])
            # preserve the key's PRNG engine (--impl rbg keys have a
            # different key_data shape than the threefry default)
            return jax.random.wrap_key_data(g, impl=jax.random.key_impl(a))
        a = np.asarray(a)
        return jax.make_array_from_callback(a.shape, rep, lambda idx: a[idx])

    return jax.tree_util.tree_map(leaf, tree)
