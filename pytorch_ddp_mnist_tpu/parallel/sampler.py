"""Epoch-seeded sharded sampler — DistributedSampler-semantics parity.

The reference shards its train set with torch's DistributedSampler
(ddp_tutorial_multi_gpu.py:26-30, re-keyed per epoch via sampler.set_epoch(i)
at :81; same pattern at mnist_cpu_mp.py:318-328,381 and
mnist_pnetcdf_cpu_mp.py:390-401,449). The semantics that matter, and that this
class reproduces (SURVEY.md §7 parity item 3):

  1. a single GLOBAL permutation of [0, n) seeded by (seed + epoch), seed=42 —
     every rank computes the same permutation;
  2. PADDING BY REPETITION: the permuted index list is extended with its own
     head so its length is divisible by world_size (total_size =
     ceil(n / world) * world);
  3. ROUND-ROBIN split: rank r takes indices[r::world_size];
  4. reshuffle each epoch by calling set_epoch(e) before iterating.

The DEFAULT permutation source is numpy's PCG64
(np.random.default_rng(seed + epoch)) rather than torch's MT19937 randperm —
deliberately: the framework carries no torch dependency, and any uniform
permutation preserves the training distribution. `permutation="torch"` opts
into BITWISE parity instead: parallel/torch_rng.py re-implements torch's CPU
generator + randperm draw order exactly, so an epoch's shard contents then
match a reference run at the same seed index-for-index. The *sharding math*
(padding, interleave, epoch keying) is bitwise-faithful in both modes;
tests/test_sampler.py cross-checks everything against
torch.utils.data.DistributedSampler when torch is importable.

Non-shuffling mode mirrors DistributedSampler(shuffle=False): identity order,
same padding and split.
"""

from __future__ import annotations

import math

import numpy as np


class ShardedSampler:
    def __init__(self, num_samples: int, *, num_replicas: int = 1, rank: int = 0,
                 shuffle: bool = True, seed: int = 42,
                 permutation: str = "pcg64"):
        if not (0 <= rank < num_replicas):
            raise ValueError(f"rank {rank} out of range for {num_replicas} replicas")
        if permutation not in ("pcg64", "torch"):
            raise ValueError(f"permutation must be 'pcg64' (default) or "
                             f"'torch' (bitwise MT19937 randperm parity); "
                             f"got {permutation!r}")
        self.num_samples = int(num_samples)
        self.num_replicas = int(num_replicas)
        self.rank = int(rank)
        self.shuffle = shuffle
        self.seed = int(seed)
        self.permutation = permutation
        self.epoch = 0
        # Per-rank sample count after padding (DistributedSampler.num_samples).
        self.samples_per_replica = math.ceil(self.num_samples / self.num_replicas)
        self.total_size = self.samples_per_replica * self.num_replicas

    def set_epoch(self, epoch: int) -> None:
        """Re-key the shuffle for a new epoch (DistributedSampler.set_epoch)."""
        self.epoch = int(epoch)

    def reshard(self, num_replicas: int, rank: int) -> "ShardedSampler":
        """A NEW sampler over the same dataset/seed/permutation source at a
        different world geometry, preserving the epoch position — the
        elastic-training re-shard (elastic/reshape.py): after a shrink or
        grow, every surviving rank re-splits the SAME global permutation
        (a pure function of seed+epoch, world-independent) under the new
        (num_replicas, rank), so the union of shards still covers the
        epoch exactly. Padding/round-robin math re-derives in __init__."""
        out = ShardedSampler(self.num_samples, num_replicas=num_replicas,
                             rank=rank, shuffle=self.shuffle, seed=self.seed,
                             permutation=self.permutation)
        out.set_epoch(self.epoch)
        return out

    def global_permutation(self) -> np.ndarray:
        """The padded global order all ranks agree on this epoch."""
        if self.shuffle and self.permutation == "torch":
            from .torch_rng import torch_randperm
            idx = torch_randperm(self.num_samples, self.seed + self.epoch)
        elif self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            idx = rng.permutation(self.num_samples)
        else:
            idx = np.arange(self.num_samples)
        pad = self.total_size - self.num_samples
        if pad > 0:
            # Pad by repeating from the head — torch repeats indices[:padding]
            # (cycling if padding exceeds n, which only happens when
            # world_size > n).
            reps = np.resize(idx, pad) if pad > idx.size else idx[:pad]
            idx = np.concatenate([idx, reps])
        return idx

    def indices(self) -> np.ndarray:
        """This rank's shard for the current epoch: global_perm[rank::world]."""
        return self.global_permutation()[self.rank::self.num_replicas]

    def __len__(self) -> int:
        return self.samples_per_replica

    def __iter__(self):
        return iter(self.indices())
