"""Bit-exact reimplementation of torch's CPU ``randperm`` stream.

The reference shards its epoch by ``torch.randperm(n, generator=g)`` with a
``torch.Generator`` seeded ``seed + epoch`` inside ``DistributedSampler``
(ddp_tutorial_multi_gpu.py:26-30 via sampler.set_epoch at :81).  A torch
``Generator`` on CPU is the classic Mersenne Twister (``at::mt19937``:
init_genrand seeding, 624-word state, standard tempering), and CPU
``randperm`` is a Fisher-Yates pass drawing one 32-bit word per position::

    r = [0, 1, ..., n-1]
    for i in 0..n-2:  z = mt() % (n - i);  swap(r[i], r[i+z])

Reimplementing exactly that here (no torch dependency) gives
``ShardedSampler(permutation="torch")`` BITWISE shard composition parity
with the reference — the last parity asterisk from SURVEY.md §7 item 3.
``tests/test_sampler.py`` cross-checks every path against real torch
(including full 60000-row MNIST epochs), so any torch-side algorithm drift
would surface there, not silently here.

Implementation notes: the twist is vectorized per 624-word block.  The
in-place reference recurrence makes entries 227..623 depend on entries
updated EARLIER IN THE SAME TWIST (new[i] = new[i-227] ^ f(old[i],
old[i+1]) for i >= 227, and the final word reads new[0]); a naive
whole-block roll uses stale words there and diverges after the first 227
draws — the bug class this module's segment-split exists to avoid.
"""

from __future__ import annotations

import numpy as np

_N, _M = 624, 397
_UPPER = np.uint32(0x80000000)        # most significant w-r bits
_LOWER = np.uint32(0x7FFFFFFF)        # least significant r bits
_MATRIX_A = np.uint32(0x9908B0DF)


class TorchMT19937:
    """``at::mt19937`` with init_genrand seeding: the engine behind a CPU
    ``torch.Generator().manual_seed(seed)``. Yields the same uint32 stream."""

    def __init__(self, seed: int):
        st = np.empty(_N, np.uint32)
        s = int(seed) & 0xFFFFFFFF
        st[0] = s
        for j in range(1, _N):
            s = (1812433253 * (s ^ (s >> 30)) + j) & 0xFFFFFFFF
            st[j] = s
        self._state = st
        self._pos = _N                 # force a twist before the first draw

    def _twist(self) -> None:
        s = self._state
        new = np.empty(_N, np.uint32)
        y = (s & _UPPER) | (np.concatenate([s[1:], s[:1]]) & _LOWER)
        f = (y >> np.uint32(1)) ^ np.where(
            (y & np.uint32(1)).astype(bool), _MATRIX_A, np.uint32(0))
        # i in [0, N-M): sources old state only
        new[:_N - _M] = s[_M:] ^ f[:_N - _M]
        # i in [N-M, N-1): new[i] = new[i-(N-M)] ^ f[i] — each 227-word
        # stripe depends on the stripe just written, so update stripe-wise
        i = _N - _M
        while i < _N - 1:
            j = min(i + (_N - _M), _N - 1)
            new[i:j] = new[i - (_N - _M):j - (_N - _M)] ^ f[i:j]
            i = j
        # i = N-1: y reads the NEW word 0 (the in-place recurrence)
        y_last = (s[_N - 1] & _UPPER) | (new[0] & _LOWER)
        f_last = (y_last >> np.uint32(1)) ^ (
            _MATRIX_A if (int(y_last) & 1) else np.uint32(0))
        new[_N - 1] = new[_M - 1] ^ f_last
        self._state = new
        self._pos = 0

    def draws(self, k: int) -> np.ndarray:
        """The next ``k`` tempered uint32 outputs, vectorized per block."""
        out = np.empty(k, np.uint32)
        filled = 0
        while filled < k:
            if self._pos >= _N:
                self._twist()
            take = min(k - filled, _N - self._pos)
            y = self._state[self._pos:self._pos + take].copy()
            y ^= y >> np.uint32(11)
            y ^= (y << np.uint32(7)) & np.uint32(0x9D2C5680)
            y ^= (y << np.uint32(15)) & np.uint32(0xEFC60000)
            y ^= y >> np.uint32(18)
            out[filled:filled + take] = y
            self._pos += take
            filled += take
        return out

    def __call__(self) -> int:
        return int(self.draws(1)[0])

    def skip(self, k: int) -> None:
        """Advance the stream by ``k`` outputs without keeping them —
        the deterministic fast-forward a resumed consumer uses to re-seat
        its position (e.g. the dropout-mask stream at a --start_epoch
        boundary). Chunked so skipping hundreds of millions of draws never
        materializes one giant array."""
        CHUNK = 1 << 20
        while k > 0:
            take = min(k, CHUNK)
            self.draws(take)
            k -= take


def torch_randperm(n: int, seed: int) -> np.ndarray:
    """``torch.randperm(n, generator=manual_seed(seed))`` on CPU, bitwise.

    One generator word per position, modulo-folded into the shrinking tail
    (torch's exact draw order — the modulo bias and all). The swap loop is
    host Python (~30 ms at n=60000): it runs once per epoch on the host,
    never on device, so clarity beats vectorization tricks here.
    """
    n = int(n)
    r = np.arange(n, dtype=np.int64)
    if n < 2:
        return r
    z = TorchMT19937(seed).draws(n - 1)
    for i in range(n - 1):
        j = i + int(z[i]) % (n - i)
        if j != i:
            r[i], r[j] = r[j], r[i]
    return r


def torch_bernoulli(gen: TorchMT19937, n: int, p: float) -> np.ndarray:
    """``tensor.bernoulli_(p)`` on a CPU float tensor, bitwise: ``n`` {0,1}
    float32 values in element (row-major) order from ``gen``'s stream.

    Torch's CPU kernel draws, per element, one 64-bit word (two sequential
    32-bit engine outputs, FIRST draw = high word), keeps the low 53 bits as
    a double in [0, 1) (x * 2^-53), and emits 1 iff that uniform is < p.
    Reimplemented from the observed stream (fuzz-pinned against real torch
    in tests/test_sampler.py across seeds/sizes/probabilities); vectorized —
    one ``draws(2n)`` block, no per-element Python.

    This is the mask stream of ``nn.Dropout`` (reference
    ddp_tutorial_cpu.py:47): ``Dropout(p)`` draws ``bernoulli_(1-p)`` on the
    SAME global generator, so pass the keep probability here.
    """
    d = gen.draws(2 * n).astype(np.uint64)
    x = (d[0::2] << np.uint64(32)) | d[1::2]
    u = (x & np.uint64((1 << 53) - 1)).astype(np.float64) * (2.0 ** -53)
    return (u < p).astype(np.float32)
