"""Multi-process wireup — the reference `distributed` class, TPU-native.

The reference's wireup layer (mnist_cpu_mp.py:14-206, extended at
mnist_pnetcdf_cpu_mp.py:51-272) derives MASTER_ADDR/PORT, RANK, WORLD_SIZE
from SLURM / OpenMPI(PMIx) / MPICH(PMI) / fallback env vars, then calls
torch.distributed.init_process_group(env://) and exposes rank/size queries
plus MPI collectives (reduceMAX, barrier, finalize).

TPU-native shape: the same env-derivation chains feed
`jax.distributed.initialize(coordinator_address, num_processes, process_id)`
— after which every JAX collective (the psum in parallel.ddp) spans all
processes' devices over ICI/DCN; there is no separate "backend" choice
because XLA owns the fabric (SURVEY.md §5.8 TPU-native equivalent).

Method names map 1:1 to the reference's --wireup_method choices so launch
scripts port directly; the reference's nccl-openmpi `os.environ(...)` crash
bug (mnist_cpu_mp.py:97) is, naturally, not reproduced.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass


def _first_host(nodelist: str) -> str:
    """First hostname of a SLURM nodelist, e.g. 'nid[0012-0015,0020]' -> nid0012.

    The reference shells out to `scontrol show hostnames`; we parse the common
    compact forms directly so no scheduler binary is required.
    """
    m = re.match(r"^([^\[,]+)\[([^\]]+)\]", nodelist)
    if m:
        prefix, ranges = m.groups()
        first = ranges.split(",")[0].split("-")[0]
        return prefix + first
    return nodelist.split(",")[0]


# The reference's literal --wireup_method spellings (mnist_cpu_mp.py:47-188,
# mnist_pnetcdf_cpu_mp.py:184-211) accepted verbatim so a reference launch
# line runs unmodified. `gloo` is the reference's localhost/env fallback
# branch (mnist_cpu_mp.py:186-188: backend="gloo", init_method='env://');
# NCCL-vs-gloo is meaningless on TPU (XLA owns the fabric), so each alias
# resolves to the env-derivation chain its reference branch used.
METHOD_ALIASES = {
    "nccl-slurm": "slurm",
    "nccl-openmpi": "openmpi",
    "nccl-mpich": "mpich",
    "gloo": "env",
}


def resolve_method(name: str) -> str:
    """Canonicalize a wireup method name, accepting reference spellings."""
    return METHOD_ALIASES.get(name, name)


@dataclass
class Runtime:
    """Process-level topology handle (reference get_rank/get_size/
    get_local_rank, mnist_cpu_mp.py:15-39)."""
    method: str
    rank: int = 0
    size: int = 1
    local_rank: int = 0
    coordinator: str | None = None
    initialized: bool = False

    def barrier(self) -> None:
        """Cross-process sync (reference barrier, mnist_cpu_mp.py:201-203).

        Chaos hook: `PDMT_FAULT=collective_timeout[:rank=R]` makes this
        barrier raise the DEADLINE_EXCEEDED-shaped RuntimeError a dead
        collective produces (utils/faultpoints) — the injectable version
        of the failure `looks_like_backend_loss` triages. Imported lazily:
        this module must stay importable without jax or the package's
        heavier utils.

        Journal bracket (telemetry/cluster.py): the barrier is a
        host-BLOCKING collective, so the journal records a true
        enter/exit pair around it — the enter lands BEFORE the faultpoint
        fires, so an injected (or real) timeout leaves an open entry: the
        exact evidence the hang report and the collective watchdog key
        on. A NullJournal (the default) makes this one attribute check."""
        from ..telemetry import cluster
        from ..utils import faultpoints
        seq = cluster.get_journal().enter("barrier", axis="world")
        faultpoints.fire("barrier", rank=self.rank)
        if self.size > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("pytorch_ddp_mnist_tpu.barrier")
        cluster.get_journal().exit(seq)

    def reduce_max(self, value: float) -> float:
        """Global max of a host scalar (reference reduceMAX via
        MPI.Reduce(op=MAX), mnist_cpu_mp.py:193-199) — delivered to ALL
        processes (allreduce; the reference's root-only Reduce result is a
        strict subset of this). Journal-bracketed like `barrier` (it is a
        host-blocking 4-byte allreduce)."""
        if self.size == 1:
            return float(value)
        from ..telemetry import cluster
        seq = cluster.get_journal().enter("allreduce", axis="world",
                                          nbytes=4)
        import jax.numpy as jnp
        from jax.experimental import multihost_utils
        gathered = multihost_utils.process_allgather(jnp.float32(value))
        cluster.get_journal().exit(seq)
        return float(gathered.max())

    def finalize(self) -> None:
        """Tear down the distributed client (reference finalize ->
        destroy_process_group, mnist_cpu_mp.py:205-206)."""
        if self.initialized:
            import jax
            jax.distributed.shutdown()
            self.initialized = False


def _require(var: str, method: str, launcher: str) -> str:
    """Fetch a required launcher env var, failing with a named, actionable
    error like the reference's per-variable raises (mnist_cpu_mp.py:57-89)
    instead of a bare KeyError."""
    val = os.environ.get(var)
    if val is None:
        raise RuntimeError(
            f"wireup method {method!r}: required environment variable {var} "
            f"is not set — it is normally exported by the {launcher} "
            f"launcher. Launch under {launcher}, or use --wireup_method env "
            f"with RANK/WORLD_SIZE/MASTER_ADDR/MASTER_PORT set manually.")
    return val


def _derive(method: str):
    """(rank, size, local_rank, coordinator) from launcher env vars."""
    method = resolve_method(method)
    env = os.environ
    if method == "slurm":
        # Reference SLURM branch: mnist_cpu_mp.py:47-89.
        rank = int(_require("SLURM_PROCID", method, "SLURM (srun)"))
        size = int(_require("SLURM_NTASKS", method, "SLURM (srun)"))
        local = int(env.get("SLURM_LOCALID", 0))
        host = _first_host(env.get("SLURM_STEP_NODELIST",
                                   env.get("SLURM_NODELIST", "127.0.0.1")))
        port = 12000 + int(env.get("SLURM_JOBID", "0")) % 20000
        return rank, size, local, f"{host}:{port}"
    if method == "openmpi":
        # Reference PMIx branch: mnist_cpu_mp.py:94-113.
        rank = int(_require("OMPI_COMM_WORLD_RANK", method, "Open MPI (mpiexec)"))
        size = int(_require("OMPI_COMM_WORLD_SIZE", method, "Open MPI (mpiexec)"))
        local = int(env.get("OMPI_COMM_WORLD_LOCAL_RANK", 0))
        coord = f"{env.get('MASTER_ADDR', '127.0.0.1')}:{env.get('MASTER_PORT', '29500')}"
        return rank, size, local, coord
    if method == "mpich":
        # Reference PMI branch: mnist_cpu_mp.py:118-142.
        rank = int(_require("PMI_RANK", method, "MPICH (mpiexec)"))
        size = int(_require("PMI_SIZE", method, "MPICH (mpiexec)"))
        local = int(env.get("MPI_LOCALRANKID", 0))
        coord = f"{env.get('MASTER_ADDR', '127.0.0.1')}:{env.get('MASTER_PORT', '29500')}"
        return rank, size, local, coord
    if method == "env":
        # Reference fallback branch: mnist_cpu_mp.py:147-185.
        rank = int(env.get("RANK", "0"))
        size = int(env.get("WORLD_SIZE", "1"))
        local = int(env.get("LOCAL_RANK", "0"))
        coord = f"{env.get('MASTER_ADDR', '127.0.0.1')}:{env.get('MASTER_PORT', '29500')}"
        return rank, size, local, coord
    raise ValueError(f"unknown wireup method {method!r}")


def detect_method() -> str:
    """Probe launcher env — the reference picks via CLI; 'auto' adds detection.

    Scheduler launchers (SLURM/MPI — explicit rank/size env) win over the
    Cloud TPU pod markers: a job srun/mpiexec'd ONTO TPU VMs should follow
    the launcher's topology, matching the reference's precedence of explicit
    wireup choices.
    """
    env = os.environ
    if "SLURM_PROCID" in env and "SLURM_NTASKS" in env:
        return "slurm"
    if "OMPI_COMM_WORLD_RANK" in env:
        return "openmpi"
    if "PMI_RANK" in env:
        return "mpich"
    if "RANK" in env and "WORLD_SIZE" in env:
        return "env"
    # Cloud TPU pod: only when the runtime metadata names MULTIPLE workers —
    # single-host TPU sessions also export TPU_WORKER_HOSTNAMES (one entry)
    # and need no rendezvous. Explicit --wireup_method tpu remains available.
    hosts = [h for h in env.get("TPU_WORKER_HOSTNAMES", "").split(",") if h]
    if len(hosts) > 1:
        return "tpu"
    return "single"


def on_tpu_backend() -> bool:
    """True when the default backend is a TPU (incl. the axon PJRT plugin,
    which aliases the tpu lowering rules). Initializes JAX: in multi-process
    runs call only AFTER initialize_runtime (rendezvous must come first)."""
    import jax
    return jax.default_backend() in ("tpu", "axon")


def _honor_platform_env() -> None:
    """Make JAX_PLATFORMS from the launcher win over any backend already
    registered at interpreter start (e.g. a site-installed TPU plugin that
    forces its own platform list). Must run before rendezvous so every
    process brings up the same platform."""
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax
    if jax.config.jax_platforms != want:
        jax.config.update("jax_platforms", want)
        try:
            from jax.extend.backend import clear_backends
            clear_backends()
        except (ImportError, AttributeError, RuntimeError):
            pass  # older jax spelling / already-clear client: re-probe anyway


class BackendUnavailableError(RuntimeError):
    """The accelerator backend stayed unavailable for the whole retry budget."""


class BackendWedgedError(BackendUnavailableError):
    """The backend is reachable again, but THIS process's jax client is not:
    an earlier jax.devices() query hung inside backend init and still holds
    xla_bridge's init lock, so every in-process backend query would block
    forever. Only a fresh interpreter can use the recovered backend — the
    caller should re-exec (bench.py does, once) or ask the user to rerun."""


def env_seconds(name: str, default: float) -> float:
    """A seconds value from the environment, tolerantly parsed: unset/empty,
    malformed, non-finite, or negative values fall back to `default` (with a
    stderr note for the malformed cases) instead of crashing the entry point
    with a float() traceback."""
    import math
    import sys

    raw = os.environ.get(name)
    if raw is None or raw.strip() == "":
        return default
    try:
        val = float(raw)
    except ValueError:
        print(f"{name}={raw!r} is not a number; using "
              f"{default:.0f}s", file=sys.stderr)
        return default
    if not math.isfinite(val) or val < 0:
        print(f"{name}={raw!r} is not a non-negative finite "
              f"number of seconds; using {default:.0f}s", file=sys.stderr)
        return default
    return val


def backend_wait_env(default: float) -> float:
    """PDMT_BACKEND_WAIT (seconds), tolerantly parsed — shared by bench.py
    and the trainer CLI so the variable means one thing."""
    return env_seconds("PDMT_BACKEND_WAIT", default)


def _probe_devices_bounded(timeout_s: float):
    """Query jax.devices() on a daemon thread so a silently HANGING probe
    cannot stall the caller forever.

    The tunneled backend has two distinct outage modes: the query *raises*
    (``RuntimeError: ... UNAVAILABLE`` — retryable in place), or the query
    *hangs* — the connection is accepted and never answered, so there is no
    exception to retry on (observed round 3). Returns one of
    ``('ok', devices)``, ``('error', retryable_exc)``, ``('fatal', exc)``
    (non-RuntimeError, e.g. a broken jax install — retrying cannot clear
    it), or ``('hang', wait_fn)``.

    A 'hang' may be a true hang or merely a slow init still in flight; its
    payload is a ``wait_fn(extra_timeout_s)`` that re-joins the SAME probe
    thread and returns a fresh (status, payload), so the caller can give a
    slow init more time. A probe that never finishes leaves the thread
    blocked inside backend init, which holds xla_bridge's init lock — every
    later in-process query will block on that lock even after the tunnel
    recovers, so the caller must then treat the whole process as wedged
    (see BackendWedgedError).
    """
    import threading

    out = {}

    def probe():
        try:
            import jax
            out["devices"] = jax.devices()
        except Exception as e:  # classified retryable/fatal in wait()
            out["error"] = e

    t = threading.Thread(target=probe, name="pdmt-backend-probe", daemon=True)
    t.start()

    def wait(extra_timeout_s: float):
        t.join(extra_timeout_s)
        if t.is_alive():
            return "hang", wait
        if "error" in out:
            e = out["error"]
            return ("error" if isinstance(e, RuntimeError) else "fatal"), e
        return "ok", out["devices"]

    return wait(timeout_s)


def _subprocess_backend_healthy(timeout_s: float) -> bool:
    """Probe backend health from a FRESH interpreter — immune to this
    process's wedged bridge lock. rc=0 within the timeout means the backend
    answers queries again.

    The child honors the parent's JAX_PLATFORMS intent through jax.config
    (not just the env var): a pre-registered accelerator plugin can hang
    backend enumeration at env-var-only platform selection, which would
    make a CPU-intent probe (tests, --parallel-on-CPU runs) report the
    DEAD accelerator instead of the healthy backend the run actually uses.
    With an accelerator intent the probe touches that backend, so a downed
    tunnel still times out -> unhealthy, as wanted."""
    import subprocess
    import sys

    code = ("import os, jax; p = os.environ.get('JAX_PLATFORMS'); "
            "p and jax.config.update('jax_platforms', p); jax.devices()")
    try:
        return subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s, capture_output=True).returncode == 0
    except (subprocess.SubprocessError, OSError):
        return False  # TimeoutExpired, spawn failure: not healthy


# Substrings (lowercased match) of RuntimeErrors that a lost/dropping
# backend produces: gRPC status names the XLA client surfaces when the
# tunnel dies mid-run, plus socket-level phrasings. Deliberately narrow —
# a compile/shape error must NOT match (see looks_like_backend_loss).
BACKEND_LOSS_SIGNATURES = (
    "unavailable", "deadline exceeded", "deadline_exceeded",
    "socket closed", "connection reset", "connection refused",
    "connection closed", "failed to connect", "broken pipe",
    "transport closed", "stream terminated", "stream removed",
    "rst_stream", "goaway", "endpoint read failed", "heartbeat",
)


def looks_like_backend_loss(e: BaseException) -> bool:
    """Does this RuntimeError look like the backend DIED (vs a deterministic
    program error)? Used by retry wrappers to decide whether re-running can
    possibly help: a shape/compile error on a healthy backend would just
    fail again N times before surfacing (ADVICE r4)."""
    msg = str(e).lower()
    return any(sig in msg for sig in BACKEND_LOSS_SIGNATURES)


def backoff_schedule(base_s: float, cap_s: float, *, seed: int = 0,
                     factor: float = 2.0):
    """Endless jittered exponential backoff delays: attempt k waits
    jitter * min(cap_s, base_s * factor**k), jitter uniform in [0.5, 1.5).

    The jitter stream is DETERMINISTIC per (seed, attempt) — chaos runs
    replay bit-identically — but seeding by RANK decorrelates the ranks:
    when N survivors re-wire after a peer loss, a fixed shared cadence
    would have all of them probe (and later rendezvous-retry) in lockstep,
    hammering the coordinator in synchronized waves that can keep a
    marginal backend wedged (the re-wireup storm). Exponential growth
    bounds the total probe count against any deadline the caller enforces;
    the cap keeps worst-case reaction latency bounded once the backend
    returns."""
    import random
    if base_s <= 0 or cap_s < base_s or factor <= 1.0:
        raise ValueError(f"need 0 < base_s <= cap_s and factor > 1; got "
                         f"base_s={base_s}, cap_s={cap_s}, factor={factor}")
    attempt = 0
    while True:
        raw = min(cap_s, base_s * factor ** attempt)
        jitter = 0.5 + random.Random((seed << 20) ^ attempt).random()
        yield raw * jitter
        attempt += 1


def wait_for_backend(max_wait_s: float = 300.0, poll_s: float = 10.0,
                     hang_timeout_s: float = None):
    """Poll jax.devices() until the backend initializes; bounded retry.

    A tunneled/remote TPU backend can be transiently UNAVAILABLE (the tunnel
    drops and recovers); a bare first query would kill the job on a blip the
    next poll would have survived. xla_bridge caches a failed init, so each
    retry clears the backend cache before re-probing. Returns the live device
    list; raises BackendUnavailableError once max_wait_s is exhausted.
    Non-RuntimeError probe failures (a broken jax install, a config
    TypeError) are NOT retried — they re-raise immediately, as before.

    Retry cadence is JITTERED EXPONENTIAL backoff (`backoff_schedule`,
    seeded by this process's RANK env so ranks decorrelate), capped at
    `poll_s` — the re-wire probe loop of the elastic coordinator runs
    through here with N survivors at once, and the old fixed cadence had
    every rank probing in lockstep (the re-wireup storm). `max_wait_s`
    stays the TOTAL deadline, and every attempt (with its chosen next
    wait) lands in the flight ring.

    Probes are hang-bounded (``hang_timeout_s``, default 75 s, overridable
    via ``PDMT_HANG_TIMEOUT`` for backends whose legitimate cold init is
    slower): if a query neither returns nor raises (the round-3 outage
    mode), backend health is polled OUT of process for the rest of the
    budget while the original probe is re-checked each cycle — a merely
    SLOW init that lands late is still returned. Once the backend answers
    out-of-process, the in-flight probe gets one more ``hang_timeout_s`` to
    land; if it stays stuck, its thread holds xla_bridge's init lock forever
    and this process can never use the recovered backend — that state raises
    BackendWedgedError so the caller can restart/re-exec (bench.py does so
    automatically) instead of blocking forever.

    The healthy path costs nothing extra: the first probe is immediate and
    its result is returned directly.
    """
    import sys
    import time

    # The flight recorder is the structured counterpart of the stderr
    # progress lines below: every probe outcome lands in the bounded ring,
    # so a terminal failure (or a caller's SIGTERM) can dump an exact
    # post-mortem of what the retry loop saw — the evidence the opaque
    # BENCH_r01-r05 `backend_unavailable` tails never carried.
    from ..telemetry import flight

    if hang_timeout_s is None:
        hang_timeout_s = env_seconds("PDMT_HANG_TIMEOUT", 75.0)
    flight.record("backend_wait_start", max_wait_s=max_wait_s,
                  poll_s=poll_s, hang_timeout_s=hang_timeout_s)
    deadline = time.monotonic() + max_wait_s
    attempt = 0
    waiter = None  # wait_fn of an abandoned (possibly just slow) probe
    # jittered exponential retry delays, capped at poll_s (the legacy
    # cadence is the CAP, not the floor); rank-seeded so a whole world
    # re-wiring at once never probes in lockstep
    try:
        _seed = int(os.environ.get("RANK", "0"))
    except ValueError:
        _seed = 0
    delays = backoff_schedule(min(1.0, poll_s), max(poll_s, 1.0),
                              seed=_seed)

    def _sleep_backoff():
        delay = min(next(delays), max(deadline - time.monotonic(), 0.1))
        flight.record("backend_retry_wait", wait_s=round(delay, 2),
                      attempt=attempt)
        time.sleep(delay)
    while True:
        remaining = deadline - time.monotonic()
        if waiter is None:
            status, payload = _probe_devices_bounded(
                min(hang_timeout_s, max(remaining, 1.0)))
        else:
            status, payload = waiter(0.0)  # re-check the in-flight probe
        if status == "ok":
            if attempt:  # only noteworthy when the backend was ever down
                flight.record("backend_recovered", attempts=attempt,
                              devices=len(payload))
            return payload
        if status == "fatal":
            flight.record("backend_probe_fatal", error=str(payload)[:500])
            raise payload
        if status == "error":
            waiter = None
            attempt += 1
            remaining = deadline - time.monotonic()
            flight.record("backend_probe_error", attempt=attempt,
                          remaining_s=round(max(remaining, 0.0), 1),
                          error=str(payload)[:500])
            if remaining <= 0:
                flight.record("backend_unavailable", attempts=attempt,
                              budget_s=max_wait_s)
                raise BackendUnavailableError(
                    f"backend unavailable after {attempt} attempts over "
                    f"{max_wait_s:.0f}s: {payload}") from payload
            print(f"wireup: backend unavailable (attempt {attempt}), "
                  f"retrying for another {remaining:.0f}s: {payload}",
                  file=sys.stderr, flush=True)  # stdout stays machine-readable
            _sleep_backoff()
            try:
                from jax._src import xla_bridge
                xla_bridge._clear_backends()
            except (ImportError, AttributeError, RuntimeError):
                pass  # older/newer jax: fall through and re-probe anyway
            continue

        # status == "hang": the probe neither returned nor raised. Watch for
        # tunnel recovery from fresh subprocesses (immune to this process's
        # held init lock) while re-checking the in-flight probe above.
        if waiter is None:
            waiter = payload
            attempt += 1
            flight.record("backend_probe_hang", attempt=attempt,
                          hang_timeout_s=hang_timeout_s)
            print(f"wireup: backend probe hung for {hang_timeout_s:.0f}s "
                  f"(no error to retry on); polling health out-of-process",
                  file=sys.stderr, flush=True)
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            flight.record("backend_unavailable", attempts=attempt,
                          budget_s=max_wait_s, mode="hang")
            raise BackendUnavailableError(
                f"backend probe hung (> {hang_timeout_s:.0f}s without "
                f"returning or raising) and out-of-process probes stayed "
                f"unhealthy for the rest of the {max_wait_s:.0f}s budget")
        healthy = _subprocess_backend_healthy(min(hang_timeout_s, remaining))
        flight.record("backend_health_poll", healthy=healthy,
                      remaining_s=round(max(remaining, 0.0), 1))
        if healthy:
            # Backend answers from a fresh process. Give the in-flight init
            # one more bounded join — a slow-but-healthy init lands here.
            status, payload = waiter(
                min(hang_timeout_s, max(deadline - time.monotonic(), 1.0)))
            if status == "ok":
                flight.record("backend_recovered", attempts=attempt,
                              devices=len(payload), mode="late_init")
                return payload
            if status in ("error", "fatal"):
                waiter = None  # init failed late; lock released — re-probe
                continue
            flight.record("backend_wedged", attempts=attempt)
            raise BackendWedgedError(
                "backend is healthy again but this process's jax client is "
                "wedged: an earlier jax.devices() probe hung inside backend "
                "init and still holds the init lock, so every in-process "
                "query would block forever. Restart the process (bench.py "
                "re-execs itself once automatically).")
        _sleep_backoff()


def initialize_runtime(method: str = "auto") -> Runtime:
    """Resolve topology and (if multi-process) rendezvous via
    jax.distributed.initialize. Safe to call in single-process runs.

    After a successful multi-process init, jax.device_count() spans ALL
    processes' devices and every jit/psum is global — the moment the
    reference reaches with dist.init_process_group (mnist_cpu_mp.py:92-188).
    """
    _honor_platform_env()
    method = resolve_method(method)
    if method == "auto":
        method = detect_method()
    if method == "single":
        return Runtime(method="single")
    if method == "tpu":
        # Cloud TPU pod: no env-var maze at all — the TPU runtime's metadata
        # (worker hostnames, task id) IS the topology, and
        # jax.distributed.initialize() autodetects it. This is the path a
        # bare multi-host TPU VM job takes with no scheduler in front
        # (SURVEY.md §7 step 3's GCE-metadata analog of the reference's
        # MASTER_ADDR derivation chains).
        import jax
        hosts = [h for h in os.environ.get("TPU_WORKER_HOSTNAMES",
                                           "").split(",") if h]
        if len(hosts) > 1:
            # Rendezvous blocks until every pod worker joins — say so, and
            # name the escape hatch for a lone interactive process.
            print(f"wireup tpu: joining {len(hosts)}-worker pod rendezvous "
                  f"(every worker must run this job; use --wireup_method "
                  f"single for a one-process session)", flush=True)
        jax.distributed.initialize()
        # initialized tracks whether initialize() was CALLED (finalize must
        # shut the client down even for a 1-process init, or a later
        # initialize in this process raises 'already initialized').
        return Runtime(method="tpu", rank=jax.process_index(),
                       size=jax.process_count(),
                       local_rank=0, coordinator=None, initialized=True)
    rank, size, local, coord = _derive(method)
    rt = Runtime(method=method, rank=rank, size=size, local_rank=local,
                 coordinator=coord)
    if size > 1:
        import jax
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=size, process_id=rank)
        rt.initialized = True
        if jax.process_count() != size:
            raise RuntimeError(
                f"wireup {rt.method}: expected {size} processes, runtime "
                f"formed {jax.process_count()} — rendezvous failed")
    return rt
