"""pytorch_ddp_mnist_tpu — a TPU-native training framework.

A from-scratch JAX/XLA re-design of the capabilities of the PyTorch DDP MNIST
reference (Jonathanlyj/pytorch_ddp_mnist): serial baseline training, SPMD
data-parallel training over a TPU device mesh (the reference's NCCL/Gloo/MPI
gradient allreduce replaced by XLA collectives over ICI/DCN), a multi-method
process wireup layer, a sharded parallel data pipeline with a native C++ reader
core (the reference's PnetCDF/MPI-IO analog), an IDX->NetCDF converter, and
launcher entry points for single-host and multi-host runs.

Layer map (mirrors reference SURVEY.md §1):
  L5 launchers   -> scripts/train_*.sh
  L4 config/CLI  -> pytorch_ddp_mnist_tpu.train.config
  L3 wireup/comm -> pytorch_ddp_mnist_tpu.parallel (mesh, wireup, collectives)
  L2 data        -> pytorch_ddp_mnist_tpu.data (idx, netcdf, loader, native C++)
  L1 model/loop  -> pytorch_ddp_mnist_tpu.models, .ops, .train
"""

__version__ = "0.6.0"

# jax API-surface drift (shard_map spelling, threefry default) is absorbed
# in ONE place; importing it here guarantees the alignment happens before
# any framework RNG/SPMD use, whatever submodule the caller enters through.
from . import compat  # noqa: E402,F401
