"""JAX-aware source lint — stdlib `ast` only, no framework import.

Engine for the rule catalog in `rules.py`. Two passes per file:

  1. **Traced-function marking** (purely syntactic): a function is traced
     when it is decorated with a jit-family decorator, or its NAME is
     passed to a trace sink (`jax.jit`, `jax.vmap`, `jax.grad`,
     `jax.value_and_grad`, `jax.lax.scan/cond/while_loop`, `shard_map`,
     `pallas_call`, ...) anywhere in the module — including one
     `functools.partial` hop (``step = partial(body, ...)`` then
     ``lax.scan(step, ...)`` marks ``body``). Matching is by name within
     the module: a deliberate over-approximation that needs no dataflow.

  2. **Rule checks**: traced-scope rules (SYNC001/002/003, the traced part
     of DT001) walk only marked functions' subtrees; module-scope rules
     (COLL001, EXC001, MUT001, MUT002, the jnp-rooted part of DT001) walk
     the whole file.

Baseline: `baseline.json` entries are `(rule, file, stripped source line)`
triples with a human reason. A finding matching an entry is suppressed; an
entry matching nothing is STALE (warned, and `--prune-baseline` rewrites
the file without it); anything else fails the run. Keying on line CONTENT
instead of line number keeps entries stable as unrelated code moves, and
re-surfaces the finding the moment the flagged line itself is edited.

The concurrency auditor (`concurrency.py`: thread-entry map, ASYNC001/
ASYNC002/LOCK001/LOCK002) runs as a third pass through the same engine —
per file here, with one union lock-order graph in `lint_paths` — and its
findings flow into the same baseline/exit-code machinery.

CLI (also reachable as `python -m pytorch_ddp_mnist_tpu lint`):

    python -m pytorch_ddp_mnist_tpu.statics.lint [paths...]
        [--json] [--baseline FILE] [--no-baseline] [--prune-baseline]
        [--check-docs]

Exit codes: 0 clean (stale-only is clean), 1 new findings (or doc drift
under --check-docs), 2 usage.
"""

from __future__ import annotations

import ast
import json
import os
import sys
from typing import Iterable, List, Optional, Sequence, Tuple

try:
    from .rules import (RULES, Finding, dotted_name as _dotted,
                        last_segment as _last, root_segment as _root)
    from . import concurrency
except ImportError:
    # Loaded BY FILE PATH with no package context (the check_telemetry.py
    # copied-alone pattern — a CI host without the framework installed):
    # pull the sibling rules.py and concurrency.py the same way.
    import importlib.util as _ilu

    def _load_sibling(stem: str):
        key = f"_pdmt_statics_{stem}"
        if key in sys.modules:
            return sys.modules[key]
        spec = _ilu.spec_from_file_location(
            key, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              f"{stem}.py"))
        mod = _ilu.module_from_spec(spec)
        sys.modules[key] = mod   # dataclasses needs it
        spec.loader.exec_module(mod)
        return mod

    _rules = _load_sibling("rules")
    RULES, Finding = _rules.RULES, _rules.Finding
    _dotted, _last, _root = (_rules.dotted_name, _rules.last_segment,
                             _rules.root_segment)
    concurrency = _load_sibling("concurrency")

# Call sites whose function-valued arguments become traced code. Last
# dotted segment is matched, so `jax.jit`, `jax.lax.scan` and a bare
# `shard_map` all count.
TRACE_SINKS = {
    "jit", "vmap", "pmap", "grad", "value_and_grad", "scan", "cond",
    "switch", "while_loop", "fori_loop", "shard_map", "pallas_call",
    "checkpoint", "remat", "custom_vjp", "custom_jvp", "make_jaxpr",
    "eval_shape",
}
# Decorator heads that make the decorated def traced.
TRACE_DECORATORS = {"jit", "vmap", "pmap", "pallas_call", "custom_vjp",
                    "custom_jvp", "checkpoint", "remat"}

# jax.lax collectives and the positional-argument count that includes the
# axis name (COLL001).
COLLECTIVE_MIN_ARGS = {
    "psum": 2, "pmean": 2, "pmax": 2, "pmin": 2, "psum_scatter": 2,
    "all_gather": 2, "all_to_all": 2, "ppermute": 2, "pshuffle": 2,
    "pswapaxes": 2, "pvary": 2, "pcast": 2, "axis_index": 1,
}
_COLLECTIVE_ROOTS = {"jax", "lax", "jnp"}

# Static array metadata: branching on these is how builders specialize
# programs, so SYNC003 never descends past them.
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "config"}

_HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_NP_SYNC_CALLS = {"asarray", "array", "copyto", "save", "savez"}


# _dotted/_last/_root live in rules.py (dotted_name/last_segment/
# root_segment) — shared with concurrency.py so the two engines can never
# drift on name resolution.


def _scoped_body(func) -> Iterable[ast.AST]:
    """Walk `func`'s own body, not descending into nested function/class
    definitions (their scopes own their own `global`/lock semantics)."""
    stack = list(func.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class _Linter:
    def __init__(self, tree: ast.Module, path: str, lines: Sequence[str]):
        self.tree = tree
        self.path = path
        self.lines = lines
        self.findings: List[Finding] = []

    # -- plumbing ----------------------------------------------------------

    def flag(self, rule_id: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        content = (self.lines[line - 1].strip()
                   if 0 < line <= len(self.lines) else "")
        self.findings.append(Finding(
            rule=rule_id, path=self.path, line=line, col=col,
            message=message, content=content, hint=RULES[rule_id].hint))

    # -- pass 1: traced-function marking -----------------------------------

    def traced_functions(self) -> List[ast.AST]:
        defs: dict = {}
        decorated: List[ast.AST] = []
        marked: set = set()
        aliases: dict = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)
                for dec in node.decorator_list:
                    heads = {_last(n) for n in ast.walk(dec)
                             if isinstance(n, (ast.Name, ast.Attribute))}
                    if heads & TRACE_DECORATORS:
                        decorated.append(node)
                        break
            elif isinstance(node, ast.Call):
                if _last(node.func) in TRACE_SINKS:
                    for arg in node.args:
                        for n in ast.walk(arg):
                            if isinstance(n, ast.Name):
                                marked.add(n.id)
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tgt, val = node.targets[0].id, node.value
                if isinstance(val, ast.Call) and _last(val.func) == "partial":
                    refs = {n.id for a in val.args
                            for n in ast.walk(a) if isinstance(n, ast.Name)}
                    aliases.setdefault(tgt, set()).update(refs - {"partial"})
                elif isinstance(val, ast.Name):
                    aliases.setdefault(tgt, set()).add(val.id)
        # one-hop-at-a-time fixpoint: a marked alias marks what it wraps
        changed = True
        while changed:
            changed = False
            for tgt, refs in aliases.items():
                if tgt in marked and not refs <= marked:
                    marked |= refs
                    changed = True
        out = list(decorated)
        seen = {id(n) for n in decorated}
        for name in marked & set(defs):
            for node in defs[name]:
                if id(node) not in seen:
                    seen.add(id(node))
                    out.append(node)
        # drop defs nested inside another traced def (parent walk covers
        # them; avoids double reports)
        inner: set = set()
        for node in out:
            for sub in ast.walk(node):
                if sub is not node and id(sub) in seen:
                    inner.add(id(sub))
        return [n for n in out if id(n) not in inner]

    # -- traced-scope rules -------------------------------------------------

    def check_traced(self, func) -> None:
        fname = getattr(func, "name", "<lambda>")
        for node in ast.walk(func):
            if isinstance(node, ast.Call):
                self._sync001(node, fname)
                self._sync002(node, fname)
            if isinstance(node, (ast.If, ast.While, ast.IfExp, ast.Assert)):
                self._sync003(node, fname)
            if isinstance(node, ast.Attribute) \
                    and node.attr in ("float64", "complex128"):
                self.flag("DT001", node,
                          f"{node.attr} inside traced function "
                          f"'{fname}' (TPUs have no f64; the wire "
                          f"contract is f32 or narrower)")
            if isinstance(node, ast.keyword) and node.arg == "dtype" \
                    and isinstance(node.value, ast.Constant) \
                    and node.value.value in ("float64", "f8", "double"):
                self.flag("DT001", node.value,
                          f"dtype={node.value.value!r} inside traced "
                          f"function '{fname}'")

    def _sync001(self, node: ast.Call, fname: str) -> None:
        callee = node.func
        if isinstance(callee, ast.Name) and callee.id == "float":
            if node.args and isinstance(node.args[0], ast.Constant):
                return  # float("inf") etc: a literal, not a tracer
            self.flag("SYNC001", node,
                      f"builtin float() inside traced function '{fname}' "
                      f"coerces a tracer to a host scalar")
            return
        if isinstance(callee, ast.Attribute):
            if callee.attr in _HOST_SYNC_METHODS:
                self.flag("SYNC001", node,
                          f".{callee.attr}() inside traced function "
                          f"'{fname}' forces a device->host sync")
                return
            d = _dotted(callee)
            if d in ("jax.device_get",):
                self.flag("SYNC001", node,
                          f"jax.device_get inside traced function "
                          f"'{fname}' forces a device->host sync")
                return
            if _root(callee) in ("np", "numpy") \
                    and callee.attr in _NP_SYNC_CALLS:
                self.flag("SYNC001", node,
                          f"np.{callee.attr} inside traced function "
                          f"'{fname}' materializes a tracer on host")

    def _sync002(self, node: ast.Call, fname: str) -> None:
        callee = node.func
        if not isinstance(callee, ast.Attribute):
            return
        d = _dotted(callee) or ""
        root = _root(callee)
        if root == "time":
            self.flag("SYNC002", node,
                      f"{d}() inside traced function '{fname}' freezes "
                      f"one trace-time timestamp into the program")
        elif root == "random" or d.startswith(("np.random.",
                                               "numpy.random.")):
            self.flag("SYNC002", node,
                      f"{d}() inside traced function '{fname}' draws host "
                      f"randomness once at trace time")
        elif callee.attr in ("now", "today", "utcnow") \
                and _last(callee.value) == "datetime":
            self.flag("SYNC002", node,
                      f"{d}() inside traced function '{fname}' freezes "
                      f"one trace-time wall clock into the program")

    def _sync003(self, node, fname: str) -> None:
        offender = self._tracer_call_in(node.test)
        if offender is not None:
            kind = type(node).__name__.lower()
            self.flag("SYNC003", node,
                      f"Python {kind} on the result of "
                      f"{_dotted(offender.func) or 'a jax call'} inside "
                      f"traced function '{fname}' coerces a tracer to "
                      f"bool")

    def _tracer_call_in(self, expr) -> Optional[ast.Call]:
        """First jnp/jax/lax-rooted Call in `expr`, pruning static-metadata
        attribute accesses (.shape/.dtype/... and jax.config)."""
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Attribute) \
                    and node.attr in _STATIC_ATTRS:
                continue  # don't descend: static metadata is host-legal
            if isinstance(node, ast.Call):
                d = _dotted(node.func) or ""
                if d.split(".", 1)[0] in ("jnp", "jax", "lax") \
                        and not d.startswith("jax.config"):
                    return node
            stack.extend(ast.iter_child_nodes(node))
        return None

    # -- module-scope rules --------------------------------------------------

    def check_module(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Attribute) and node.attr == "float64" \
                    and _root(node) == "jnp":
                self.flag("DT001", node,
                          "jnp.float64 (device f64) — TPUs have no f64 "
                          "ALU and x64 is off framework-wide")
            if isinstance(node, ast.Call):
                self._x64_flip(node)
                self._coll001(node)
            if isinstance(node, ast.ExceptHandler):
                self._exc001(node)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                self._mut001(node)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._mut002(node)

    def _x64_flip(self, node: ast.Call) -> None:
        callee = node.func
        if isinstance(callee, ast.Attribute) and callee.attr == "update" \
                and len(node.args) >= 2 \
                and isinstance(node.args[0], ast.Constant) \
                and node.args[0].value == "jax_enable_x64" \
                and isinstance(node.args[1], ast.Constant) \
                and node.args[1].value:
            self.flag("DT001", node,
                      "jax_enable_x64 flipped on — every op doubles and "
                      "the wire-dtype contract breaks")

    def _coll001(self, node: ast.Call) -> None:
        callee = node.func
        if not isinstance(callee, ast.Attribute):
            return
        need = COLLECTIVE_MIN_ARGS.get(callee.attr)
        if need is None or _root(callee) not in _COLLECTIVE_ROOTS:
            return
        kwargs = {k.arg for k in node.keywords}
        if len(node.args) < need and "axis_name" not in kwargs:
            self.flag("COLL001", node,
                      f"jax.lax.{callee.attr} without an explicit axis "
                      f"name")

    def _exc001(self, node: ast.ExceptHandler) -> None:
        def broad(t) -> bool:
            return t is None or _last(t) in ("Exception", "BaseException")

        t = node.type
        is_broad = broad(t) or (isinstance(t, ast.Tuple)
                                and any(broad(e) for e in t.elts))
        if not is_broad:
            return
        if any(isinstance(n, ast.Raise) for n in ast.walk(node)):
            return  # re-raising handlers don't swallow the signal
        what = "bare except" if t is None else f"except {_last(t) or '...'}"
        self.flag("EXC001", node,
                  f"{what} without re-raise swallows TrainingHealthError/"
                  f"CheckpointError too")

    def _mut001(self, node) -> None:
        defaults = list(getattr(node.args, "defaults", []))
        defaults += [d for d in getattr(node.args, "kw_defaults", []) if d]
        for d in defaults:
            if isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(d, ast.Call)
                    and isinstance(d.func, ast.Name)
                    and d.func.id in ("list", "dict", "set")
                    and not d.args and not d.keywords):
                name = getattr(node, "name", "<lambda>")
                self.flag("MUT001", d,
                          f"mutable default argument in '{name}' is "
                          f"shared across every call")

    def _mut002(self, node) -> None:
        globals_: List[ast.Global] = []
        assigned: set = set()
        locked = False
        for n in _scoped_body(node):
            if isinstance(n, ast.Global):
                globals_.append(n)
            elif isinstance(n, ast.Assign):
                assigned |= {t.id for t in n.targets
                             if isinstance(t, ast.Name)}
            elif isinstance(n, (ast.AugAssign, ast.AnnAssign)) \
                    and isinstance(n.target, ast.Name):
                assigned.add(n.target.id)
            elif isinstance(n, ast.With):
                for item in n.items:
                    d = _dotted(item.context_expr) or ""
                    if isinstance(item.context_expr, ast.Call):
                        d = _dotted(item.context_expr.func) or ""
                    if "lock" in d.lower():
                        locked = True
            elif isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "acquire":
                locked = True
        if locked:
            return
        for g in globals_:
            hot = sorted(set(g.names) & assigned)
            if hot:
                self.flag("MUT002", g,
                          f"'{node.name}' reassigns module global(s) "
                          f"{', '.join(hot)} without holding a lock")

    # -- driver --------------------------------------------------------------

    def run(self) -> List[Finding]:
        for func in self.traced_functions():
            self.check_traced(func)
        self.check_module()
        # stable order + dedup (a def marked through two routes walks once,
        # but belt and braces)
        uniq = {}
        for f in self.findings:
            uniq[(f.rule, f.path, f.line, f.col, f.message)] = f
        return sorted(uniq.values(), key=lambda f: (f.path, f.line, f.col,
                                                    f.rule))


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def lint_source(src: str, path: str = "<string>") -> List[Finding]:
    """Lint one source string — the PR 8 rule set plus the concurrency
    auditor (LOCK002 sees only this file's lock-order edges; lint_paths
    runs it over the union graph). `path` is stamped verbatim."""
    tree = ast.parse(src, filename=path)
    findings = _Linter(tree, path, src.splitlines()).run()
    findings.extend(concurrency.analyze_source(src, path, tree=tree))
    return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))


def _iter_py_files(paths: Iterable[str]) -> List[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                out.extend(os.path.join(dirpath, f)
                           for f in filenames if f.endswith(".py"))
        else:
            out.append(p)
    return sorted(set(out))


def lint_paths(paths: Iterable[str], root: Optional[str] = None
               ) -> Tuple[List[Finding], int]:
    """Lint files/directories; returns (findings, files checked). Finding
    paths are repo-root-relative ('/'-separated) so baseline entries are
    machine-independent. The concurrency auditor runs with ONE shared
    lock-order graph across every file, so LOCK002 catches a lock pair
    nested one way in module A and the other way in module B."""
    root = root or repo_root()
    findings: List[Finding] = []
    auditor = concurrency.ConcurrencyAuditor()
    files = _iter_py_files(paths)
    for path in files:
        with open(path, encoding="utf-8") as f:
            src = f.read()
        rel = os.path.relpath(os.path.abspath(path), root)
        if rel.startswith(".."):
            rel = os.path.abspath(path)
        rel = rel.replace(os.sep, "/")
        tree = ast.parse(src, filename=rel)
        findings.extend(_Linter(tree, rel, src.splitlines()).run())
        auditor.add_source(src, rel, tree=tree)
    findings.extend(auditor.finish())
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, len(files)


# -- baseline ----------------------------------------------------------------

def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def load_baseline(path: str) -> dict:
    """{"version": 1, "entries": [...]} — a missing file is an empty
    baseline; a malformed one is an error (a silently ignored baseline
    would un-suppress everything and fail CI confusingly)."""
    if not os.path.exists(path):
        return {"version": 1, "entries": []}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or not isinstance(
            data.get("entries"), list):
        raise ValueError(f"baseline {path}: expected an object with an "
                         f"'entries' list")
    for e in data["entries"]:
        missing = [k for k in ("rule", "file", "content", "reason")
                   if k not in e]
        if missing:
            raise ValueError(f"baseline {path}: entry {e!r} missing "
                             f"{missing} (every suppression carries a "
                             f"reason)")
    return data


def apply_baseline(findings: List[Finding], baseline: dict
                   ) -> Tuple[List[Finding], List[Finding], List[dict]]:
    """(new, suppressed, stale_entries). An entry suppresses every finding
    with its (rule, file, content) key; an entry matching nothing is
    stale."""
    by_key = {}
    for e in baseline.get("entries", []):
        by_key[(e["rule"], e["file"], e["content"])] = e
    matched: set = set()
    new, suppressed = [], []
    for f in findings:
        if f.key() in by_key:
            matched.add(f.key())
            suppressed.append(f)
        else:
            new.append(f)
    stale = [e for k, e in by_key.items() if k not in matched]
    return new, suppressed, stale


def prune_baseline(path: str, baseline: dict, stale: List[dict]) -> int:
    """Rewrite `path` without the stale entries; returns how many were
    dropped. Order and reasons of surviving entries are preserved."""
    stale_keys = {(e["rule"], e["file"], e["content"]) for e in stale}
    kept = [e for e in baseline.get("entries", [])
            if (e["rule"], e["file"], e["content"]) not in stale_keys]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": baseline.get("version", 1), "entries": kept},
                  f, indent=2)
        f.write("\n")
    return len(baseline.get("entries", [])) - len(kept)


# -- rule-catalog / doc drift ------------------------------------------------

def default_docs_path() -> str:
    return os.path.join(repo_root(), "docs", "STATIC_ANALYSIS.md")


def check_docs(doc_path: Optional[str] = None) -> List[str]:
    """Assert the rule catalog and docs/STATIC_ANALYSIS.md agree: every
    rule ID in rules.py has a `| \\`ID\\` |` table row, and every ID the
    doc tables name exists in the catalog. Returns human-readable drift
    messages ([] = in sync). The doc side matches backticked IDs at the
    start of a table row, so prose mentions of retired rules don't count
    as rows."""
    import re
    path = doc_path or default_docs_path()
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError as e:
        return [f"cannot read rule-catalog doc: {e}"]
    doc_ids = set(re.findall(r"^\|\s*`([A-Z]+[0-9]{3})`", text,
                             flags=re.MULTILINE))
    errors = [f"rule {rid} has no table row in {os.path.basename(path)}"
              for rid in sorted(set(RULES) - doc_ids)]
    errors += [f"{os.path.basename(path)} documents unknown rule {rid} "
               f"(retired? drop the row)"
               for rid in sorted(doc_ids - set(RULES))]
    return errors


# -- CLI ---------------------------------------------------------------------

def default_targets() -> List[str]:
    """The whole-package lint surface: the framework package, bench.py and
    scripts/ (tests are excluded — fixtures there violate rules on
    purpose)."""
    root = repo_root()
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = [pkg]
    for extra in ("bench.py", "scripts"):
        p = os.path.join(root, extra)
        if os.path.exists(p):
            out.append(p)
    return out


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        prog=os.path.basename(sys.argv[0]),
        description="JAX-aware source lint (stdlib ast; rule catalog in "
                    "docs/STATIC_ANALYSIS.md). Exit 0 clean, 1 new "
                    "findings, 2 usage.")
    p.add_argument("paths", nargs="*",
                   help="files/directories to lint (default: the package, "
                        "bench.py and scripts/)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report on stdout")
    p.add_argument("--baseline", default=default_baseline_path(),
                   help="baseline file of accepted findings "
                        "(default: statics/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline (report everything)")
    p.add_argument("--prune-baseline", action="store_true",
                   help="rewrite the baseline without stale entries")
    p.add_argument("--check-docs", action="store_true",
                   help="check rule-catalog/doc drift instead of linting: "
                        "every rule ID in statics/rules.py must have a "
                        "table row in docs/STATIC_ANALYSIS.md and vice "
                        "versa (exit 1 on drift)")
    a = p.parse_args(argv)

    if a.check_docs:
        drift = check_docs()
        for msg in drift:
            print(f"lint: doc drift: {msg}", file=sys.stderr)
        if drift:
            return 1
        print(f"lint: OK — rule catalog and docs/STATIC_ANALYSIS.md "
              f"agree on {len(RULES)} rule(s)")
        return 0

    try:
        findings, n_files = lint_paths(a.paths or default_targets())
    except (OSError, SyntaxError, UnicodeDecodeError, ValueError) as e:
        # a missing/unreadable/unparsable target is a USAGE problem (fix
        # the path or the file), not a rule finding — the documented exit
        # 2, with the offending file named, instead of a raw traceback
        print(f"lint: cannot lint target: {e}", file=sys.stderr)
        return 2
    if a.no_baseline:
        baseline = {"version": 1, "entries": []}
    else:
        try:
            baseline = load_baseline(a.baseline)
        except (ValueError, OSError) as e:
            print(f"lint: {e}", file=sys.stderr)
            return 2
    new, suppressed, stale = apply_baseline(findings, baseline)

    pruned = 0
    if a.prune_baseline and stale and not a.no_baseline:
        pruned = prune_baseline(a.baseline, baseline, stale)
        stale = []

    if a.json:
        print(json.dumps({
            "files": n_files,
            "findings": [f.to_json() for f in new],
            "suppressed": len(suppressed),
            "stale_baseline_entries": stale,
            "pruned": pruned,
        }, indent=2))
    else:
        for f in new:
            print(f.render())
    for e in stale:
        print(f"lint: warning: stale baseline entry (finding gone): "
              f"{e['rule']} {e['file']}: {e['content']!r} — re-run with "
              f"--prune-baseline to drop it", file=sys.stderr)
    if pruned:
        print(f"lint: pruned {pruned} stale baseline entr"
              f"{'y' if pruned == 1 else 'ies'} from {a.baseline}",
              file=sys.stderr)
    if new:
        print(f"lint: FAIL — {len(new)} new finding(s) across {n_files} "
              f"file(s) ({len(suppressed)} baselined)", file=sys.stderr)
        return 1
    if not a.json:
        print(f"lint: OK — 0 new findings across {n_files} file(s) "
              f"({len(suppressed)} baselined"
              + (f", {len(stale)} stale" if stale else "") + ")")
    return 0


if __name__ == "__main__":
    sys.exit(main())
