"""Concurrency auditor — thread-entry map + ASYNC/LOCK rules, stdlib `ast`.

PR 8's lint reads single statements; the bugs that survived it were
*interaction* bugs: a blocking call on the serve event loop (the PR 9
SLOWindow sort), shared state written with and without its lock (the PR 6
snapshot race), check-then-install races on module globals. This engine
checks those classes the same way `lint.py` checks the sync/dtype
contracts — purely syntactic, loadable by file path on hosts without the
framework, flowing into the same baseline/exit-code machinery.

Three passes per file:

  1. **Thread-entry map**: which functions run on which execution context.
     Event-loop residents are every `async def`, every function whose NAME
     is scheduled onto the loop (`call_soon`/`call_later`/`call_at`/
     `call_soon_threadsafe`/`create_task`/`ensure_future`/
     `run_coroutine_threadsafe`), and — fixpoint — every same-module
     function a resident calls (by bare or method name: the lint's
     name-within-module over-approximation). `threading.Thread(target=...)`
     targets and `signal.signal`/`add_signal_handler` handlers land in the
     map too (`ConcurrencyAuditor.entries`, for reports and docs).
  2. **ASYNC rules** over loop-resident bodies: ASYNC001 (blocking call —
     `time.sleep`, file/`subprocess`/`shutil` IO, `sorted()`/`.sort()`
     over a stored window, `block_until_ready`/`device_sync`, lock
     `.acquire()` with no timeout) and ASYNC002 (`await` lexically inside
     a sync `with <lock>:` block; `async with` is exempt).
  3. **LOCK rules**: LOCK001 — a `self.X` attribute (grouped per class)
     or a `global` name written under a lock at one site and bare at
     another (constructors exempt); LOCK002 — the lock-order graph from
     nested with-blocks/`.acquire()` sites, lock identity by qualified
     name (`self._lock` -> `ClassName._lock`, module globals by name, so
     the graph unions across files), any cycle flagged at the edges that
     close it. The lexical graph cannot see cross-module call chains —
     `statics.sanitize.lock_trace()` is the runtime half that can.

"Lock-ish" matching is by name, like MUT002: a context expression whose
dotted spelling contains ``lock``/``mutex``, or a direct
``threading.Lock()/RLock()`` call.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Dict, Iterable, List, Optional, Set, Tuple

try:
    from .rules import (RULES, Finding, dotted_name as _dotted,
                        last_segment as _last, root_segment as _root)
except ImportError:
    # Loaded BY FILE PATH with no package context (the check_telemetry.py
    # copied-alone pattern): pull the sibling rules.py the same way,
    # reusing lint.py's module instance when it got there first.
    import importlib.util as _ilu
    if "_pdmt_statics_rules" in sys.modules:
        _rules = sys.modules["_pdmt_statics_rules"]
    else:
        _spec = _ilu.spec_from_file_location(
            "_pdmt_statics_rules",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "rules.py"))
        _rules = _ilu.module_from_spec(_spec)
        sys.modules["_pdmt_statics_rules"] = _rules
        _spec.loader.exec_module(_rules)
    RULES, Finding = _rules.RULES, _rules.Finding
    _dotted, _last, _root = (_rules.dotted_name, _rules.last_segment,
                             _rules.root_segment)

# Call sites whose function-valued arguments run on the event loop even
# though they are not themselves `async def`.
LOOP_CALLBACK_SINKS = {
    "call_soon", "call_later", "call_at", "call_soon_threadsafe",
    "create_task", "ensure_future", "run_coroutine_threadsafe",
}

# ASYNC001's blocking-call vocabulary (module-rooted).
_BLOCKING_ROOTS = {"subprocess", "shutil"}      # any call under these
_OS_BLOCKING = {"makedirs", "replace", "rename", "remove", "unlink",
                "fsync", "stat", "listdir", "system", "popen"}
_LOCK_FACTORIES = {"Lock", "RLock", "Semaphore", "BoundedSemaphore",
                   "Condition"}


def _is_lockish(expr) -> bool:
    """Name-based lock detection (the MUT002 convention: name your locks
    `*lock*`). A Call is unwrapped so `threading.Lock()` inline counts."""
    if isinstance(expr, ast.Call):
        if _last(expr.func) in _LOCK_FACTORIES:
            return True
        expr = expr.func
    d = _dotted(expr) or ""
    low = d.lower()
    return "lock" in low or "mutex" in low


def _scoped_walk(root) -> Iterable[ast.AST]:
    """Walk `root`'s body without descending into nested function/class
    definitions (they own their own residency/locking story)."""
    stack = list(root.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


_CTOR_NAMES = {"__init__", "__new__", "__post_init__", "__init_subclass__"}


class _FileAudit:
    """One file's concurrency pass. Produces per-file findings plus the
    file's lock-order edges for the cross-file LOCK002 graph."""

    def __init__(self, tree: ast.Module, path: str, lines):
        self.tree = tree
        self.path = path
        self.lines = lines
        self.findings: List[Finding] = []
        # (src_lock_id, dst_lock_id, line, col, content)
        self.edges: List[Tuple[str, str, int, int, str]] = []
        self.entries: Dict[str, Set[str]] = {
            "loop": set(), "thread": set(), "signal": set()}
        # def name -> [(node, class name or None)]
        self._defs: Dict[str, List[Tuple[ast.AST, Optional[str]]]] = {}
        self._reason: Dict[int, str] = {}   # id(def node) -> residency why

    # -- plumbing ----------------------------------------------------------

    def flag(self, rule_id: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        content = (self.lines[line - 1].strip()
                   if 0 < line <= len(self.lines) else "")
        self.findings.append(Finding(
            rule=rule_id, path=self.path, line=line, col=col,
            message=message, content=content, hint=RULES[rule_id].hint))

    # -- pass 1: thread-entry map ------------------------------------------

    def _collect_defs(self) -> None:
        def visit(node, cls: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, child.name)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    self._defs.setdefault(child.name, []).append(
                        (child, cls))
                    visit(child, cls)
                else:
                    visit(child, cls)
        visit(self.tree, None)

    def _arg_names(self, exprs) -> Set[str]:
        names: Set[str] = set()
        for e in exprs:
            for n in ast.walk(e):
                if isinstance(n, ast.Name):
                    names.add(n.id)
                elif isinstance(n, ast.Attribute):
                    names.add(n.attr)
        return names

    def _mark_residents(self) -> List[Tuple[ast.AST, Optional[str]]]:
        resident: Dict[int, Tuple[ast.AST, Optional[str]]] = {}
        for name, defs in self._defs.items():
            for node, cls in defs:
                if isinstance(node, ast.AsyncFunctionDef):
                    resident[id(node)] = (node, cls)
                    self._reason[id(node)] = f"async def '{name}'"
                    self.entries["loop"].add(name)
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            sink = _last(node.func)
            if sink in LOOP_CALLBACK_SINKS:
                for nm in self._arg_names(node.args):
                    for fn, cls in self._defs.get(nm, ()):
                        resident.setdefault(id(fn), (fn, cls))
                        self._reason.setdefault(
                            id(fn), f"'{nm}' scheduled on the event loop "
                                    f"via {sink}()")
                        self.entries["loop"].add(nm)
            elif sink == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        self.entries["thread"] |= self._arg_names([kw.value])
            elif sink in ("signal", "add_signal_handler") \
                    and len(node.args) >= 2:
                self.entries["signal"] |= self._arg_names(node.args[1:])
        # fixpoint: a resident's same-module callees become resident
        changed = True
        while changed:
            changed = False
            for fn, cls in list(resident.values()):
                for sub in _scoped_walk(fn):
                    if not isinstance(sub, ast.Call):
                        continue
                    callee = _last(sub.func)
                    for cand, ccls in self._defs.get(callee, ()):
                        if id(cand) not in resident:
                            resident[id(cand)] = (cand, ccls)
                            self._reason[id(cand)] = (
                                f"'{callee}' called from event-loop-"
                                f"resident '{fn.name}'")
                            self.entries["loop"].add(callee)
                            changed = True
        return list(resident.values())

    # -- pass 2: ASYNC rules -----------------------------------------------

    def _check_async001(self, fn) -> None:
        where = self._reason.get(id(fn), f"'{fn.name}'")
        for node in _scoped_walk(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = node.func
            d = _dotted(callee) or ""
            last = _last(callee)
            root = _root(callee)
            what = None
            if d == "time.sleep":
                what = "time.sleep() parks the whole loop, not one task"
            elif root in _BLOCKING_ROOTS:
                what = f"{d}() does blocking process/file IO"
            elif root == "os" and last in _OS_BLOCKING:
                what = f"{d}() does blocking file IO"
            elif isinstance(callee, ast.Name) and callee.id == "open":
                what = "open() does blocking file IO"
            elif last in ("block_until_ready", "device_sync"):
                what = (f"{d or last}() forces a device drain on the "
                        f"loop thread")
            elif last == "acquire":
                kwargs = {k.arg for k in node.keywords}
                nonblocking = (node.args
                               and isinstance(node.args[0], ast.Constant)
                               and node.args[0].value is False)
                if "timeout" not in kwargs and not nonblocking:
                    what = (f"{d or '.acquire'}() with no timeout can "
                            f"block the loop behind another thread")
            elif isinstance(callee, ast.Name) and callee.id == "sorted" \
                    and node.args \
                    and isinstance(node.args[0],
                                   (ast.Name, ast.Attribute)):
                what = (f"sorted({_dotted(node.args[0])}) re-sorts a "
                        f"stored window per call (the PR 9 SLOWindow "
                        f"class)")
            elif isinstance(callee, ast.Attribute) and callee.attr == "sort" \
                    and isinstance(callee.value, (ast.Name, ast.Attribute)):
                what = (f"{d}() sorts a stored window in place on the "
                        f"loop thread")
            if what:
                self.flag("ASYNC001", node,
                          f"{what} — reachable from the serve event loop "
                          f"({where})")

    def _check_async002(self, fn) -> None:
        def walk(node, lock_node) -> None:
            if isinstance(node, ast.Await) and lock_node is not None:
                self.flag("ASYNC002", node,
                          f"await inside `with "
                          f"{_dotted(lock_node) or 'lock'}:` in "
                          f"'{fn.name}' holds a sync lock across a "
                          f"suspension point")
                # keep walking: one with-block can hold several awaits
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)) \
                    and node is not fn:
                return
            held = lock_node
            if isinstance(node, ast.With):
                for item in node.items:
                    if _is_lockish(item.context_expr):
                        held = item.context_expr
                        break
            for child in ast.iter_child_nodes(node):
                walk(child, held)
        walk(fn, None)

    # -- pass 3: LOCK rules ------------------------------------------------

    def _lock_id(self, expr, cls: Optional[str]) -> Optional[str]:
        if isinstance(expr, ast.Call):
            expr = expr.func
        d = _dotted(expr)
        if d is None:
            return None
        if d.startswith("self.") and cls:
            return f"{cls}.{d[5:]}"
        return d

    def _check_lock001_and_edges(self) -> None:
        # (kind, scope, name) -> [(locked, node, fn name)]
        writes: Dict[Tuple[str, str, str],
                     List[Tuple[bool, ast.AST, str]]] = {}
        for name, defs in self._defs.items():
            for fn, cls in defs:
                self._scan_fn(fn, cls, writes)
        for (kind, scope, name), sites in writes.items():
            if not any(locked for locked, _, _ in sites):
                continue
            spelled = f"self.{name}" if kind == "attr" else name
            guarded_in = sorted({f for locked, _, f in sites if locked})
            for locked, node, fname in sites:
                if locked:
                    continue
                self.flag("LOCK001", node,
                          f"{spelled} written in '{fname}' without the "
                          f"lock that guards it in "
                          f"{', '.join(repr(g) for g in guarded_in)} — "
                          f"the unlocked write races every locked "
                          f"reader/writer")

    def _scan_fn(self, fn, cls: Optional[str], writes) -> None:
        declared_globals: Set[str] = set()
        for node in _scoped_walk(fn):
            if isinstance(node, ast.Global):
                declared_globals |= set(node.names)
        is_ctor = fn.name in _CTOR_NAMES

        def record_write(target, locked: bool, node) -> None:
            if is_ctor:
                return
            if isinstance(target, ast.Attribute) \
                    and isinstance(target.value, ast.Name) \
                    and target.value.id == "self" and cls:
                writes.setdefault(("attr", cls, target.attr), []).append(
                    (locked, node, fn.name))
            elif isinstance(target, ast.Name) \
                    and target.id in declared_globals:
                writes.setdefault(("global", self.path, target.id),
                                  []).append((locked, node, fn.name))

        def walk(node, held: Tuple[str, ...]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)) \
                    and node is not fn:
                return
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    record_write(t, bool(held), node)
            elif isinstance(node, ast.AugAssign) or (
                    isinstance(node, ast.AnnAssign)
                    and node.value is not None):
                # a value-less AnnAssign (`self._n: int`) is a pure
                # annotation: no store happens at runtime
                record_write(node.target, bool(held), node)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "acquire" \
                    and _is_lockish(node.func.value):
                lid = self._lock_id(node.func.value, cls)
                if lid is not None:
                    for h in held:
                        if h != lid:
                            self._edge(h, lid, node)
            new_held = held
            if isinstance(node, ast.With):
                for item in node.items:
                    if _is_lockish(item.context_expr):
                        lid = self._lock_id(item.context_expr, cls)
                        if lid is not None:
                            for h in held:
                                if h != lid:
                                    self._edge(h, lid, item.context_expr)
                            if lid not in new_held:
                                new_held = new_held + (lid,)
            for child in ast.iter_child_nodes(node):
                walk(child, new_held)

        walk(fn, ())

    def _edge(self, src: str, dst: str, node) -> None:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        content = (self.lines[line - 1].strip()
                   if 0 < line <= len(self.lines) else "")
        self.edges.append((src, dst, line, col, content))

    # -- driver ------------------------------------------------------------

    def run(self) -> None:
        self._collect_defs()
        for fn, _cls in self._mark_residents():
            self._check_async001(fn)
            if isinstance(fn, ast.AsyncFunctionDef):
                self._check_async002(fn)
        self._check_lock001_and_edges()


class ConcurrencyAuditor:
    """Feed files with `add_source`; `finish()` runs LOCK002 over the
    union lock-order graph (lock ids are class-/name-qualified, not
    path-qualified, so a lock nested differently in two files still forms
    a cycle) and returns every finding."""

    def __init__(self):
        self._findings: List[Finding] = []
        # (src, dst) -> (path, line, col, content) of the first such edge
        self._edges: Dict[Tuple[str, str],
                          Tuple[str, int, int, str]] = {}
        self.entries: Dict[str, Set[str]] = {
            "loop": set(), "thread": set(), "signal": set()}

    def add_source(self, src: str, path: str = "<string>", *,
                   tree: Optional[ast.Module] = None) -> List[Finding]:
        """Audit one file; returns (and retains) its per-file findings.
        Pass `tree` when the caller already parsed `src` (lint_paths
        does) — parsing dominates the pass, so the engines share one."""
        if tree is None:
            tree = ast.parse(src, filename=path)
        audit = _FileAudit(tree, path, src.splitlines())
        audit.run()
        self._findings.extend(audit.findings)
        for key, names in audit.entries.items():
            self.entries[key] |= names
        for src_id, dst_id, line, col, content in audit.edges:
            self._edges.setdefault((src_id, dst_id),
                                   (path, line, col, content))
        return audit.findings

    def edge_graph(self) -> Dict[str, Set[str]]:
        adj: Dict[str, Set[str]] = {}
        for a, b in self._edges:
            adj.setdefault(a, set()).add(b)
        return adj

    def _cycle_through(self, a: str, b: str) -> Optional[List[str]]:
        """A path b -> ... -> a in the edge graph (so edge a->b closes a
        cycle), or None."""
        adj = self.edge_graph()
        stack, seen = [(b, [b])], set()
        while stack:
            node, path = stack.pop()
            if node == a:
                return path
            if node in seen:
                continue
            seen.add(node)
            for nxt in sorted(adj.get(node, ())):
                stack.append((nxt, path + [nxt]))
        return None

    def finish(self) -> List[Finding]:
        """LOCK002 over the union graph, then every finding, sorted."""
        for (a, b), (path, line, col, content) in sorted(
                self._edges.items()):
            cycle = self._cycle_through(a, b)
            if cycle is not None:
                loop = " -> ".join([a] + cycle)
                self._findings.append(Finding(
                    rule="LOCK002", path=path, line=line, col=col,
                    message=f"lock order cycle {loop}: this edge "
                            f"acquires {b} while holding {a}, the "
                            f"reverse order exists elsewhere (potential "
                            f"deadlock)",
                    content=content, hint=RULES["LOCK002"].hint))
        uniq = {}
        for f in self._findings:
            uniq[(f.rule, f.path, f.line, f.col, f.message)] = f
        return sorted(uniq.values(),
                      key=lambda f: (f.path, f.line, f.col, f.rule))


def analyze_source(src: str, path: str = "<string>", *,
                   tree: Optional[ast.Module] = None) -> List[Finding]:
    """Single-file audit (LOCK002 sees only this file's edges)."""
    auditor = ConcurrencyAuditor()
    auditor.add_source(src, path, tree=tree)
    return auditor.finish()
