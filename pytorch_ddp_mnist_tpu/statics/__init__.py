"""statics/ — JAX-aware static analysis: the repo's contracts, machine-checked.

Three static passes behind one CLI (`python -m pytorch_ddp_mnist_tpu lint`
/ `... audit-program`) plus a runtime-sanitizer layer:

  * **Source lint** (`rules.py` + `lint.py`, stdlib `ast` only — the
    check_telemetry.py discipline: loadable by file path on hosts without
    jax): JAX/TPU-specific rules with stable IDs — host syncs and wall
    clocks inside traced code, Python `if` on tracer values, f64 dtypes,
    collectives without an explicit axis name, overbroad `except` that
    would swallow `TrainingHealthError`/`CheckpointError`, mutable default
    args, and module-global reassignment without a lock (the PR 6 tracer
    race, as a rule). A committed `baseline.json` suppresses accepted
    findings with a reason string, so CI fails only on NEW ones.

  * **Concurrency auditor** (`concurrency.py`, same discipline, same
    baseline/CLI plumbing): a thread-entry map (async defs + loop-
    scheduled callbacks, `threading.Thread` targets, signal handlers) and
    the interaction rules PR 8's per-statement lint cannot see — blocking
    calls on the serve event loop (ASYNC001, the PR 9 sort-per-request
    class), `await` under a sync lock (ASYNC002), shared state written
    both under and outside a lock (LOCK001, the snapshot-race class), and
    lock-acquisition-order cycles over a cross-file graph (LOCK002).

  * **Program auditor** (`jaxpr_audit.py`): lower the full step-program
    matrix (comm x overlap x {streaming step, fit_cached scan body}) over
    a deviceless 8-way AbstractMesh and walk the jaxpr asserting the
    structural contracts the hand-written pins guard one test at a time —
    collective kinds/counts per strategy and per bucket, wire dtypes (the
    wire never carries f32 for bf16/int8), no f64, no host callbacks, and
    bytes-on-wire recomputed from the audited program matching the
    `ddp.bytes_on_wire` cost model.

  * **Runtime sanitizers** (`sanitize.py`): what the static passes cannot
    prove, checked on a live run — `no_host_sync()` (the PR 6/9 test
    interception technique as a context manager: block_until_ready +
    device-fetch budgets), `event_loop_stall()` (per-callback stall
    detector on the asyncio loop), `lock_trace()` (runtime acquisition-
    order recording that confirms/refutes LOCK002). `make sanitize-smoke`
    arms all three over the serve selftest and a short training run.

`lint`/`concurrency`/`sanitize` import nothing outside the stdlib at
module scope; `jaxpr_audit` (and `no_host_sync.__enter__`) import jax
lazily, so importing this package stays cheap.

docs/STATIC_ANALYSIS.md carries the rule catalog, the per-strategy audit
contract table, the baseline workflow, and the sanitizer guide.
"""

from __future__ import annotations

from .rules import CONCURRENCY_RULES, RULES, Finding, Rule  # noqa: F401
from .lint import check_docs, lint_paths, lint_source, load_baseline  # noqa: F401
from .concurrency import ConcurrencyAuditor, analyze_source  # noqa: F401
from . import sanitize  # noqa: F401

__all__ = ["RULES", "CONCURRENCY_RULES", "Rule", "Finding", "lint_source",
           "lint_paths", "load_baseline", "check_docs",
           "ConcurrencyAuditor", "analyze_source", "sanitize"]
