"""statics/ — JAX-aware static analysis: the repo's contracts, machine-checked.

Two passes behind one CLI (`python -m pytorch_ddp_mnist_tpu lint` /
`... audit-program`):

  * **Source lint** (`rules.py` + `lint.py`, stdlib `ast` only — the
    check_telemetry.py discipline: loadable by file path on hosts without
    jax): JAX/TPU-specific rules with stable IDs — host syncs and wall
    clocks inside traced code, Python `if` on tracer values, f64 dtypes,
    collectives without an explicit axis name, overbroad `except` that
    would swallow `TrainingHealthError`/`CheckpointError`, mutable default
    args, and module-global reassignment without a lock (the PR 6 tracer
    race, as a rule). A committed `baseline.json` suppresses accepted
    findings with a reason string, so CI fails only on NEW ones.

  * **Program auditor** (`jaxpr_audit.py`): lower the full step-program
    matrix (comm x overlap x {streaming step, fit_cached scan body}) over
    a deviceless 8-way AbstractMesh and walk the jaxpr asserting the
    structural contracts the hand-written pins guard one test at a time —
    collective kinds/counts per strategy and per bucket, wire dtypes (the
    wire never carries f32 for bf16/int8), no f64, no host callbacks, and
    bytes-on-wire recomputed from the audited program matching the
    `ddp.bytes_on_wire` cost model.

`lint` imports nothing outside the stdlib; `jaxpr_audit` imports jax (and
the step builders) lazily inside its functions, so importing this package
stays cheap.

docs/STATIC_ANALYSIS.md carries the rule catalog, the per-strategy audit
contract table, and the baseline workflow.
"""

from __future__ import annotations

from .rules import RULES, Finding, Rule  # noqa: F401
from .lint import lint_paths, lint_source, load_baseline  # noqa: F401

__all__ = ["RULES", "Rule", "Finding", "lint_source", "lint_paths",
           "load_baseline"]
