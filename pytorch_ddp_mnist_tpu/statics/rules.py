"""Rule catalog for the JAX-aware source lint — stable IDs, one-line fixes.

Pure stdlib (no jax import): like scripts/check_telemetry.py, this module
must load by file path on any host the source lands on. The engine lives in
`lint.py`; this module is the contract — rule IDs are STABLE (tests,
baseline entries and docs key on them; retire a rule by deleting it, never
by renaming).

Scope vocabulary used below:

  * "traced code" — the body of a function the engine marks as traced: it
    is decorated with (or passed to) `jax.jit` / `jax.vmap` / `jax.grad` /
    `jax.value_and_grad` / `jax.lax.scan` / `jax.lax.cond` /
    `jax.lax.while_loop` / `shard_map` / `pallas_call` (directly, or one
    `functools.partial` hop away), anywhere in the module. Matching is by
    function NAME within the module — a deliberate over-approximation
    (two defs sharing a name are both marked) that keeps the pass purely
    syntactic.
  * "event loop" — the body of a function the concurrency auditor
    (`concurrency.py`) marks as event-loop-resident: every `async def`,
    plus any same-module function whose name is scheduled onto the loop
    (`call_soon`/`call_later`/`call_at`/`create_task`/`ensure_future`/...)
    or called from a resident function — the same name-within-module
    over-approximation as "traced", applied to the thread-entry map.
  * "anywhere" — the whole file.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Optional


def dotted_name(node) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None — the ONE dotted-name
    resolution both engines (lint.py, concurrency.py) share, so they can
    never disagree on what a callee is."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def last_segment(node) -> Optional[str]:
    d = dotted_name(node)
    return d.rsplit(".", 1)[-1] if d else None


def root_segment(node) -> Optional[str]:
    d = dotted_name(node)
    return d.split(".", 1)[0] if d else None


@dataclass(frozen=True)
class Rule:
    id: str
    title: str
    rationale: str
    hint: str          # the one-line fix a finding prints
    scope: str         # "traced" | "anywhere" (documentation; the engine
    #                    hard-codes where each check runs)


@dataclass(frozen=True)
class Finding:
    """One lint hit. `content` is the stripped source line — together with
    (rule, file) it is the baseline suppression key, robust to the line
    NUMBER drifting as unrelated code moves."""
    rule: str
    path: str          # repo-relative, '/'-separated
    line: int
    col: int
    message: str
    content: str
    hint: str = field(default="")

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} " \
               f"{self.message} [fix: {self.hint}]"

    def key(self) -> tuple:
        return (self.rule, self.path, self.content)

    def to_json(self) -> dict:
        return {"rule": self.rule, "file": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "content": self.content, "hint": self.hint}


RULES = {r.id: r for r in [
    Rule(
        id="SYNC001",
        title="host sync inside traced code",
        rationale=(
            "float()/.item()/.tolist()/np.asarray()/jax.device_get()/"
            ".block_until_ready() on a tracer either fails at trace time or "
            "forces a device->host round trip per call — the zero-per-step-"
            "host-sync invariant (docs/OBSERVABILITY.md) dies one innocent "
            "cast at a time. Host numpy math belongs in the builder, not "
            "the traced body."),
        hint="compute on-device with jnp, or hoist the host math into the "
             "(untraced) builder",
        scope="traced"),
    Rule(
        id="SYNC002",
        title="wall clock / host RNG inside traced code",
        rationale=(
            "time.*, random.*, np.random.* and argless datetime calls "
            "evaluate ONCE at trace time and freeze into the jaxpr as "
            "constants — the step then replays a stale timestamp/draw "
            "forever (and recompilation changes results). Timing belongs "
            "on the host around the dispatch; randomness belongs to "
            "jax.random keys threaded through the step."),
        hint="move timing to the host caller; draw randomness from a "
             "threaded jax.random key",
        scope="traced"),
    Rule(
        id="SYNC003",
        title="Python control flow on a traced value",
        rationale=(
            "`if`/`while` on a jnp/jax call result coerces a tracer to a "
            "Python bool: TracerBoolConversionError at best, a silent "
            "trace-time specialization at worst. Static metadata "
            "(.shape/.dtype/.ndim) is exempt — branching on it is how the "
            "builders specialize programs."),
        hint="use jax.lax.cond / jnp.where, or branch on static "
             ".shape/.dtype metadata",
        scope="traced"),
    Rule(
        id="DT001",
        title="float64 dtype in device code",
        rationale=(
            "TPUs have no f64 ALU; with jax_enable_x64 off the dtype "
            "silently truncates, with it on every op doubles its HBM "
            "footprint and the wire contract ('bf16/int8 strategies never "
            "carry f32' — let alone f64) breaks. Host-side np.float64 "
            "statistics are fine and out of scope; jnp.float64 anywhere, "
            "f64 dtypes inside traced code, and jax_enable_x64 flips are "
            "not."),
        hint="use jnp.float32 (or bf16) on device; keep f64 to host numpy "
             "post-processing",
        scope="traced (plus jnp.float64 / jax_enable_x64 anywhere)"),
    Rule(
        id="COLL001",
        title="collective without an explicit axis name",
        rationale=(
            "jax.lax.psum/pmean/all_gather/... with the axis argument "
            "missing raises deep inside tracing with no source context — "
            "or, under nested meshes, silently reduces over the wrong "
            "axes. Every collective in this codebase names its axis "
            "('dp'); the auditor then verifies the LOWERED program agrees."),
        hint="pass the axis name explicitly (DATA_AXIS / axis_name=...)",
        scope="anywhere"),
    Rule(
        id="EXC001",
        title="bare/overbroad except that swallows framework signals",
        rationale=(
            "`except:` / `except Exception:` without a re-raise also "
            "catches TrainingHealthError (deliberately NOT a RuntimeError "
            "so health aborts pass through generic runtime handling — "
            "telemetry/health.py) and CheckpointError — one careless "
            "handler and a fatal-NaN abort reads as a handled hiccup. "
            "Deliberate catch-alls (fault barriers around arbitrary user "
            "callables) go in the baseline with a reason."),
        hint="catch the specific exceptions, re-raise, or baseline with a "
             "reason",
        scope="anywhere"),
    Rule(
        id="MUT001",
        title="mutable default argument",
        rationale=(
            "def f(xs=[]) evaluates the default ONCE at def time; every "
            "call then shares (and mutates) the same object — state leaks "
            "across calls and across tests. In a codebase built on pure "
            "functions and explicit carries this is always a bug."),
        hint="default to None and create the container inside the function",
        scope="anywhere"),
    Rule(
        id="MUT002",
        title="module global reassigned without a lock",
        rationale=(
            "`global NAME` + assignment in a function that takes no lock "
            "is a check-then-act race the moment a second thread appears — "
            "exactly the PR 6 tracer-registry race: serve's asyncio "
            "threads and the Prometheus scrape thread share these "
            "modules' process-wide singletons with the train loop."),
        hint="guard the read-modify-write with a module-level "
             "threading.Lock",
        scope="anywhere"),
    Rule(
        id="ASYNC001",
        title="blocking call on the event loop",
        rationale=(
            "time.sleep / file & subprocess IO / a sorted()/.sort() over a "
            "shared window / block_until_ready / an untimeout'd lock "
            ".acquire() inside a coroutine (or a callback the loop "
            "schedules) stalls EVERY in-flight request, not just its own — "
            "the PR 9 bug was exactly this: an O(W log W) sort on the "
            "serve loop per offered request, inflating the very queue "
            "delay its admission predictor was computing."),
        hint="await the async spelling (asyncio.sleep, executors), cache "
             "the sort per completion, or move the work off-loop",
        scope="event loop"),
    Rule(
        id="ASYNC002",
        title="await while holding a sync lock",
        rationale=(
            "`with threading.Lock(): await ...` parks the coroutine with "
            "the lock still held; any OTHER thread (the Prometheus scrape "
            "thread, a readahead worker) then blocks on that lock for as "
            "long as the await takes — and if resuming the coroutine "
            "needs that thread, the process deadlocks. Sync locks must "
            "not span suspension points."),
        hint="release before awaiting, or use asyncio.Lock (async with) "
             "for loop-side exclusion",
        scope="event loop"),
    Rule(
        id="LOCK001",
        title="shared state written both under and outside a lock",
        rationale=(
            "An attribute/global assigned under a lock in one method and "
            "bare in another means the lock guards nothing: the unlocked "
            "writer races every locked reader — the "
            "MetricsRegistry.snapshot()-vs-scrape-thread class (PR 6), "
            "and the SLOWindow sorted-cache written from both the serve "
            "loop and the /metrics scrape thread. Construction "
            "(`__init__`) is exempt: it happens-before publication."),
        hint="take the same lock at every write site (or stop locking any "
             "of them and document why the state is single-threaded)",
        scope="anywhere"),
    Rule(
        id="LOCK002",
        title="inconsistent lock-acquisition order",
        rationale=(
            "Nesting lock B inside lock A in one function and A inside B "
            "in another is a deadlock waiting for the right interleaving "
            "— two threads each holding one and blocking on the other. "
            "Detection is lexical (with-blocks and .acquire() sites per "
            "file, lock identity by qualified name); the runtime "
            "`sanitize.lock_trace()` confirms or refutes findings across "
            "the real cross-module call graph."),
        hint="pick one global order for the lock pair and acquire in that "
             "order everywhere (or collapse to one lock)",
        scope="anywhere"),
]}

# The concurrency auditor's rule IDs (engine: concurrency.py) — the split
# bench.py's artifact stamp reports as `concurrency_findings` beside the
# source lint's `lint_findings`.
CONCURRENCY_RULES = frozenset({"ASYNC001", "ASYNC002", "LOCK001", "LOCK002"})
