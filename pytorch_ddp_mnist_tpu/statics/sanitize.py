"""Runtime contract sanitizers — the hand-rolled test interceptions,
promoted to one reusable layer.

The source rules (`lint.py` + `concurrency.py`) read what the code SAYS;
these context managers check what a RUN actually does. Each one packages a
technique the test suite invented ad hoc and re-implemented per test:

  * `no_host_sync()` — the PR 6 watchdog pin + PR 9 serve-tracing pin as
    one tool: intercepts `jax.block_until_ready`, `jax.device_get` and
    `np.asarray`-of-a-`jax.Array` (the repo's two fetch choke points) for
    the duration of the block, counts them, and — against optional budgets
    — fails the block that silently grew a per-step host sync. The lint's
    SYNC001 catches the *traced-code* spellings statically; this catches
    the host-side loop that fetches too often, which no source rule can.
  * `event_loop_stall(threshold_ms)` — the PR 9 bug (an O(W log W) sort on
    the serve event loop per offered request) as a harness: times every
    callback and coroutine step through `asyncio.events.Handle._run` (the
    one choke point all of them pass), records any single run past the
    threshold, and fails the block. Needs no debug mode and no control of
    how the loop was created.
  * `lock_trace()` — LOCK002's runtime half: patches the
    `threading.Lock`/`RLock` factories so every lock created inside the
    block records its acquisition order (per-thread held-stack -> directed
    edges keyed by creation site), then fails on any cycle in the observed
    graph. Confirms or refutes the lexical auditor's findings across the
    real cross-module call graph. Locks created BEFORE the block (module
    import time) are not traced — arm it early; the lexical pass covers
    the import-time singletons.

All three are pure stdlib at import time (numpy/jax resolve lazily inside
`no_host_sync.__enter__`, gated — a jax-less host degrades to unarmed with
zero counts), patch process-wide entry points only for the duration of the
`with` block, restore them on exit even when the block raises, and raise a
`SanitizerError` subclass only when the block itself succeeded (a primary
failure is never masked by the sanitizer's verdict).

`scripts/sanitize_smoke.py` (`make sanitize-smoke`) runs the serve
selftest and a short real training run under all three.
"""

from __future__ import annotations

import asyncio.events
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple


class SanitizerError(AssertionError):
    """A runtime contract the sanitized block was supposed to honor did
    not hold. Subclasses AssertionError so test harnesses treat it as a
    failed assertion, not an infrastructure error."""


class HostSyncError(SanitizerError):
    pass


class EventLoopStallError(SanitizerError):
    pass


class LockOrderError(SanitizerError):
    pass


# ---------------------------------------------------------------------------
# no_host_sync
# ---------------------------------------------------------------------------

class no_host_sync:
    """Count (and budget) device->host synchronizations inside the block.

        with no_host_sync() as s:                 # zero block_until_ready
            fit(...)
        assert s.fetches <= epochs * 6            # epoch-granular fetches

    Counters: `block_until_ready_calls` (explicit drains — budget
    `max_block_until_ready`, default 0: the zero-sync invariant) and
    `fetches` (`np.asarray` of a `jax.Array` + `jax.device_get` — budget
    `max_fetches`, default None: count only, callers assert their own
    shape, e.g. "exactly 2 per flush"). Exceeding a budget raises
    `HostSyncError` at exit. `armed` is False when jax is unavailable
    (counters stay 0 and no budget can fail — there is no device to sync
    with). Nestable; each level restores what it saw."""

    def __init__(self, *, max_block_until_ready: Optional[int] = 0,
                 max_fetches: Optional[int] = None):
        self.max_block_until_ready = max_block_until_ready
        self.max_fetches = max_fetches
        self.block_until_ready_calls = 0
        self.fetches = 0
        self.armed = False

    def __enter__(self) -> "no_host_sync":
        try:
            import jax
            import numpy as np
        except ImportError:     # jax-less host: nothing can sync
            return self
        self._jax, self._np = jax, np
        self._orig_bur = jax.block_until_ready
        self._orig_dget = jax.device_get
        self._orig_asarray = np.asarray
        san = self

        def counting_bur(tree):
            san.block_until_ready_calls += 1
            return san._orig_bur(tree)

        def counting_dget(x, *args, **kw):
            san.fetches += 1
            return san._orig_dget(x, *args, **kw)

        def counting_asarray(a, *args, **kw):
            if isinstance(a, san._jax.Array):
                san.fetches += 1
            return san._orig_asarray(a, *args, **kw)

        jax.block_until_ready = counting_bur
        jax.device_get = counting_dget
        np.asarray = counting_asarray
        self.armed = True
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self.armed:
            self._jax.block_until_ready = self._orig_bur
            self._jax.device_get = self._orig_dget
            self._np.asarray = self._orig_asarray
        if exc_type is not None:
            return
        problems = []
        if (self.max_block_until_ready is not None
                and self.block_until_ready_calls
                > self.max_block_until_ready):
            problems.append(
                f"{self.block_until_ready_calls} block_until_ready "
                f"call(s) (budget {self.max_block_until_ready}) — the "
                f"zero-host-sync invariant broke")
        if self.max_fetches is not None and self.fetches > self.max_fetches:
            problems.append(
                f"{self.fetches} device->host fetch(es) (budget "
                f"{self.max_fetches}) — fetch cadence grew")
        if problems:
            raise HostSyncError("no_host_sync: " + "; ".join(problems))


# ---------------------------------------------------------------------------
# event_loop_stall
# ---------------------------------------------------------------------------

def _describe_handle(handle) -> str:
    cb = getattr(handle, "_callback", None)
    return repr(cb)[:160] if cb is not None else repr(handle)[:160]


class event_loop_stall:
    """Fail when any single event-loop callback (including coroutine
    steps) runs longer than `threshold_ms` inside the block.

        with event_loop_stall(threshold_ms=50) as loop_guard:
            asyncio.run(scenario())
        # loop_guard.stalls == [] on a healthy loop

    `stalls` holds `{"dur_ms", "callback"}` dicts for every offending run;
    more than `max_stalls` of them (default 0) raises
    `EventLoopStallError` at exit. The patch point is
    `asyncio.events.Handle._run`, so `call_soon`/`call_later` callbacks
    and task steps are all on the clock whatever loop policy created the
    loop."""

    def __init__(self, threshold_ms: float = 50.0, *, max_stalls: int = 0):
        if threshold_ms <= 0:
            raise ValueError(f"threshold_ms must be > 0; got {threshold_ms}")
        self.threshold_s = float(threshold_ms) / 1e3
        self.max_stalls = int(max_stalls)
        self.stalls: List[dict] = []

    def __enter__(self) -> "event_loop_stall":
        self._orig_run = asyncio.events.Handle._run
        san = self
        orig = self._orig_run

        def timed_run(handle):
            t0 = time.perf_counter()
            try:
                return orig(handle)
            finally:
                dt = time.perf_counter() - t0
                if dt >= san.threshold_s:
                    san.stalls.append({
                        "dur_ms": round(dt * 1e3, 3),
                        "callback": _describe_handle(handle)})

        asyncio.events.Handle._run = timed_run
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        asyncio.events.Handle._run = self._orig_run
        if exc_type is not None:
            return
        if len(self.stalls) > self.max_stalls:
            worst = max(self.stalls, key=lambda s: s["dur_ms"])
            raise EventLoopStallError(
                f"event_loop_stall: {len(self.stalls)} callback(s) over "
                f"{self.threshold_s * 1e3:.0f}ms (budget "
                f"{self.max_stalls}); worst {worst['dur_ms']}ms in "
                f"{worst['callback']}")


# ---------------------------------------------------------------------------
# lock_trace
# ---------------------------------------------------------------------------

# The ACTIVE trace, module-level: wrapper objects outlive the `with` block
# that created them (a service built inside one lock_trace keeps its
# instrumented locks forever), so they must report to whichever trace is
# armed NOW — not to the trace that happened to exist at creation. With no
# trace armed, a wrapper is a near-free passthrough. The per-thread held
# stack is likewise module-level, so a lock still held when a new trace
# arms is accounted in that trace's edges.
_ACTIVE_TRACE: "Optional[lock_trace]" = None
# guards the arm/disarm swap (statics rule MUT002); created at import,
# before any factory patching, so it is never itself traced
_ARM_LOCK = threading.Lock()
_HELD = threading.local()


def _held_stack() -> list:
    if not hasattr(_HELD, "stack"):
        _HELD.stack = []
    return _HELD.stack


class _TracedLock:
    """A threading.Lock/RLock wrapper that reports acquisition order to
    the currently armed lock_trace (if any). Everything not intercepted
    proxies to the real lock (so `threading.Condition` keeps working;
    acquisitions a Condition performs through `_release_save`/
    `_acquire_restore` bypass tracing, which is consistent: the owning
    thread is blocked in wait() and acquires nothing else meanwhile)."""

    def __init__(self, real, site: str):
        self._real = real
        self.site = site

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._real.acquire(blocking, timeout)
        if got:
            held = _held_stack()
            trace = _ACTIVE_TRACE
            if trace is not None:
                trace._note_edges(held, self)
            held.append(self)
        return got

    def release(self) -> None:
        held = _held_stack()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break
        self._real.release()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __getattr__(self, name):
        return getattr(self._real, name)


class lock_trace:
    """Record the runtime lock-acquisition-order graph; fail on cycles.

        with lock_trace() as locks:
            run_the_system()
        locks.edges()     # [(held_site, acquired_site, count), ...]

    Lock identity is the `threading.Lock()`/`RLock()` creation site
    (file:line), so every instance a class creates aggregates to one node
    — the granularity LOCK002's lexical ids approximate. Edges record "B
    acquired while A held" per thread (RLock re-entry adds no self-edge);
    a cycle at exit raises `LockOrderError` naming it (suppress with
    `fail_on_cycle=False` to inspect instead).

    Instrumented lock OBJECTS outlive the block that created them (a
    service built inside one trace holds its locks forever), so they
    report to whichever trace is armed at acquisition time: a later
    lock_trace sees cycles on locks an earlier one created, and with no
    trace armed the wrappers are near-free passthroughs. Only one
    lock_trace may be armed at a time (nesting raises)."""

    def __init__(self, *, fail_on_cycle: bool = True):
        self.fail_on_cycle = fail_on_cycle
        self._edges: Dict[Tuple[str, str], int] = {}
        self._meta = threading.Lock()   # created pre-patch: never traced

    # -- bookkeeping (called from _TracedLock.acquire) --------------------

    def _note_edges(self, held: list, lock: _TracedLock) -> None:
        new_edges = [(h.site, lock.site) for h in held
                     if h.site != lock.site]
        if new_edges:
            with self._meta:
                for e in new_edges:
                    self._edges[e] = self._edges.get(e, 0) + 1

    # -- the graph --------------------------------------------------------

    def edges(self) -> List[Tuple[str, str, int]]:
        with self._meta:
            return sorted((a, b, n) for (a, b), n in self._edges.items())

    def cycles(self) -> List[List[str]]:
        """Every distinct cycle in the observed order graph (each reported
        once, rotated to start at its smallest node)."""
        with self._meta:
            adj: Dict[str, set] = {}
            for a, b in self._edges:
                adj.setdefault(a, set()).add(b)
        found: Dict[Tuple[str, ...], List[str]] = {}

        def dfs(node: str, path: List[str], on_path: set) -> None:
            for nxt in sorted(adj.get(node, ())):
                if nxt in on_path:
                    cyc = path[path.index(nxt):]
                    k = min(range(len(cyc)), key=lambda i: cyc[i])
                    canon = tuple(cyc[k:] + cyc[:k])
                    found.setdefault(canon, list(canon))
                elif nxt not in path:
                    dfs(nxt, path + [nxt], on_path | {nxt})

        for start in sorted(adj):
            dfs(start, [start], {start})
        return list(found.values())

    # -- patching ---------------------------------------------------------

    def __enter__(self) -> "lock_trace":
        global _ACTIVE_TRACE
        with _ARM_LOCK:
            if _ACTIVE_TRACE is not None:
                raise RuntimeError("a lock_trace is already armed; traces "
                                   "do not nest (their edge graphs would "
                                   "be ambiguous)")
            self._orig_lock = threading.Lock
            self._orig_rlock = threading.RLock

            def make(factory):
                def traced_factory(*a, **kw):
                    frame = sys._getframe(1)
                    site = (f"{os.path.basename(frame.f_code.co_filename)}"
                            f":{frame.f_lineno}")
                    return _TracedLock(factory(*a, **kw), site)
                return traced_factory

            threading.Lock = make(self._orig_lock)
            threading.RLock = make(self._orig_rlock)
            _ACTIVE_TRACE = self
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        global _ACTIVE_TRACE
        with _ARM_LOCK:
            _ACTIVE_TRACE = None
            threading.Lock = self._orig_lock
            threading.RLock = self._orig_rlock
        if exc_type is not None:
            return
        if self.fail_on_cycle:
            cyc = self.cycles()
            if cyc:
                pretty = "; ".join(" -> ".join(c + [c[0]]) for c in cyc)
                raise LockOrderError(
                    f"lock_trace: {len(cyc)} acquisition-order cycle(s) "
                    f"observed (potential deadlock): {pretty}")
