"""Program auditor: lower the step-program matrix, assert the contracts.

Where the source lint (`lint.py`) reads what the code SAYS, this pass reads
what the program IS: every (comm strategy x overlap x program form) the
trainer can build is traced to a jaxpr over a deviceless 8-way
`AbstractMesh` (the tests/test_export_lowering.py technique — no devices,
no compile, CI-cheap) and the jaxpr is walked asserting the structural
contracts the repo otherwise guards with hand-written per-test pins:

  * **collective-shape** — the collective primitive kinds and per-bucket
    counts each strategy promises (pmean: one f32 allreduce operand per
    leaf; sharded: reduce-scatter + all-gather per bucket, nothing else;
    bf16: bf16 allreduce per leaf/bucket; int8: all_to_all + all_gather
    pairs per bucket, payload + block scales);
  * **wire-dtype** — "the wire never carries f32" for bf16/int8: every
    payload-sized collective operand is bf16 (bf16 strategy) or int8 plus
    exact scale-sized f32 vectors (int8 strategy). The scalar loss pmean
    is control-plane, exempt by size (<= SMALL_ELEMS elements);
  * **no-f64** — no float64/complex128 aval anywhere in the program;
  * **no-callback** — no host-callback primitive inside the step;
  * **collective-axis** — every collective names the 'dp' axis explicitly;
  * **wire-bytes** — per-step bytes recomputed from the AUDITED program
    (ring cost model: allreduce 2(N-1)/N * M, RS/A2A (N-1)/N * M_in, AG
    (N-1)/N * M_out) equals `parallel.collectives.bytes_on_wire` exactly —
    the telemetry cost model can never drift from the lowered program;
  * **journal-schedule** — the per-rank collective journal's static half
    (`parallel.collectives.collective_schedule`, what a `--journal` run
    records per step — telemetry/cluster.py) matches the audited
    program's payload collectives entry for entry (same multiset of
    kind + ring bytes): the journal can never describe a program nobody
    ran;
  * **donation-aliasing** — the JITTED wrappers (make_dp_train_step /
    make_dp_run_fn) donate exactly the inputs they declare (`.donates`:
    params + key, plus the int8 error-feedback residual) and never a
    data input: the traced program's top-level pjit `donated_invars`
    flags are matched against the public argument tree by shape+dtype,
    so a silently dropped `donate_argnums` entry — which would double
    the params' HBM footprint — fails BY NAME (the regression tripwire
    ROADMAP item 3's buffer-donation work gates on).

Two program forms per config: `step` (parallel.ddp.dp_step_program — the
streaming make_dp_train_step body) and `run` (train.scan.make_dp_run_fn —
the fit_cached scan body; collectives are audited at the innermost scan
depth, so the per-RUN pmean re-replication of params is correctly outside
the per-step byte account).

jax 0.4.x note: the legacy pmean path runs under shard_map's replication
checker, which rewrites `psum` to `psum2` and inserts zero-wire
`pbroadcast` bookkeeping — both spellings are recognized, pbroadcast is
axis-checked but carries no bytes.

CLI (also `python -m pytorch_ddp_mnist_tpu audit-program`):

    audit-program [--comm X] [--overlap] [--form step|run|both]
                  [--bucket-elems N] [--json]

Exit codes: 0 every audited config passes, 3 contract violation (the
violated contract and config are named), 2 usage.
"""

from __future__ import annotations

import json
import sys
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

# Payload threshold: collective operands at or below this many elements are
# control-plane, never gradient payload. The only control-plane collectives
# a step emits are the scalar loss pmean (1 element) and the health aux
# vector (3); the smallest possible payload operand is an int8 block-scale
# vector of a minimum-size bucket — padded/quant_block = n_devices = 8
# elements — so the cut sits strictly between 3 and 8.
SMALL_ELEMS = 4

# jaxpr primitive name -> wire kind. psum2/pbroadcast are the jax-0.4.x
# shard_map replication-checker spellings; *_invariant are newer jax.
WIRE_KINDS = {
    "psum": "allreduce", "psum2": "allreduce",
    "psum_invariant": "allreduce",
    "all_gather": "all_gather", "all_gather_invariant": "all_gather",
    "reduce_scatter": "reduce_scatter", "psum_scatter": "reduce_scatter",
    "all_to_all": "all_to_all",
}
# Axis-named primitives that move no payload bytes.
AXIS_ONLY = {"axis_index", "pbroadcast", "pvary"}
CALLBACK_MARKERS = ("callback", "outside_call", "infeed", "outfeed")

N_DEVICES = 8
BATCH_PER_DEVICE = 16
COMMS = ("pmean", "sharded", "bf16", "int8")
FORMS = ("step", "run")


class AuditViolation(AssertionError):
    """A named structural contract the lowered program broke."""

    def __init__(self, contract: str, config: str, detail: str):
        self.contract = contract
        self.config = config
        super().__init__(f"[{contract}] {config}: {detail}")


@dataclass
class CollectiveOp:
    """One operand of one collective eqn in the walked jaxpr."""
    prim: str
    kind: str               # WIRE_KINDS value, or "axis" for AXIS_ONLY
    dtype: str
    in_elems: int
    out_elems: int
    axes: Tuple[str, ...]
    scan_depth: int
    eqn_id: int

    @property
    def payload(self) -> bool:
        return (self.kind != "axis"
                and max(self.in_elems, self.out_elems) > SMALL_ELEMS)

    def to_json(self) -> dict:
        return {"prim": self.prim, "kind": self.kind, "dtype": self.dtype,
                "in_elems": self.in_elems, "out_elems": self.out_elems,
                "axes": list(self.axes), "scan_depth": self.scan_depth}


@dataclass
class AuditReport:
    comm: str
    overlap: bool
    form: str
    n_devices: int
    n_buckets: int
    payload_ops: List[CollectiveOp]
    wire_bytes_program: int
    wire_bytes_model: int
    f64_ops: int = 0
    callbacks: int = 0
    ok: bool = True
    donated_labels: List[str] = field(default_factory=list)

    def to_json(self) -> dict:
        return {"comm": self.comm, "overlap": self.overlap,
                "form": self.form, "n_devices": self.n_devices,
                "n_buckets": self.n_buckets,
                "payload_ops": [o.to_json() for o in self.payload_ops],
                "wire_bytes_program": self.wire_bytes_program,
                "wire_bytes_model": self.wire_bytes_model, "ok": self.ok,
                "donated": list(self.donated_labels)}


# -- jaxpr walking -----------------------------------------------------------

def _aval_elems(aval) -> int:
    import numpy as np
    shape = getattr(aval, "shape", ())
    return int(np.prod(shape)) if shape else 1


def _norm_axes(params: dict) -> Tuple[str, ...]:
    raw = params.get("axes", params.get("axis_name", ()))
    if raw is None:
        raw = ()
    if isinstance(raw, str):
        raw = (raw,)
    return tuple(str(a) for a in raw)


def walk_jaxpr(jaxpr, depth: int = 0, state: Optional[dict] = None) -> dict:
    """Recursively collect collectives, f64 avals and callback primitives
    from `jaxpr` and every sub-jaxpr (pjit/scan/cond/shard_map/...).
    `scan` eqns increment the scan depth of everything inside them."""
    if state is None:
        state = {"ops": [], "f64": [], "callbacks": [], "eqn_id": 0}
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        state["eqn_id"] += 1
        eqn_id = state["eqn_id"]
        for v in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(v, "aval", None)
            dt = str(getattr(aval, "dtype", "")) if aval is not None else ""
            if "float64" in dt or "complex128" in dt:
                state["f64"].append((name, dt))
        if any(m in name for m in CALLBACK_MARKERS):
            state["callbacks"].append(name)
        if name in WIRE_KINDS or name in AXIS_ONLY:
            kind = WIRE_KINDS.get(name, "axis")
            axes = _norm_axes(eqn.params)
            invars = [v for v in eqn.invars if getattr(v, "aval", None)
                      is not None]
            outvars = list(eqn.outvars)
            if not invars:        # axis_index: no operands
                state["ops"].append(CollectiveOp(
                    prim=name, kind=kind, dtype="int32", in_elems=0,
                    out_elems=_aval_elems(outvars[0].aval) if outvars
                    else 0, axes=axes, scan_depth=depth, eqn_id=eqn_id))
            else:
                # multi-operand collectives (tree psum) pair invars with
                # outvars positionally
                for i, v in enumerate(invars):
                    out_aval = (outvars[i].aval if i < len(outvars)
                                else v.aval)
                    state["ops"].append(CollectiveOp(
                        prim=name, kind=kind,
                        dtype=str(v.aval.dtype),
                        in_elems=_aval_elems(v.aval),
                        out_elems=_aval_elems(out_aval),
                        axes=axes, scan_depth=depth, eqn_id=eqn_id))
        inner_depth = depth + (1 if name == "scan" else 0)
        for v in eqn.params.values():
            for sub in (v if isinstance(v, (list, tuple)) else [v]):
                if hasattr(sub, "eqns"):
                    walk_jaxpr(sub, inner_depth, state)
                elif hasattr(sub, "jaxpr"):
                    walk_jaxpr(sub.jaxpr, inner_depth, state)
    return state


# -- program builders --------------------------------------------------------

def _mesh(n_dev: int):
    from ..compat import abstract_mesh
    return abstract_mesh((n_dev,), ("dp",))


def _example_params(model: str = "mlp", param_scale: int = 1):
    import jax
    from ..models.zoo import resolve_model
    return resolve_model(model, param_scale).init(jax.random.PRNGKey(0))


def build_step_program(comm: str, overlap: bool = False, *,
                       n_dev: int = N_DEVICES,
                       batch: int = BATCH_PER_DEVICE,
                       bucket_elems: Optional[int] = None,
                       quant_block: Optional[int] = None,
                       mesh=None, model: str = "mlp",
                       param_scale: int = 1):
    """(program, example_args) for the streaming DP step
    (parallel.ddp.dp_step_program) over an AbstractMesh — shared by the
    auditor, tests/test_export_lowering.py AND telemetry/costs.py's
    cost/memory harvest, so the program the tests lower, the program the
    auditor walks, and the program forensics measure can never drift.
    `mesh` overrides the deviceless AbstractMesh with a real one (the
    cost harvest compiles, which an AbstractMesh cannot); `model`/
    `param_scale` select the workload (models/zoo.py) so the harvest can
    measure the MULTICHIP artifact geometries."""
    import jax
    import jax.numpy as jnp
    from ..parallel import collectives
    from ..parallel.ddp import dp_step_program
    params = _example_params(model, param_scale)
    prog = dp_step_program(mesh if mesh is not None else _mesh(n_dev),
                           0.01, comm=comm, overlap=overlap,
                           bucket_elems=bucket_elems,
                           quant_block=quant_block,
                           model=model, param_scale=param_scale)
    key = jax.random.PRNGKey(1)
    x = jnp.zeros((n_dev * batch, 784), jnp.float32)
    y = jnp.zeros((n_dev * batch,), jnp.int32)
    if collectives.carries_state(comm):
        qb = collectives.QUANT_BLOCK if quant_block is None else quant_block
        be = (collectives.DEFAULT_BUCKET_ELEMS if bucket_elems is None
              else bucket_elems)
        resid = jnp.zeros(
            (n_dev, collectives.comm_state_elems(
                params, n_dev, bucket_elems=be, quant_block=qb)),
            jnp.float32)
        return prog, (params, key, resid, x, y)
    return prog, (params, key, x, y)


def build_run_program(comm: str, overlap: bool = False, *,
                      n_dev: int = N_DEVICES,
                      batch: int = BATCH_PER_DEVICE,
                      epochs: int = 1, steps: int = 2,
                      bucket_elems: Optional[int] = None,
                      quant_block: Optional[int] = None,
                      mesh=None, model: str = "mlp",
                      param_scale: int = 1):
    """(program, example_args) for the fit_cached scan body
    (train.scan.make_dp_run_fn) over an AbstractMesh (or a real `mesh` —
    see build_step_program)."""
    import jax
    import jax.numpy as jnp
    from ..parallel import collectives
    from ..train.scan import make_dp_run_fn
    params = _example_params(model, param_scale)
    run = make_dp_run_fn(mesh if mesh is not None else _mesh(n_dev),
                         lr=0.01, comm=comm, overlap=overlap,
                         quant_block=quant_block,
                         bucket_elems=bucket_elems,
                         model=model, param_scale=param_scale)
    key = jax.random.PRNGKey(1)
    rows = n_dev * steps * batch
    x_all = jnp.zeros((rows, 784), jnp.uint8)
    y_all = jnp.zeros((rows,), jnp.int32)
    idxs = jnp.zeros((epochs, steps, n_dev * batch), jnp.int32)
    if collectives.carries_state(comm):
        qb = collectives.QUANT_BLOCK if quant_block is None else quant_block
        be = (collectives.DEFAULT_BUCKET_ELEMS if bucket_elems is None
              else bucket_elems)
        resid = jnp.zeros(
            (n_dev, collectives.comm_state_elems(
                params, n_dev, bucket_elems=be, quant_block=qb)),
            jnp.float32)
        return run, (params, key, x_all, y_all, idxs, resid)
    return run, (params, key, x_all, y_all, idxs)


def build_jit_step(comm: str, overlap: bool = False, *,
                   n_dev: int = N_DEVICES,
                   batch: int = BATCH_PER_DEVICE,
                   bucket_elems: Optional[int] = None,
                   quant_block: Optional[int] = None,
                   model: str = "mlp", param_scale: int = 1):
    """(step, example_args) for the JITTED streaming DP step
    (parallel.ddp.make_dp_train_step) over an AbstractMesh — the wrapper
    whose `donate_argnums` the donation-aliasing contract audits. Public
    argument order (params, key, x, y[, resid]); the wrapper carries its
    declared `.donates` tuple."""
    import jax
    import jax.numpy as jnp
    from ..parallel import collectives
    from ..parallel.ddp import make_dp_train_step
    step = make_dp_train_step(_mesh(n_dev), 0.01, comm=comm,
                              overlap=overlap, bucket_elems=bucket_elems,
                              quant_block=quant_block,
                              model=model, param_scale=param_scale)
    params = _example_params(model, param_scale)
    key = jax.random.PRNGKey(1)
    x = jnp.zeros((n_dev * batch, 784), jnp.float32)
    y = jnp.zeros((n_dev * batch,), jnp.int32)
    if collectives.carries_state(comm):
        qb = collectives.QUANT_BLOCK if quant_block is None else quant_block
        be = (collectives.DEFAULT_BUCKET_ELEMS if bucket_elems is None
              else bucket_elems)
        resid = jnp.zeros(
            (n_dev, collectives.comm_state_elems(
                params, n_dev, bucket_elems=be, quant_block=qb)),
            jnp.float32)
        return step, (params, key, x, y, resid)
    return step, (params, key, x, y)


# -- the audit ---------------------------------------------------------------

def _expected_layout(comm: str, n_dev: int, bucket_elems: Optional[int],
                     quant_block: Optional[int]):
    """(n_leaves, n_params, n_buckets, padded_total, scale_sizes) from the
    same bucket math the strategies run (parallel.collectives)."""
    import jax
    from ..parallel import collectives
    qb = collectives.QUANT_BLOCK if quant_block is None else quant_block
    be = (collectives.DEFAULT_BUCKET_ELEMS if bucket_elems is None
          else bucket_elems)
    leaves = jax.tree_util.tree_leaves(_example_params())
    n_params = sum(collectives._leaf_size(l) for l in leaves)
    align = (1 if comm in ("pmean", "bf16")
             else n_dev if comm == "sharded" else n_dev * qb)
    layout = collectives._bucket_layout(leaves, be, align)
    padded = sum(p for (_b, _n, p) in layout)
    scale_sizes = sorted({p // qb for (_b, _n, p) in layout})
    return len(leaves), n_params, len(layout), padded, scale_sizes


def _ring_bytes(op: CollectiveOp, n_dev: int) -> float:
    import numpy as np
    itemsize = np.dtype(op.dtype).itemsize
    ring = (n_dev - 1) / n_dev
    if op.kind == "allreduce":
        return 2 * ring * op.in_elems * itemsize
    if op.kind == "all_gather":
        return ring * op.out_elems * itemsize
    return ring * op.in_elems * itemsize       # reduce_scatter / all_to_all


def audit_collected(ops: List[CollectiveOp], f64_ops: List, callbacks: List,
                    comm: str, overlap: bool, form: str, *,
                    n_dev: int = N_DEVICES,
                    bucket_elems: Optional[int] = None,
                    quant_block: Optional[int] = None) -> AuditReport:
    """Assert every contract over an already-walked program; raises
    AuditViolation (named contract + config) on the first breach."""
    from ..parallel import collectives
    cfg = f"comm={comm} overlap={overlap} form={form}"
    collectives.validate_comm(comm)

    if f64_ops:
        raise AuditViolation("no-f64", cfg,
                             f"float64/complex128 avals in the program: "
                             f"{sorted(set(f64_ops))[:5]}")
    if callbacks:
        raise AuditViolation("no-callback", cfg,
                             f"host-callback primitives inside the step: "
                             f"{sorted(set(callbacks))}")
    for op in ops:
        if "dp" not in op.axes:
            raise AuditViolation(
                "collective-axis", cfg,
                f"{op.prim} (depth {op.scan_depth}) bound to axes "
                f"{op.axes!r}, not the 'dp' mesh axis")

    wire = [o for o in ops if o.kind != "axis"]
    if form == "run":
        # per-STEP accounting: collectives of the innermost scan body. The
        # per-RUN params re-replication (legacy pmean) sits at depth 0 by
        # design and is excluded from the per-step byte model.
        depth = max((o.scan_depth for o in wire), default=0)
        if depth < 2:
            raise AuditViolation(
                "collective-shape", cfg,
                f"expected the gradient collectives inside the epoch+step "
                f"scan nest (depth 2); deepest wire collective sits at "
                f"depth {depth}")
        wire = [o for o in wire if o.scan_depth == depth]
    payload = [o for o in wire if o.payload]

    n_leaves, n_params, n_buckets, padded, scale_sizes = _expected_layout(
        comm, n_dev, bucket_elems, quant_block)

    # wire-dtype first: the contract whose breach is the attack the
    # acceptance pins (int8 path quietly allreducing f32 grads).
    if comm in ("bf16", "int8"):
        want = "bfloat16" if comm == "bf16" else "int8"
        for o in payload:
            if o.dtype == want:
                continue
            if comm == "int8" and o.dtype == "float32" \
                    and o.kind in ("all_to_all", "all_gather") \
                    and (o.in_elems in scale_sizes
                         or o.out_elems in scale_sizes):
                continue  # block scales: f32 by design, scale-sized
            raise AuditViolation(
                "wire-dtype", cfg,
                f"{o.prim} carries {o.in_elems} x {o.dtype} on the wire; "
                f"the {comm} strategy's payload must be {want} "
                f"(f32 only as {scale_sizes}-sized block scales)"
                if comm == "int8" else
                f"{o.prim} carries {o.in_elems} x {o.dtype} on the wire; "
                f"the {comm} strategy's payload must be {want}")

    def count(kind, dtype=None):
        return [o for o in payload if o.kind == kind
                and (dtype is None or o.dtype == dtype)]

    def expect(cond, detail):
        if not cond:
            raise AuditViolation("collective-shape", cfg, detail)

    if comm == "pmean":
        ar = count("allreduce", "float32")
        want_ops = n_leaves if not overlap else n_buckets
        expect(len(ar) == want_ops and not count("reduce_scatter")
               and not count("all_gather") and not count("all_to_all"),
               f"pmean expects exactly {want_ops} f32 allreduce operands "
               f"({'one per leaf' if not overlap else 'one per bucket'}) "
               f"and no RS/AG/A2A; got {len(ar)} allreduce + "
               f"{len(payload) - len(ar)} other payload ops")
        expect(sum(o.in_elems for o in ar) == (n_params if not overlap
                                               else padded),
               f"pmean allreduce covers {sum(o.in_elems for o in ar)} "
               f"elements, expected {n_params if not overlap else padded}")
    elif comm == "sharded":
        rs, ag = count("reduce_scatter", "float32"), count("all_gather",
                                                           "float32")
        expect(len(rs) == n_buckets and len(ag) == n_buckets
               and not count("all_to_all") and not count("allreduce"),
               f"sharded expects {n_buckets} reduce-scatter + {n_buckets} "
               f"all-gather per step and nothing else; got {len(rs)} RS, "
               f"{len(ag)} AG, {len(count('allreduce'))} allreduce, "
               f"{len(count('all_to_all'))} A2A")
        expect(sum(o.in_elems for o in rs) == padded
               and sum(o.out_elems for o in ag) == padded,
               f"sharded RS/AG cover {sum(o.in_elems for o in rs)}/"
               f"{sum(o.out_elems for o in ag)} elements, expected "
               f"{padded} each")
    elif comm == "bf16":
        ar = count("allreduce", "bfloat16")
        want_ops = n_leaves if not overlap else n_buckets
        expect(len(ar) == want_ops and len(payload) == len(ar),
               f"bf16 expects exactly {want_ops} bf16 allreduce operands "
               f"and no other payload collectives; got {len(ar)} bf16 "
               f"allreduce of {len(payload)} payload ops")
        expect(sum(o.in_elems for o in ar) == (n_params if not overlap
                                               else padded),
               f"bf16 allreduce covers {sum(o.in_elems for o in ar)} "
               f"elements, expected {n_params if not overlap else padded}")
    else:  # int8
        a2a_q = count("all_to_all", "int8")
        a2a_s = count("all_to_all", "float32")
        ag_q = count("all_gather", "int8")
        ag_s = count("all_gather", "float32")
        expect(len(a2a_q) == n_buckets and len(a2a_s) == n_buckets
               and len(ag_q) == n_buckets and len(ag_s) == n_buckets
               and not count("allreduce"),
               f"int8 expects per bucket one int8+one-scale all_to_all "
               f"and one int8+one-scale all_gather ({n_buckets} "
               f"bucket(s)), no allreduce; got A2A {len(a2a_q)} int8/"
               f"{len(a2a_s)} f32, AG {len(ag_q)} int8/{len(ag_s)} f32, "
               f"{len(count('allreduce'))} allreduce")
        expect(sum(o.in_elems for o in a2a_q) == padded
               and sum(o.out_elems for o in ag_q) == padded,
               f"int8 quantized payload covers "
               f"{sum(o.in_elems for o in a2a_q)} (A2A) / "
               f"{sum(o.out_elems for o in ag_q)} (AG) elements, "
               f"expected {padded}")

    qb = collectives.QUANT_BLOCK if quant_block is None else quant_block
    be = (collectives.DEFAULT_BUCKET_ELEMS if bucket_elems is None
          else bucket_elems)
    model = collectives.bytes_on_wire(_example_params(), n_dev, comm,
                                      bucket_elems=be, quant_block=qb)
    program = int(round(sum(_ring_bytes(o, n_dev) for o in payload)))
    if program != model:
        raise AuditViolation(
            "wire-bytes", cfg,
            f"bytes recomputed from the audited program ({program}) != "
            f"ddp.bytes_on_wire cost model ({model})")

    # journal-schedule: the per-rank collective journal's static half
    # (telemetry/cluster.py records what collectives.collective_schedule
    # enumerates) must match the AUDITED program entry-for-entry — same
    # multiset of (kind, ring bytes) — or the journal a rank writes would
    # describe a program nobody ran and every cross-rank comparison built
    # on it would be fiction.
    schedule = collectives.collective_schedule(
        _example_params(), n_dev, comm, overlap=overlap,
        bucket_elems=be, quant_block=qb)
    want = sorted((e["kind"], int(e["bytes"])) for e in schedule)
    got = sorted((o.kind, int(round(_ring_bytes(o, n_dev))))
                 for o in payload)
    if want != got:
        missing = [w for w in want if w not in got]
        extra = [g for g in got if g not in want]
        raise AuditViolation(
            "journal-schedule", cfg,
            f"collective_schedule ({len(want)} entr(ies)) does not match "
            f"the audited program's payload collectives ({len(got)}): "
            f"schedule-only {missing[:4]}, program-only {extra[:4]} — "
            f"the journal would record a program nobody ran")

    return AuditReport(comm=comm, overlap=overlap, form=form,
                       n_devices=n_dev, n_buckets=n_buckets,
                       payload_ops=payload, wire_bytes_program=program,
                       wire_bytes_model=model)


def collect_donation(program, args):
    """Trace `program(*args)` and read the `donated_invars` flags off its
    top-level pjit eqn(s): `{(shape, dtype): [donated, ...]}` over every
    jitted-call input, plus whether ANY donation metadata was found at
    all (a wrapper jitted without `donate_argnums` has the flags all
    False — still "found"; an un-jitted program has no pjit eqn)."""
    import jax
    closed = jax.make_jaxpr(program)(*args)
    by_sig: dict = {}
    found = False
    for eqn in closed.jaxpr.eqns:
        donated = eqn.params.get("donated_invars")
        if donated is None:
            continue
        found = True
        for v, d in zip(eqn.invars, donated):
            aval = getattr(v, "aval", None)
            if aval is None or not hasattr(aval, "shape"):
                continue
            sig = (tuple(aval.shape), str(aval.dtype))
            by_sig.setdefault(sig, []).append(bool(d))
    return by_sig, found


def _donation_arg_labels(args, form: str):
    """Flatten the public argument tuple to (name, shape, dtype) leaves,
    named by the builders' fixed argument order."""
    import jax
    names = (("params", "key", "x", "y", "resid") if form == "step"
             else ("params", "key", "x_all", "y_all", "idxs", "resid"))
    # stateful builders append resid LAST in the public order
    if len(args) == len(names) - 1:
        names = names[:-1]
    out = []
    for name, val in zip(names, args):
        for leaf in jax.tree_util.tree_leaves(val):
            out.append((name, tuple(leaf.shape), str(leaf.dtype)))
    return out


def audit_donation(program, args, comm: str, overlap: bool, form: str, *,
                   n_dev: int = N_DEVICES) -> List[str]:
    """The donation-aliasing contract: the traced program donates exactly
    the inputs the wrapper DECLARES (`.donates` — params + key, plus the
    int8 error-feedback residual) and never a data input. Matching is by
    (shape, dtype) signature against the public argument tree (the
    geometry keeps every argument class signature-distinct). Raises
    AuditViolation naming the first leaf whose donation flag disagrees;
    returns the sorted donated label set otherwise."""
    cfg = f"comm={comm} overlap={overlap} form={form}"
    declared = getattr(program, "donates", None)
    if declared is None:
        raise AuditViolation(
            "donation-aliasing", cfg,
            "the jitted wrapper declares no .donates tuple — the traced "
            "donation flags have nothing to be cross-checked against")
    stateful = len(args) == (5 if form == "step" else 6)
    expected = {"params", "key"} | ({"resid"} if stateful else set())
    if set(declared) != expected:
        raise AuditViolation(
            "donation-aliasing", cfg,
            f"declared .donates {sorted(declared)} != the strategy's "
            f"expected donation set {sorted(expected)}")
    by_sig, found = collect_donation(program, args)
    if not found:
        raise AuditViolation(
            "donation-aliasing", cfg,
            "no donated_invars on any top-level pjit eqn — the step is "
            "not a jitted program at all (donation audits the jit "
            "wrapper, not the raw python body)")
    donated = set()
    for name, shape, dtype in _donation_arg_labels(args, form):
        flags = by_sig.get((shape, dtype))
        if flags is None:
            raise AuditViolation(
                "donation-aliasing", cfg,
                f"input {name} {dtype}{list(shape)} never appears among "
                f"the jitted program's invars — the tracer and the "
                f"builder disagree about the argument tree")
        want = name in declared
        if want and not all(flags):
            raise AuditViolation(
                "donation-aliasing", cfg,
                f"input {name} {dtype}{list(shape)} is declared donated "
                f"but the traced program does NOT donate it — a dropped "
                f"donate_argnums entry silently doubles its HBM "
                f"footprint")
        if not want and any(flags):
            raise AuditViolation(
                "donation-aliasing", cfg,
                f"data input {name} {dtype}{list(shape)} IS donated — "
                f"donating a batch input invalidates the caller's live "
                f"buffer")
        if want:
            donated.add(name)
    return sorted(donated)


def audit_program(program, args, comm: str, overlap: bool, form: str, *,
                  n_dev: int = N_DEVICES,
                  bucket_elems: Optional[int] = None,
                  quant_block: Optional[int] = None) -> AuditReport:
    """Trace `program(*args)` to a jaxpr, walk it, assert the contracts."""
    import jax
    state = walk_jaxpr(jax.make_jaxpr(program)(*args).jaxpr)
    return audit_collected(state["ops"], state["f64"], state["callbacks"],
                           comm, overlap, form, n_dev=n_dev,
                           bucket_elems=bucket_elems,
                           quant_block=quant_block)


def audit_step_program(comm: str, overlap: bool = False, *,
                       n_dev: int = N_DEVICES,
                       bucket_elems: Optional[int] = None,
                       quant_block: Optional[int] = None) -> AuditReport:
    prog, args = build_step_program(comm, overlap, n_dev=n_dev,
                                    bucket_elems=bucket_elems,
                                    quant_block=quant_block)
    report = audit_program(prog, args, comm, overlap, "step", n_dev=n_dev,
                           bucket_elems=bucket_elems,
                           quant_block=quant_block)
    # donation-aliasing audits the JIT WRAPPER (the raw step body above
    # carries no donation metadata), traced over the same AbstractMesh
    step, jargs = build_jit_step(comm, overlap, n_dev=n_dev,
                                 bucket_elems=bucket_elems,
                                 quant_block=quant_block)
    report.donated_labels = audit_donation(step, jargs, comm, overlap,
                                           "step", n_dev=n_dev)
    return report


def audit_run_program(comm: str, overlap: bool = False, *,
                      n_dev: int = N_DEVICES,
                      bucket_elems: Optional[int] = None,
                      quant_block: Optional[int] = None) -> AuditReport:
    prog, args = build_run_program(comm, overlap, n_dev=n_dev,
                                   bucket_elems=bucket_elems,
                                   quant_block=quant_block)
    report = audit_program(prog, args, comm, overlap, "run", n_dev=n_dev,
                           bucket_elems=bucket_elems,
                           quant_block=quant_block)
    # build_run_program already returns the jitted wrapper — one trace
    # serves both audits in principle, but collect_donation retraces so
    # the collective walker stays donation-agnostic
    report.donated_labels = audit_donation(prog, args, comm, overlap,
                                           "run", n_dev=n_dev)
    return report


def audit_matrix(comms: Sequence[str] = COMMS,
                 overlaps: Sequence[bool] = (False, True),
                 forms: Sequence[str] = FORMS, *,
                 n_dev: int = N_DEVICES,
                 bucket_elems: Optional[int] = None) -> List[AuditReport]:
    """The full contract matrix; raises AuditViolation on the first
    breach, returns one report per audited config otherwise."""
    out = []
    for comm in comms:
        for overlap in overlaps:
            for form in forms:
                fn = (audit_step_program if form == "step"
                      else audit_run_program)
                out.append(fn(comm, overlap, n_dev=n_dev,
                              bucket_elems=bucket_elems))
    return out


def main(argv=None) -> int:
    import argparse
    import os
    p = argparse.ArgumentParser(
        prog=os.path.basename(sys.argv[0]),
        description="Audit the lowered step-program matrix against the "
                    "repo's collective/dtype/wire contracts "
                    "(docs/STATIC_ANALYSIS.md). Exit 0 all pass, "
                    "3 contract violation, 2 usage.")
    p.add_argument("--comm", choices=COMMS + ("all",), default="all",
                   help="one strategy, or the whole matrix (default)")
    p.add_argument("--overlap", action="store_true",
                   help="with --comm: audit only the bucket-pipelined "
                        "variant (default with --comm: only overlap=False; "
                        "the full matrix always runs both)")
    p.add_argument("--form", choices=("step", "run", "both"),
                   default="both",
                   help="streaming step program, fit_cached scan body, or "
                        "both (default)")
    p.add_argument("--bucket-elems", type=int, default=None,
                   help="override the bucket size (exercises the "
                        "multi-bucket contracts)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable per-config reports on stdout")
    a = p.parse_args(argv)

    comms = COMMS if a.comm == "all" else (a.comm,)
    overlaps = ((False, True) if a.comm == "all"
                else ((True,) if a.overlap else (False,)))
    forms = FORMS if a.form == "both" else (a.form,)
    try:
        reports = audit_matrix(comms, overlaps, forms,
                               bucket_elems=a.bucket_elems)
    except AuditViolation as e:
        print(f"audit-program: FAIL {e}", file=sys.stderr)
        return 3
    if a.json:
        print(json.dumps([r.to_json() for r in reports], indent=2))
    else:
        for r in reports:
            print(f"audit-program: OK comm={r.comm:<8} "
                  f"overlap={str(r.overlap):<5} form={r.form:<4} "
                  f"buckets={r.n_buckets} "
                  f"wire_bytes={r.wire_bytes_program}")
        print(f"audit-program: OK — {len(reports)} config(s), every "
              f"contract holds")
    return 0


if __name__ == "__main__":
    sys.exit(main())
