"""Pull-based live metrics: Prometheus text-format exposition of the
unified registry, served from a stdlib HTTP thread.

The registry already renders the whole process as one JSON snapshot; this
module is the same truth in the format every scraping stack
(Prometheus/Grafana, `curl | grep`) consumes, LIVE — not after the run.
Two deliberate constraints:

  * pure stdlib (`http.server` on a daemon thread): the framework must not
    grow a web-framework dependency to answer GET /metrics;
  * read-only and lock-light: a scrape renders from the same live metric
    objects `snapshot()` reads — counters/gauges are attribute reads,
    histogram percentiles are O(buckets) — so a scraper polling every few
    seconds costs the training loop nothing.

Name mapping (documented in docs/OBSERVABILITY.md §Prometheus endpoint):
registry names pass through with every non-`[a-zA-Z0-9_:]` character
replaced by `_` — `serve.latency_s` -> `serve_latency_s`,
`health.worst_severity_level` -> `health_worst_severity_level`. Counters
render as `counter`, gauges as `gauge` (None-valued gauges are omitted —
absent beats lying), histograms as Prometheus `summary` quantile series
plus `_sum`/`_count` and a `_max` gauge.

Endpoints: `/metrics` (text/plain; version=0.0.4) and `/healthz` (JSON:
the `health_summary` verdict — 200 while nothing fatal fired, 503 after;
a serve replica fleet with zero healthy replicas is also 503, while a
degraded-but-serving fleet stays 200 with `fleet.degraded: true`).
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .registry import MetricsRegistry, get_registry

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_QUANTILES = (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99"))


def metric_name(name: str) -> str:
    """Registry name -> Prometheus metric name (one rule, no prefixes)."""
    out = _NAME_RE.sub("_", str(name))
    return ("_" + out) if out[:1].isdigit() else out


def _fmt(v) -> str:
    # Prometheus floats: repr keeps full precision; ints stay ints
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


def render_prometheus(registry: Optional[MetricsRegistry] = None) -> str:
    """The whole registry as Prometheus text exposition format (0.0.4).
    Deterministic: metrics sort by name, so the output is golden-testable
    and diffs between scrapes are semantic."""
    reg = registry if registry is not None else get_registry()
    snap = reg.snapshot()
    lines: "list[str]" = []
    for name, value in sorted(snap["counters"].items()):
        m = metric_name(name)
        lines.append(f"# TYPE {m} counter")
        lines.append(f"{m} {_fmt(value)}")
    for name, value in sorted(snap["gauges"].items()):
        if value is None:  # dead provider / never set: absent beats lying
            continue
        m = metric_name(name)
        lines.append(f"# TYPE {m} gauge")
        lines.append(f"{m} {_fmt(value)}")
    for name, h in sorted(snap["histograms"].items()):
        m = metric_name(name)
        lines.append(f"# TYPE {m} summary")
        for label, key in _QUANTILES:
            lines.append(f'{m}{{quantile="{label}"}} {_fmt(h[key])}')
        lines.append(f"{m}_sum {_fmt(h['mean'] * h['n'])}")
        lines.append(f"{m}_count {_fmt(h['n'])}")
        lines.append(f"# TYPE {m}_max gauge")
        lines.append(f"{m}_max {_fmt(h['max'])}")
    return "\n".join(lines) + "\n"


class _MetricsHandler(BaseHTTPRequestHandler):
    # class attrs bound per-server by start_metrics_server
    registry: MetricsRegistry = None  # type: ignore[assignment]

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler's spelling
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            body = render_prometheus(self.registry).encode()
            self._reply(200, body,
                        "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/healthz":
            from .health import health_summary
            verdict = health_summary(self.registry)
            # fatal watchdog OR a replica fleet with nothing healthy left:
            # both mean "stop sending traffic here" (a merely DEGRADED
            # fleet stays 200 — it is still serving)
            fleet = verdict.get("fleet")
            dead_fleet = fleet is not None and fleet["healthy"] == 0
            status = (503 if verdict["worst_severity"] == "fatal"
                      or dead_fleet else 200)
            self._reply(status, (json.dumps(verdict) + "\n").encode(),
                        "application/json")
        else:
            self._reply(404, b"not found: try /metrics or /healthz\n",
                        "text/plain")

    def _reply(self, status: int, body: bytes, ctype: str) -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # scrapes must not spam stderr
        pass


def start_metrics_server(port: int, *,
                         registry: Optional[MetricsRegistry] = None,
                         host: str = "127.0.0.1") -> ThreadingHTTPServer:
    """Bind the /metrics endpoint on `host:port` (0 = ephemeral) and serve
    it from a daemon thread. Returns the server; `.server_address[1]` is
    the bound port, `.shutdown()` stops it (the thread is daemonic, so a
    crashed run never hangs on it either)."""
    reg = registry if registry is not None else get_registry()

    class Handler(_MetricsHandler):
        pass

    Handler.registry = reg
    server = ThreadingHTTPServer((host, int(port)), Handler)
    server.daemon_threads = True
    thread = threading.Thread(target=server.serve_forever,
                              name="pdmt-metrics", daemon=True)
    thread.start()
    return server
