"""telemetry/ — process-wide observability: metrics registry, JSONL event
trace, runtime collectors.

The north star ("as fast as the hardware allows") is unreachable without
knowing where time and memory actually go; the reference repo descends from
an I/O-cost-evaluation harness whose timing code was lost (SURVEY.md §5.1).
This package is the measurement substrate every later perf PR builds on,
shared by train, serve, and bench alike:

  * `registry.py`  — counters / gauges / histograms, get-or-create by name,
    the whole process snapshot-able as ONE JSON dict. Absorbs what was
    `serve.metrics.LatencyHistogram` (now a thin alias of `Histogram`).
  * `events.py`    — schema-versioned JSONL event trace with nestable,
    async-dispatch-aware `span()` context managers (opt-in
    `block_until_ready` at exit, the `utils.profiling.Timer` contract); a
    process-wide `NullTracer` until `enable()` so instrumented call sites
    never branch and disabled telemetry costs nothing.
  * `runtime.py`   — collectors: cached process index (shared with
    `utils.logging.rank_zero_log`), XLA compile counts via `jax.monitoring`
    (engine-probe fallback), device `memory_stats()` guarded for CPU, host
    RSS.
  * `analysis.py`  — the READ side: load one or many per-process JSONL
    traces, reconstruct the span tree (structural validation shared with
    `scripts/check_telemetry.py`), per-phase p50/p95/max, per-epoch trend,
    cross-process straggler skew, the baseline-diff regression gate, and
    the serve-path tail-latency attribution report (`serve_report`:
    per-stage p50/p95/p99 + %-of-e2e from the request/batch spans
    `serve/tracing.py` emits, behind `trace report --serve`).
  * `export.py`    — merged trace -> Chrome trace-event JSON (Perfetto /
    `chrome://tracing`: one track per process, counter tracks from registry
    snapshots); `profiler_trace` is the op-level jax.profiler hatch.
  * `flight.py`    — bounded ring-buffer flight recorder fed by
    `parallel/wireup.py`'s probe/retry loop and `serve/admission.py`'s
    reject path; dumped to disk on failure/SIGTERM, stamped into bench
    `backend_unavailable` artifacts.
  * `health.py`    — the LIVE side: training-health watchdog (rolling
    EWMA detectors over the values the loop already fetches — loss
    spike, NaN/Inf, grad-norm explosion, update-ratio drift, throughput
    collapse, straggler drift), severity-leveled `health` events into
    trace + flight recorder + `health.*` metrics, and the
    warn / checkpoint-and-warn / abort fatal-signal policy.
  * `prom.py`      — pull-based live metrics: Prometheus text-format
    exposition of the registry (plus the `health_*` gauges), served from
    a stdlib HTTP thread (`/metrics`, `/healthz`) on `--metrics_port`.
  * `costs.py`     — program forensics: per-program XLA cost/memory
    records (`lowered.compile().cost_analysis()`/`.memory_analysis()`
    over the statics program builders + the serve bucket ladder), the
    measured-vs-analytic roofline attribution from DDP bench artifacts,
    the compile/HBM regression gate (`trace report --cost --baseline`),
    and OOM forensics (`looks_like_oom` + the flight-recorder program
    memory table).
  * `dispatch.py`  — DISPATCH forensics: the per-step host-timeline
    profiler that decomposes PR 12's overhead O into named phases
    (`python_prestep` / `dispatch` / `device_idle` / `sync_wait`) as
    `dispatch.*` histograms + flight samples + per-epoch trace points;
    `NullProfiler` zero-overhead default, sampled 1-in-K device-idle
    drain, `measure_dispatch_phases` bench probe. Front doors:
    `cli/train.py --profile_dispatch`, `trace report --overhead`,
    `make overhead-smoke`.
  * `cluster.py`   — CLUSTER forensics: the per-rank collective journal
    (static kinds/bytes from the audited schedule, host boundary stamps;
    NullJournal zero-overhead default), cross-rank desync detection,
    per-collective straggler attribution, and hang forensics — the
    collective watchdog that dumps a who-is-where table and flips
    `/healthz` when an entered collective never exits. Front doors:
    `cli/train.py --journal`, `trace report --cluster`,
    `make cluster-smoke`.

Front doors: `cli/train.py --telemetry DIR` (JSONL + rank-0 end-of-run
summary) / `--health POLICY` / `--metrics_port N`, `python -m
pytorch_ddp_mnist_tpu trace report|export` (analysis + Perfetto export +
regression gate), `cli/serve.py`'s `{"op": "stats"}` / `{"op": "health"}`
TCP ops (live registry snapshot, rolling p99 + service rate), `bench.py`
artifact stamps (incl. `health_summary`), `make obs-smoke` /
`make trace-smoke` / `make health-smoke` + `scripts/check_telemetry.py`
(schema + span-structure + health-event validation). See
docs/OBSERVABILITY.md.
"""

from .registry import (Counter, Gauge, Histogram, MetricsRegistry,  # noqa: F401
                       get_registry)
from .events import (SCHEMA_VERSION, EventTrace, NullTracer,  # noqa: F401
                     disable, enable, get_tracer)
from .runtime import (collect_memory, compile_attribution,  # noqa: F401
                      current_compile_label, device_memory_stats,
                      host_rss_bytes, install_compile_listener,
                      install_memory_watermarks, label_compiles,
                      process_index_cached, record_engine_compiles,
                      record_memory_point)
from .analysis import (analyze, compare, compare_overhead,  # noqa: F401
                       cost_record_errors, dispatch_record_errors,
                       load_trace, overhead_from_artifact, overhead_report,
                       serve_report, serve_structure_errors,
                       span_structure_errors, trace_files)
from .dispatch import (DispatchProfiler, NullProfiler,  # noqa: F401
                       measure_dispatch_phases)
from . import dispatch  # noqa: F401
from .costs import (CostRecord, attribution_from_artifact,  # noqa: F401
                    build_cost_report, compare_cost, harvest_engine,
                    harvest_program, harvest_step_matrix, looks_like_oom,
                    record_oom_forensics, register_program)
from . import costs  # noqa: F401
from .export import chrome_trace, profiler_trace, write_chrome_trace  # noqa: F401
from .flight import (FlightRecorder, get_flight_recorder)  # noqa: F401
from . import flight  # noqa: F401
from .cluster import (CollectiveJournal, CollectiveWatchdog,  # noqa: F401
                      NullJournal, cluster_report, disable_journal,
                      enable_journal, format_cluster_report, get_journal,
                      journal_files, load_journal, who_is_where)
from . import cluster  # noqa: F401
from .health import (HealthConfig, HealthEvent, TrainingHealthError,  # noqa: F401
                     Watchdog, device_health_aux, health_summary)
from .prom import (metric_name, render_prometheus,  # noqa: F401
                   start_metrics_server)
