"""Cluster forensics: the per-rank collective journal, cross-rank desync
detection, straggler attribution, and hang forensics.

Everything observability built so far (spans, request tracing, program
forensics) stops at the process boundary; the cross-rank view was a single
offline skew number. But the paper's whole point is MULTI-process training,
and at that scale the question a dead run poses is not "which step" but
*which rank died in which collective* (the per-collective characterization
regime of arXiv:1810.11112; the Gemma-on-TPU operational discipline). This
module closes that gap with a per-rank **collective journal**:

  * every payload collective the step program issues gets one journal
    record `(seq, kind, axis, bytes, bucket, step, t_enter, t_exit)`. The
    STATIC half (kinds/counts/bytes/buckets) comes from
    `parallel.collectives.collective_schedule` — the same bucket math the
    strategies run, pinned against the walked jaxpr by the
    `journal-schedule` contract in `statics/jaxpr_audit.py`, so the journal
    a rank writes is the program the auditor proved. The DYNAMIC half is
    host-side boundary stamps: the step's collectives share the step's
    dispatch window (XLA schedules inside one program; the host cannot
    subdivide it without buying a sync, and the zero-host-sync contract —
    pinned under `sanitize.no_host_sync` — is non-negotiable), while
    host-BLOCKING collectives (the wireup barrier, the reduce_max, the
    end-of-epoch flush that drains every step's collectives) are bracketed
    with true enter/exit records — they are where a hang actually
    manifests to the host;
  * seq numbering is identical on every rank by construction (same
    program, same schedule, and the journal opens with a cross-rank
    startup barrier at seq 0), so the merged per-rank journals form ONE
    causal timeline: **desync detection** (mismatched kind/bytes/bucket
    at the same seq, or cleanly-closed journals ending at different
    positions — exit 3, naming both ranks and the diverging collective),
    **per-collective straggler attribution** (wall-aligned enter-time
    spread per rank pair, p50/p95 — which collective eats the skew), and
    **hang forensics**: an enter with no exit is an open collective, and
    the report renders a who-is-where table of every rank's last journal
    position;
  * `CollectiveWatchdog` is the LIVE half of hang forensics: a daemon
    thread that fires when an open entry ages past its timeout — it dumps
    the who-is-where table to the flight recorder, dumps the ring, and
    flips `/healthz` to 503 (the `health.worst_severity_level` gauge the
    endpoint reads) — so an injected `collective_timeout` faultpoint (or
    a real dead peer) produces a report naming the stuck collective
    instead of a silent wedge.

Zero-overhead default, NullTracer-style: `get_journal()` returns the
shared `NullJournal` until `enable_journal()` swaps in a real one, so the
instrumented paths (wireup barrier, the train loop) cost one attribute
check when journaling is off — and the journal itself never touches the
device (host clock reads + one JSONL line per collective), so
journal-enabled training stays bitwise identical to journal-off (pinned by
test, with the sanitizer green).

Read side: `trace report --cluster DIR` (cli/trace.py) merges
`journal*.jsonl` + the flight dumps beside them. Pure stdlib at import
(registry/flight only), same contract as analysis.py: the read side must
run wherever the journals land.
"""

from __future__ import annotations

import glob
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from . import flight
from .registry import MetricsRegistry, get_registry

JOURNAL_SCHEMA = 1
# journal record kinds: one header, completed collectives, open/close
# brackets for host-blocking collectives, and a clean-shutdown trailer
# (its absence marks a crashed rank — position differences then read as a
# crash/hang story, not a desync)
JOURNAL_KINDS = ("journal_start", "program", "coll", "coll_enter",
                 "coll_exit", "journal_end")
# collective kinds a journal may record beyond the step schedule's wire
# kinds: the wireup barrier/reduce_max and the end-of-epoch flush (the
# host-side drain of every dispatched step's collectives)
HOST_KINDS = ("barrier", "allreduce", "flush")
# default live-hang threshold (seconds an entered collective may stay
# open); override via $PDMT_COLLECTIVE_HANG_S or the CLI
DEFAULT_HANG_S = 120.0


def journal_path(out_dir: str, rank: int) -> str:
    """Rank 0 writes `journal.jsonl`, other ranks `journal.rankN.jsonl` —
    the events.jsonl naming convention, so one `--telemetry DIR` holds
    both surfaces side by side."""
    name = ("journal.jsonl" if rank == 0 else f"journal.rank{rank}.jsonl")
    return os.path.join(out_dir, name)


def journal_files(target: str) -> List[str]:
    """Resolve a --telemetry dir (every `journal*.jsonl` inside) or a
    single journal file to a sorted list of paths; [] when absent. The
    single-file form applies the same `journal*.jsonl` name rule as the
    dir glob — an events trace (or any other file) handed here must NOT
    be misparsed as a collective journal (the export CLI routes one
    target through both resolvers)."""
    if os.path.isdir(target):
        return sorted(glob.glob(os.path.join(target, "journal*.jsonl")))
    name = os.path.basename(target)
    if (os.path.exists(target) and name.startswith("journal")
            and name.endswith(".jsonl")):
        return [target]
    return []


class CollectiveJournal:
    """The write side: one journal per rank, append-only JSONL,
    line-buffered like the event trace (a crash keeps everything up to its
    last completed record — which is exactly the hang evidence).

    Thread-safety: the train loop and the wireup brackets write from the
    main thread; the watchdog thread only READS `open_entry()` — the
    `_lock` makes the open-entry handoff and seq allocation atomic."""

    def __init__(self, path: str, *, rank: int = 0, world: int = 1,
                 registry: Optional[MetricsRegistry] = None):
        self.path = str(path)
        self.rank = int(rank)
        self.world = int(world)
        self.dir = os.path.dirname(os.path.abspath(self.path))
        self._f = open(self.path, "a", buffering=1)
        self._lock = threading.Lock()
        self._seq = 0
        self._open: Optional[dict] = None
        self._schedule: List[dict] = []
        self.overhead_s = 0.0   # cumulative host seconds spent journaling
        reg = registry if registry is not None else get_registry()
        self._collectives = reg.counter("cluster.collectives")
        self._bytes = reg.counter("cluster.bytes_on_wire")
        self._seq_gauge = reg.gauge("cluster.seq")
        self._seq_gauge.set(0)
        reg.gauge("cluster.world").set(self.world)
        reg.gauge("cluster.journal_overhead_s").set_fn(
            lambda: self.overhead_s)
        self._write({"kind": "journal_start", "v": JOURNAL_SCHEMA,
                     "rank": self.rank, "world": self.world,
                     "pid": os.getpid()})

    # -- plumbing ----------------------------------------------------------

    def _write(self, rec: dict) -> None:
        rec.setdefault("t_wall", time.time())
        rec.setdefault("t_mono", time.perf_counter())
        if not self._f.closed:
            self._f.write(json.dumps(rec) + "\n")

    # -- write surface -----------------------------------------------------

    def bind_program(self, comm: str, overlap: bool,
                     schedule: List[dict]) -> None:
        """Record the step program's static collective schedule (one
        `program` record; per-step `coll` records then reference it by
        position so the hot path writes indices, not repeated shapes)."""
        self._schedule = list(schedule)
        self._write({"kind": "program", "comm": str(comm),
                     "overlap": bool(overlap), "schedule": self._schedule})

    def record_step(self, step: int, t_enter: float, t_exit: float,
                    t_wall: float) -> None:
        """Expand one dispatched step into per-collective records: every
        schedule entry gets its own seq, sharing the step's host dispatch
        window [t_enter, t_exit] (enqueue-side stamps under async
        dispatch — the Timer/span honesty contract; the end-of-epoch
        flush bracket is where device-side completion is observable).
        `t_wall` is the window's ENTER wall stamp — the cross-rank
        alignment key the skew report and the export arrows ride."""
        t0 = time.perf_counter()
        with self._lock:
            for i, ent in enumerate(self._schedule):
                self._write({"kind": "coll", "seq": self._seq + i,
                             "k": ent["kind"], "axis": ent["axis"],
                             "bytes": ent["bytes"],
                             "bucket": ent["bucket"], "step": int(step),
                             "t_enter": t_enter, "t_exit": t_exit,
                             "t_wall": t_wall})
                self._bytes.inc(ent["bytes"])
            self._seq += len(self._schedule)
            self._collectives.inc(len(self._schedule))
            self._seq_gauge.set(self._seq)
        self.overhead_s += time.perf_counter() - t0

    def enter(self, kind: str, *, axis: str = "world", nbytes: int = 0,
              **attrs) -> int:
        """Open a host-BLOCKING collective (barrier / reduce_max / the
        epoch flush): writes the enter record and arms the watchdog's
        open-entry view. Returns the seq to pass to `exit`."""
        now_m, now_w = time.perf_counter(), time.time()
        with self._lock:
            seq = self._seq
            self._seq += 1
            self._open = {"seq": seq, "kind": str(kind),
                          "t_enter_mono": now_m, "t_enter_wall": now_w,
                          **attrs}
            self._write({"kind": "coll_enter", "seq": seq, "k": str(kind),
                         "axis": axis, "bytes": int(nbytes), "bucket": 0,
                         "t_enter": now_m, "t_wall": now_w, **attrs})
            self._collectives.inc()
            self._seq_gauge.set(self._seq)
        return seq

    def exit(self, seq: int) -> None:
        with self._lock:
            if self._open is not None and self._open["seq"] == seq:
                self._open = None
            self._write({"kind": "coll_exit", "seq": int(seq),
                         "t_exit": time.perf_counter()})

    def open_entry(self) -> Optional[dict]:
        """The currently entered-but-not-exited collective (the watchdog's
        poll target), or None."""
        with self._lock:
            return dict(self._open) if self._open is not None else None

    def last_position(self) -> dict:
        with self._lock:
            return {"rank": self.rank, "seq": self._seq,
                    "open": dict(self._open) if self._open else None}

    def close(self, clean: bool = True) -> None:
        """Write the `journal_end` trailer (clean shutdown marker the
        desync detector keys on) and close the file."""
        with self._lock:
            if not self._f.closed:
                if clean:
                    self._write({"kind": "journal_end", "seq": self._seq})
                self._f.close()


class NullJournal:
    """The disabled default: every call is a no-op; `enter` returns -1 so
    the bracketing call sites never branch (one attribute check on the
    barrier path, nothing at all on the per-step path — the loop only
    journals when handed a real journal)."""

    rank = 0
    world = 1
    overhead_s = 0.0

    def bind_program(self, comm, overlap, schedule):
        pass

    def record_step(self, step, t_enter, t_exit, t_wall):
        pass

    def enter(self, kind, *, axis="world", nbytes=0, **attrs):
        return -1

    def exit(self, seq):
        pass

    def open_entry(self):
        return None

    def last_position(self):
        return {"rank": 0, "seq": 0, "open": None}

    def close(self, clean=True):
        pass


_NULL = NullJournal()
_journal = _NULL
_watchdog: "Optional[CollectiveWatchdog]" = None
# enable/disable swap the process-wide journal; the wireup brackets and a
# late CLI toggle can race the swap (statics rule MUT002) — readers get
# either journal, both valid
_JOURNAL_LOCK = threading.Lock()


def get_journal():
    """The process-wide journal: a real CollectiveJournal after
    `enable_journal()`, the shared NullJournal otherwise."""
    return _journal


def enable_journal(out_dir: str, *, rank: int = 0, world: int = 1,
                   registry: Optional[MetricsRegistry] = None,
                   hang_timeout_s: Optional[float] = None,
                   watchdog: bool = True) -> CollectiveJournal:
    """Open this rank's journal under `out_dir` (created if needed), swap
    it in process-wide, and (by default) start the collective hang
    watchdog. `hang_timeout_s` falls back to $PDMT_COLLECTIVE_HANG_S,
    then DEFAULT_HANG_S."""
    global _journal, _watchdog
    os.makedirs(out_dir, exist_ok=True)
    j = CollectiveJournal(journal_path(out_dir, rank), rank=rank,
                          world=world, registry=registry)
    with _JOURNAL_LOCK:
        if isinstance(_journal, CollectiveJournal):
            _journal.close(clean=False)
        if _watchdog is not None:
            _watchdog.stop()
            _watchdog = None
        _journal = j
        if watchdog:
            if hang_timeout_s is None:
                from ..parallel.wireup import env_seconds
                hang_timeout_s = env_seconds("PDMT_COLLECTIVE_HANG_S",
                                             DEFAULT_HANG_S)
            _watchdog = CollectiveWatchdog(j, timeout_s=hang_timeout_s,
                                           registry=registry)
            _watchdog.start()
    return j


def disable_journal(clean: bool = True) -> None:
    """Stop the watchdog, write the `journal_end` trailer (`clean=False`
    for a crash path: the missing trailer IS the evidence), restore the
    null journal."""
    global _journal, _watchdog
    with _JOURNAL_LOCK:
        if _watchdog is not None:
            _watchdog.stop()
            _watchdog = None
        if isinstance(_journal, CollectiveJournal):
            _journal.close(clean=clean)
        _journal = _NULL


def measure_journal_overhead(schedule: List[dict], steps: int = 200) -> float:
    """Measured host seconds per journaled step for `schedule` — the
    in-artifact half of the zero-overhead claim (`bench.py --mode ddp`
    stamps `journal_overhead_share` = this / the measured step time).
    Writes to os.devnull: serialization + write syscall, no disk."""
    j = CollectiveJournal(os.devnull, rank=0, world=1,
                          registry=MetricsRegistry())
    try:
        j.bind_program("probe", False, schedule)
        t0 = time.perf_counter()
        for i in range(steps):
            t = time.perf_counter()
            j.record_step(i, t, t, time.time())
        return (time.perf_counter() - t0) / max(steps, 1)
    finally:
        j.close(clean=False)


# ---------------------------------------------------------------------------
# the live hang watchdog
# ---------------------------------------------------------------------------


class CollectiveWatchdog:
    """Fires when the journal's open entry (an entered, un-exited
    collective) ages past `timeout_s`: who-is-where table to the flight
    recorder, ring dump, `/healthz` flipped fatal (the
    `health.worst_severity_level` gauge prom.py's endpoint reads), one
    stderr line. Fires once per stuck seq — a wedged rank must not spam
    its own post-mortem."""

    def __init__(self, journal: CollectiveJournal, *,
                 timeout_s: float = DEFAULT_HANG_S,
                 registry: Optional[MetricsRegistry] = None,
                 poll_s: Optional[float] = None):
        self.journal = journal
        self.timeout_s = float(timeout_s)
        self.registry = registry if registry is not None else get_registry()
        self._poll_s = (poll_s if poll_s is not None
                        else max(self.timeout_s / 4.0, 0.01))
        self._stop = threading.Event()
        self._fired: "set[int]" = set()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._watch,
                                        name="pdmt-collective-watchdog",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def _watch(self) -> None:
        while not self._stop.wait(self._poll_s):
            entry = self.journal.open_entry()
            if entry is None or entry["seq"] in self._fired:
                continue
            age = time.perf_counter() - entry["t_enter_mono"]
            if age >= self.timeout_s:
                self._fired.add(entry["seq"])
                self.fire(entry, age)

    def fire(self, entry: dict, age_s: float) -> None:
        """The hang verdict (also callable synchronously — the CLI's
        injected-timeout path reports through here so the live and
        crash-path stories are one code path)."""
        report_hang(self.journal, entry, age_s=age_s,
                    registry=self.registry)


def report_hang(journal: CollectiveJournal, entry: dict, *,
                age_s: float = 0.0,
                registry: Optional[MetricsRegistry] = None) -> dict:
    """Record a stuck collective: flight `collective_hang` entry with the
    who-is-where table (every rank's last journal position, read from the
    shared telemetry dir), flight ring dump, `cluster.hangs` counter, and
    the fatal health flip (`health.worst_severity_level` = 2 →
    `/healthz` answers 503; `health.fired.collective_hang` counts it).
    Returns the who-is-where table."""
    import sys
    reg = registry if registry is not None else get_registry()
    who = who_is_where(journal.dir)
    flight.record("collective_hang", rank=journal.rank,
                  seq=int(entry.get("seq", -1)),
                  collective=str(entry.get("kind", "?")),
                  age_s=round(float(age_s), 3), who_is_where=who)
    reg.counter("cluster.hangs").inc()
    reg.counter("health.fired.collective_hang").inc()
    reg.counter("health.events_total").inc()
    worst = reg.gauge("health.worst_severity_level")
    if not isinstance(worst.value, (int, float)) or worst.value < 2:
        worst.set(2)
    flight.dump(reason=f"collective hang: rank {journal.rank} entered seq "
                       f"{entry.get('seq')} ({entry.get('kind')}), not "
                       f"exited after {age_s:.1f}s")
    print(f"[cluster] rank{journal.rank} FATAL collective_hang: entered "
          f"seq {entry.get('seq')} ({entry.get('kind')}), not exited "
          f"after {age_s:.1f}s — who-is-where: "
          + "; ".join(f"rank{w['rank']} at seq {w['seq']} ({w['last']})"
                      for w in who),
          file=sys.stderr, flush=True)
    return who


def who_is_where(target: str) -> List[dict]:
    """Every rank's last journal position, read from the journals under
    `target` (the shared --telemetry dir — the same shared-fs contract
    the checkpoint directory documents): one
    `{rank, seq, last, open}` row per journal, `last` a human label of
    the newest record, `open` the stuck collective when an enter has no
    exit."""
    rows = []
    for path in journal_files(target):
        j = load_journal(path)
        rows.append({"rank": j["rank"], "seq": j["last_seq"],
                     "last": j["last_label"],
                     "open": j["open"][0] if j["open"] else None})
    rows.sort(key=lambda r: r["rank"])
    return rows


# ---------------------------------------------------------------------------
# read side: load, merge, detect
# ---------------------------------------------------------------------------


def load_journal(path: str) -> dict:
    """Parse one rank's journal -> {rank, world, program, records, open,
    closed, last_seq, last_label, segments, errors}. Lenient like the
    trace loader: a torn last line (the crash case) becomes an error
    string, never an exception. `records` holds completed collectives
    (both stamps); `open` holds enters with no matching exit — the hang
    evidence.

    The file opens in APPEND mode (a re-exec'd outage resume or a plain
    re-run into the same --telemetry dir adds a segment beginning with a
    fresh `journal_start`, exactly like events.jsonl), and seq numbering
    restarts per segment — so the loader reports the NEWEST segment (the
    live run's story; a stale segment's seqs would collide and its open
    entries would read as hangs a later clean run already superseded).
    Earlier segments stay in the file for manual inspection; their count
    is surfaced as `segments`."""
    rank, world = 0, 1
    program: Optional[dict] = None
    records: List[dict] = []
    enters: Dict[int, dict] = {}
    errors: List[str] = []
    closed = False
    last_seq = 0
    last_label = "journal_start"
    segments = 0
    with open(path) as f:
        for line_no, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                errors.append(f"{path}:{line_no}: malformed JSON ({e})")
                continue
            if not isinstance(rec, dict):
                errors.append(f"{path}:{line_no}: record is not an object")
                continue
            kind = rec.get("kind")
            if kind == "journal_start":
                # a fresh appended segment: reset to ITS story (seq scope
                # and open-entry state restart with the run)
                segments += 1
                rank = int(rec.get("rank", rank))
                world = int(rec.get("world", world))
                program = None
                records = []
                enters = {}
                closed = False
                last_seq = 0
                last_label = "journal_start"
            elif kind == "program":
                program = rec
            elif kind == "coll":
                records.append(rec)
                last_seq = max(last_seq, int(rec.get("seq", 0)) + 1)
                last_label = (f"{rec.get('k')} step {rec.get('step')}"
                              if rec.get("step") is not None
                              else str(rec.get("k")))
            elif kind == "coll_enter":
                enters[rec.get("seq")] = rec
                last_seq = max(last_seq, int(rec.get("seq", 0)) + 1)
                last_label = f"{rec.get('k')} (open)"
            elif kind == "coll_exit":
                ent = enters.pop(rec.get("seq"), None)
                if ent is not None:
                    ent = dict(ent)
                    ent["t_exit"] = rec.get("t_exit")
                    records.append(ent)
                    last_label = str(ent.get("k"))
                else:
                    errors.append(f"{path}:{line_no}: exit for seq "
                                  f"{rec.get('seq')} with no matching "
                                  f"enter")
            elif kind == "journal_end":
                closed = True
                last_label = "journal_end"
            elif kind is not None and kind not in JOURNAL_KINDS:
                errors.append(f"{path}:{line_no}: unknown journal record "
                              f"kind {kind!r}")
    open_entries = sorted(
        ({"seq": int(e.get("seq", -1)), "kind": str(e.get("k", "?")),
          "t_enter": e.get("t_enter"), "t_wall": e.get("t_wall"),
          **{k: v for k, v in e.items()
             if k in ("first_seq", "last_seq", "steps")}}
         for e in enters.values()), key=lambda e: e["seq"])
    return {"path": path, "rank": rank, "world": world, "program": program,
            "records": records, "open": open_entries, "closed": closed,
            "last_seq": last_seq, "last_label": last_label,
            "segments": max(segments, 1), "errors": errors}


def _percentile(sorted_vals: List[float], q: float) -> float:
    import math
    if not sorted_vals:
        return 0.0
    rank = max(1, math.ceil(q * len(sorted_vals)))
    return sorted_vals[min(rank, len(sorted_vals)) - 1]


def _flight_context(target: str) -> List[dict]:
    """Fault/hang entries from the flight dumps beside the journals — the
    injected-fault and watchdog-verdict context a hang report renders.
    Lenient: an unreadable dump is skipped (the journals are the primary
    evidence)."""
    out = []
    if not os.path.isdir(target):
        return out
    for path in sorted(glob.glob(os.path.join(target, "flight.*.json"))):
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            continue
        for e in payload.get("entries", []):
            if isinstance(e, dict) and e.get("kind") in (
                    "fault_injected", "collective_hang"):
                out.append({k: v for k, v in e.items()
                            if k != "t_mono"})
    return out


def cluster_report(target: str) -> dict:
    """Merge every rank's journal (+ flight context) under `target` into
    the cluster forensics report: desync violations, per-rank-pair
    enter-time skew with the worst collective named, and the hang section
    (open collectives + the who-is-where table). `cli/trace.py report
    --cluster` renders it and exits 3 on desync."""
    paths = journal_files(target)
    journals = [load_journal(p) for p in paths]
    journals.sort(key=lambda j: j["rank"])
    ranks = [j["rank"] for j in journals]
    errors: List[str] = []
    for j in journals:
        errors.extend(j["errors"])

    # per-seq view: rank -> record (completed collectives + opens)
    by_seq: Dict[int, Dict[int, dict]] = {}
    for j in journals:
        for rec in j["records"]:
            by_seq.setdefault(int(rec.get("seq", -1)), {})[j["rank"]] = rec
        for e in j["open"]:
            by_seq.setdefault(e["seq"], {})[j["rank"]] = {
                "k": e["kind"], "bytes": None, "bucket": None,
                "t_enter": e["t_enter"], "t_wall": e["t_wall"],
                "open": True}

    # -- desync: same seq, different collective ---------------------------
    violations: List[dict] = []
    for seq in sorted(by_seq):
        per_rank = by_seq[seq]
        if len(per_rank) < 2:
            continue
        items = sorted(per_rank.items())
        r0, rec0 = items[0]
        for r1, rec1 in items[1:]:
            for fld in ("k", "bytes", "bucket"):
                v0, v1 = rec0.get(fld), rec1.get(fld)
                if v0 is None or v1 is None:
                    continue   # an open entry has no bytes to compare
                if v0 != v1:
                    violations.append({
                        "seq": seq, "field": fld,
                        "ranks": [r0, r1],
                        "rank_a": {"rank": r0, "kind": rec0.get("k"),
                                   "bytes": rec0.get("bytes"),
                                   "bucket": rec0.get("bucket")},
                        "rank_b": {"rank": r1, "kind": rec1.get("k"),
                                   "bytes": rec1.get("bytes"),
                                   "bucket": rec1.get("bucket")},
                        "detail": f"rank {r0} recorded "
                                  f"{rec0.get('k')}/{rec0.get('bytes')}B/"
                                  f"bucket {rec0.get('bucket')} at seq "
                                  f"{seq} while rank {r1} recorded "
                                  f"{rec1.get('k')}/{rec1.get('bytes')}B/"
                                  f"bucket {rec1.get('bucket')}"})
                    break
    # position desync: two CLEANLY closed journals ending at different
    # seqs ran different programs (a crashed rank's short journal is a
    # crash story, reported under hang/who-is-where instead)
    closed = [j for j in journals if j["closed"]]
    for i in range(len(closed)):
        for k in range(i + 1, len(closed)):
            a, b = closed[i], closed[k]
            if a["last_seq"] != b["last_seq"]:
                violations.append({
                    "seq": min(a["last_seq"], b["last_seq"]),
                    "field": "position",
                    "ranks": [a["rank"], b["rank"]],
                    "rank_a": {"rank": a["rank"], "seq": a["last_seq"]},
                    "rank_b": {"rank": b["rank"], "seq": b["last_seq"]},
                    "detail": f"rank {a['rank']} closed its journal at "
                              f"seq {a['last_seq']} while rank "
                              f"{b['rank']} closed at seq "
                              f"{b['last_seq']} — the ranks ran "
                              f"different collective sequences"})

    # -- straggler attribution: wall-aligned enter spread per rank pair --
    pair_deltas: Dict[str, List[Tuple[float, int, str]]] = {}
    for seq, per_rank in by_seq.items():
        enters = {r: rec.get("t_wall") for r, rec in per_rank.items()
                  if isinstance(rec.get("t_wall"), (int, float))}
        if len(enters) < 2:
            continue
        rs = sorted(enters)
        kind = per_rank[rs[0]].get("k")
        for i in range(len(rs)):
            for k in range(i + 1, len(rs)):
                delta = abs(enters[rs[i]] - enters[rs[k]])
                pair_deltas.setdefault(f"{rs[i]}-{rs[k]}", []).append(
                    (delta, seq, str(kind)))
    pairs = {}
    worst = None
    for pair, deltas in sorted(pair_deltas.items()):
        vals = sorted(d for d, _s, _k in deltas)
        top = max(deltas)
        pairs[pair] = {"n": len(vals),
                       "p50_s": _percentile(vals, 0.50),
                       "p95_s": _percentile(vals, 0.95),
                       "max_s": top[0],
                       "worst": {"seq": top[1], "kind": top[2],
                                 "spread_s": top[0]}}
        if worst is None or top[0] > worst["spread_s"]:
            worst = {"pair": pair, "seq": top[1], "kind": top[2],
                     "spread_s": top[0]}

    # -- hang section -----------------------------------------------------
    open_all = [{"rank": j["rank"], **e} for j in journals
                for e in j["open"]]
    stuck = min(open_all, key=lambda e: e["seq"]) if open_all else None
    who = [{"rank": j["rank"], "seq": j["last_seq"],
            "last": j["last_label"], "closed": j["closed"],
            "open": j["open"][0] if j["open"] else None}
           for j in journals]

    totals = {"collectives": sum(len(j["records"]) for j in journals),
              "bytes": sum(int(r.get("bytes") or 0)
                           for j in journals for r in j["records"])}
    # appended re-runs: the report covers each journal's NEWEST segment;
    # say so rather than letting a truncated view read as the whole story
    multi_segment = sorted(j["rank"] for j in journals
                           if j["segments"] > 1)
    return {
        "report": "cluster_forensics",
        "v": 1,
        "files": paths,
        "ranks": ranks,
        "n_ranks": len(ranks),
        "programs": sorted({(j["program"] or {}).get("comm", "?")
                            for j in journals if j["program"]}),
        "totals": totals,
        "multi_segment_ranks": multi_segment,
        "errors": errors,
        "desync": {"ok": not violations, "violations": violations},
        "skew": {"pairs": pairs, "worst": worst},
        "hang": {"open": open_all, "stuck": stuck, "who_is_where": who},
        "faults": _flight_context(target),
    }


def format_cluster_report(report: dict) -> str:
    """Human rendering of `cluster_report` (the --json flag prints the
    dict itself)."""
    lines = [f"cluster report: {report['n_ranks']} rank(s), "
             f"{report['totals']['collectives']} journaled collective(s), "
             f"{report['totals']['bytes']} wire byte(s)"
             + (f", program(s): {', '.join(report['programs'])}"
                if report["programs"] else "")]
    if report.get("multi_segment_ranks"):
        lines.append(f"note: rank(s) {report['multi_segment_ranks']} hold "
                     f"appended earlier run segments — this report covers "
                     f"each journal's NEWEST segment only")
    d = report["desync"]
    if d["ok"]:
        lines.append("desync: OK — every shared seq agrees on "
                     "kind/bytes/bucket")
    else:
        lines.append(f"desync: {len(d['violations'])} violation(s)")
        for v in d["violations"][:8]:
            lines.append(f"  DESYNC seq {v['seq']} ({v['field']}): "
                         f"{v['detail']}")
    sk = report["skew"]
    if sk["pairs"]:
        for pair, st in sorted(sk["pairs"].items()):
            lines.append(f"skew rank pair {pair}: p50 "
                         f"{st['p50_s'] * 1e3:.3f}ms p95 "
                         f"{st['p95_s'] * 1e3:.3f}ms max "
                         f"{st['max_s'] * 1e3:.3f}ms at seq "
                         f"{st['worst']['seq']} ({st['worst']['kind']})")
        w = sk["worst"]
        lines.append(f"worst straggler collective: seq {w['seq']} "
                     f"({w['kind']}) — {w['spread_s'] * 1e3:.3f}ms spread "
                     f"on pair {w['pair']}")
    else:
        lines.append("skew: fewer than 2 ranks share a seq "
                     "(nothing to compare)")
    h = report["hang"]
    if h["stuck"] is not None:
        s = h["stuck"]
        lines.append(f"HANG: rank {s['rank']} entered collective seq "
                     f"{s['seq']} ({s['kind']}) and never exited")
        lines.append("who-is-where (every rank's last journal position):")
        for w in h["who_is_where"]:
            state = ("OPEN at seq {seq} ({kind})".format(**w["open"])
                     if w["open"] else
                     "closed cleanly" if w["closed"] else
                     "no trailer (crashed?)")
            lines.append(f"  rank {w['rank']}: seq {w['seq']}, last "
                         f"{w['last']} — {state}")
    else:
        lines.append("hang: none (no open collectives)")
    for f_ in report["faults"][:8]:
        lines.append(f"flight: {f_.get('kind')} "
                     + ", ".join(f"{k}={v}" for k, v in sorted(f_.items())
                                 if k not in ("kind", "t_wall",
                                              "who_is_where", "seq")
                                 and not isinstance(v, (dict, list))))
    if report["errors"]:
        lines.append(f"journal parse: {len(report['errors'])} "
                     f"problem(s); first: {report['errors'][0]}")
    verdict = ("FAIL — cross-rank desync" if not d["ok"]
               else "HANG detected" if h["stuck"] is not None else "OK")
    lines.append(f"cluster verdict: {verdict}")
    return "\n".join(lines)
