"""Program forensics: XLA cost/memory attribution, roofline reports, and
the compile/HBM regression gate.

The observability stack attributes everything HOST-side (spans, request
stages, data-wait); this module is the first layer that can answer where
time and memory go BELOW the step boundary. It harvests
`lowered.compile().cost_analysis()` / `.memory_analysis()` for every
jitted program the repo builds — the comm x overlap DDP step/run programs
(built through `statics.jaxpr_audit.build_step_program` /
`build_run_program`, so forensics and the contract audits can never walk
different programs) and the serve engine's AOT bucket ladder — into one
per-program `CostRecord`:

    {program, flops, transcendentals, bytes_accessed,
     argument/output/temp/generated_code/alias bytes, peak_bytes,
     analytic_flops (the exact MLP roofline model), wire_bytes
     (parallel.collectives.bytes_on_wire), compile_s}

All byte/flop figures are PER-DEVICE: XLA reports the partitioned SPMD
module each device runs, and `bytes_on_wire` is per-device by contract, so
the two sides of a record always talk about the same program.

The read side (`trace report --cost`, cli/trace.py) combines the records
with MEASURED step time from a DDP bench artifact
(`attribution_from_artifact`, the arXiv:1810.11112 decomposition): per
strategy, measured step time T splits into analytic compute C (from the
artifact's own 1-device rate via `scaling_efficiency_vs_1dev`), wire time
M (the artifact's isolated `collective_s_p50` probe), and overhead
O = T - bound where bound = C + M (serial) or max(C, M) (overlapped) —
the roofline story that explains the MULTICHIP_r07 0.09-0.17 efficiency
numbers (docs/PERF.md). `analytic_efficiency` = C / bound is the
efficiency the cost model predicts if only compute and wire existed;
measured efficiency below it is overhead, not physics.

`compare_cost` is the regression gate: `trace report --cost --baseline
OLD` exits 3 when the compiled-program count GREW (a recompile storm or a
silently widened ladder — any growth gates, refresh the baseline to
acknowledge a deliberate one), when summary or per-program peak HBM
regressed past the threshold, or when a strategy's analytic efficiency
fell past it (better-is-bigger, old/new ratio — the `compare` efficiency
convention).

OOM forensics: `looks_like_oom` classifies allocation failures (the
RESOURCE_EXHAUSTED / out-of-memory shapes, deliberately disjoint from
`parallel.wireup.looks_like_backend_loss`'s retryable signatures), and
`record_oom_forensics` dumps the loaded program memory table
(`register_program` feeds it at harvest/engine warmup) plus the live
watermarks to the flight recorder — an OOM names the program and the
budget it blew instead of dying as an opaque XlaRuntimeError.

Module import is pure stdlib (jax only inside harvest functions), by the
analysis.py contract: the report/gate side must run wherever the JSON
lands, including hosts without the framework installed.

Front doors: `python -m pytorch_ddp_mnist_tpu trace cost` (harvest ->
COST_r0X.json artifact + optional --telemetry trace), `trace report
--cost [--baseline OLD]`, `make cost-smoke`. See docs/OBSERVABILITY.md
§Program forensics.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import asdict, dataclass
from typing import Dict, List, Optional, Sequence

# Trace point-event name for one emitted cost record; the checker
# (scripts/check_telemetry.py via analysis.cost_record_errors) validates
# these: non-empty string `program`, non-negative numeric cost fields.
COST_POINT = "program_cost"
COST_REPORT_TAG = "program_cost_report"

# Default geometry: the audit matrix's (statics/jaxpr_audit.py).
N_DEVICES = 8
BATCH_PER_DEVICE = 16
# Run-form (fit_cached scan body) harvest geometry, passed EXPLICITLY to
# build_run_program so the analytic totals below always price the same
# step count the program executes.
RUN_EPOCHS = 1
RUN_STEPS = 2
# The bench default per-chip batch — the legacy-artifact fallback when a
# strategies row predates the `per_chip_batch` stamp (bench.py rows carry
# it since this PR).
DEFAULT_PER_CHIP_BATCH = 128

COMMS = ("pmean", "sharded", "bf16", "int8")

# Substrings (lowercased match) of allocation-failure errors. Narrow by
# the looks_like_backend_loss design rule: a retryable backend outage
# ("unavailable", "deadline exceeded") must NOT read as an OOM, and a
# shape/compile error must match neither.
OOM_SIGNATURES = (
    "resource_exhausted", "resource exhausted", "out of memory",
    "out-of-memory", "failed to allocate", "allocation failure",
    "cannot allocate", "exceeds available memory", "hbm limit",
)


def _label(comm: str, overlap: bool = False, form: str = "step") -> str:
    """`ddp.<form>.<comm>[+overlap]` — kept as a LITERAL twin of
    `parallel.collectives.step_cost_label` so this module imports no
    framework at load time (tests pin the two against each other)."""
    return f"ddp.{form}.{comm}" + ("+overlap" if overlap else "")


def looks_like_oom(e: BaseException) -> bool:
    """Does this error look like a device allocation failure (vs a backend
    loss or a deterministic program error)? The forensics trigger: only a
    True here dumps the program memory table."""
    msg = str(e).lower()
    return any(sig in msg for sig in OOM_SIGNATURES)


@dataclass
class CostRecord:
    """One jitted program's cost/memory story (see module docstring; all
    figures per device). `compiled=False` means the deviceless fallback:
    flops/bytes_accessed come from `lowered.cost_analysis()` (available
    without a backend) and the memory fields are None — compile-dependent
    analysis needs real devices."""
    program: str
    kind: str                    # "ddp" | "serve"
    n_devices: int
    compiled: bool
    flops: Optional[float] = None
    transcendentals: Optional[float] = None
    bytes_accessed: Optional[float] = None
    argument_bytes: Optional[int] = None
    output_bytes: Optional[int] = None
    temp_bytes: Optional[int] = None
    generated_code_bytes: Optional[int] = None
    alias_bytes: Optional[int] = None
    peak_bytes: Optional[int] = None
    analytic_flops: Optional[int] = None
    wire_bytes: Optional[int] = None
    compile_s: Optional[float] = None
    comm: Optional[str] = None
    overlap: Optional[bool] = None
    form: Optional[str] = None
    model: Optional[str] = None
    param_scale: Optional[int] = None
    n_params: Optional[int] = None
    batch_per_device: Optional[int] = None
    error: Optional[str] = None

    def to_json(self) -> dict:
        return {k: v for k, v in asdict(self).items()
                if v is not None or k in ("program", "kind", "compiled")}


# -- the loaded-program table (what an OOM dump names) -----------------------

_TABLE_LOCK = threading.Lock()
_PROGRAM_TABLE: Dict[str, dict] = {}


def register_program(record: "CostRecord | dict") -> None:
    """Remember a program's memory story in the process-wide table the OOM
    forensics dump names. Harvest registers every record; the serve engine
    registers its bucket ladder at warmup."""
    rec = record.to_json() if isinstance(record, CostRecord) else dict(record)
    label = rec.get("program")
    if not label:
        return
    with _TABLE_LOCK:
        _PROGRAM_TABLE[str(label)] = rec


def loaded_program_table() -> Dict[str, dict]:
    with _TABLE_LOCK:
        return {k: dict(v) for k, v in _PROGRAM_TABLE.items()}


def record_oom_forensics(e: BaseException, program: Optional[str] = None,
                         dump: bool = True) -> Optional[str]:
    """If `e` classifies as an OOM, record an `oom_forensics` entry (the
    failing program's name, the loaded program memory table, and the live
    watermarks) in the flight recorder, dump the ring, and return the dump
    path. Non-OOM errors return None untouched — callers re-raise either
    way, this only annotates the post-mortem."""
    if not looks_like_oom(e):
        return None
    from . import flight
    from .runtime import MEM_GAUGES, current_compile_label
    label = program or current_compile_label() or "<unlabeled>"
    watermarks = {}
    for name, fn in MEM_GAUGES:
        try:
            v = fn()
        except (OSError, ValueError, RuntimeError):
            v = None  # a dying backend's probe must not mask the OOM
        if v is not None:
            watermarks[name] = v
    table = loaded_program_table()
    programs = {
        lbl: {k: rec.get(k) for k in ("peak_bytes", "temp_bytes",
                                      "argument_bytes", "output_bytes")
              if rec.get(k) is not None}
        for lbl, rec in table.items()}
    flight.record("oom_forensics", program=label, error=str(e)[:500],
                  watermarks=watermarks, programs=programs)
    if not dump:
        return None
    return flight.dump(reason=f"oom: {label}")


# -- the analytic roofline model ---------------------------------------------

def model_macs(dims: Sequence[int]) -> int:
    """Forward MACs per image of an MLP with the given layer dims —
    784*128 + 128*128 + 128*10 = 118,016 for the reference model (the
    bench.py MACS_FWD_PER_IMG constant, generalized to the zoo)."""
    return sum(int(a) * int(b) for a, b in zip(dims[:-1], dims[1:]))


def analytic_step_flops(dims: Sequence[int], batch_per_device: int) -> int:
    """Exact matmul-FLOPs lower bound of one per-device TRAIN step:
    2 FLOPs/MAC forward, backward ~2x forward (the standard 6x rule the
    bench roofline uses). Element-wise ops (relu, dropout, softmax) are
    excluded — this is the roofline floor, not the XLA bill."""
    return 6 * model_macs(dims) * int(batch_per_device)


def analytic_forward_flops(dims: Sequence[int], batch_per_device: int) -> int:
    """Exact matmul-FLOPs lower bound of one per-device INFERENCE pass
    (2 FLOPs/MAC, no backward) — the serve bucket ladder's model."""
    return 2 * model_macs(dims) * int(batch_per_device)


# -- harvest (jax imported lazily from here on) ------------------------------

def _cost_dict(ca) -> dict:
    """Normalize `cost_analysis()`'s shape (a list of per-module dicts on
    some jax versions, one dict on others) to the main module's dict."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca or {})


def _nonneg(v) -> Optional[float]:
    """XLA reports some fields as -1/garbage where unknown (CPU
    `optimal_seconds` is famously negative); records carry only honest
    non-negative values."""
    if isinstance(v, (int, float)) and v >= 0:
        return float(v)
    return None


# Failure modes a harvest must degrade through, never die of: XLA refusing
# to compile the sharded program (XlaRuntimeError is a RuntimeError), an
# AbstractMesh with no devices (RuntimeError/ValueError), an older jaxlib
# without memory_analysis (AttributeError/NotImplementedError).
_HARVEST_ERRORS = (RuntimeError, ValueError, TypeError, AttributeError,
                   NotImplementedError, OSError)


def _fill_memory(rec: "CostRecord", ma) -> None:
    """Copy a `memory_analysis()` result's fields into `rec` and derive
    `peak_bytes` — XLA's standard peak estimate: everything resident at
    once (args + outputs + temps + code), minus donated aliases counted
    on both sides. The ONE place the formula lives, so DDP and
    serve-ladder records can never compute different peaks."""
    for attr, fld in (("argument_size_in_bytes", "argument_bytes"),
                      ("output_size_in_bytes", "output_bytes"),
                      ("temp_size_in_bytes", "temp_bytes"),
                      ("generated_code_size_in_bytes",
                       "generated_code_bytes"),
                      ("alias_size_in_bytes", "alias_bytes")):
        v = getattr(ma, attr, None)
        if isinstance(v, (int, float)) and v >= 0:
            setattr(rec, fld, int(v))
    parts = [rec.argument_bytes, rec.output_bytes, rec.temp_bytes,
             rec.generated_code_bytes]
    if any(p is not None for p in parts):
        rec.peak_bytes = (sum(p or 0 for p in parts)
                          - (rec.alias_bytes or 0))


def harvest_program(program, args, *, label: str, kind: str, n_devices: int,
                    registry=None, **meta) -> CostRecord:
    """Lower (and where a backend exists, compile) `program(*args)` and
    extract its cost/memory record. Compiles run under
    `runtime.label_compiles(label)` so the jax.monitoring listener
    attributes their durations to this program; a failed compile degrades
    to the deviceless `lowered.cost_analysis()` with `compiled=False` and
    the failure in `record.error`."""
    import jax

    from .runtime import label_compiles

    rec = CostRecord(program=label, kind=kind, n_devices=int(n_devices),
                     compiled=False, **meta)
    try:
        lowered = jax.jit(program).lower(*args)
    except _HARVEST_ERRORS as e:
        rec.error = f"lower: {e}"[:300]
        register_program(rec)
        return rec
    try:
        t0 = time.perf_counter()
        with label_compiles(label):
            compiled = lowered.compile()
        rec.compile_s = round(time.perf_counter() - t0, 6)
        rec.compiled = True
        ca = _cost_dict(compiled.cost_analysis())
        ma = compiled.memory_analysis()
    except _HARVEST_ERRORS as e:
        # deviceless (AbstractMesh) or refused compile: the pre-compile
        # analysis still prices the program's math
        rec.error = f"compile: {e}"[:300]
        try:
            ca = _cost_dict(lowered.cost_analysis())
        except _HARVEST_ERRORS as e2:
            rec.error += f"; cost_analysis: {e2}"[:200]
            ca = {}
        ma = None
    rec.flops = _nonneg(ca.get("flops"))
    rec.transcendentals = _nonneg(ca.get("transcendentals"))
    rec.bytes_accessed = _nonneg(ca.get("bytes accessed"))
    if ma is not None:
        _fill_memory(rec, ma)
    register_program(rec)
    return rec


def _resolve_mesh(n_dev: int):
    """A real n_dev mesh when the backend has the devices (compile +
    memory_analysis work), else None (the builders fall back to their
    deviceless AbstractMesh — cost-only records)."""
    import jax
    try:
        devices = jax.devices()
    except RuntimeError:
        return None  # no backend at all: deviceless harvest
    if len(devices) < n_dev:
        return None
    from ..parallel.mesh import DATA_AXIS, make_mesh
    return make_mesh([n_dev], [DATA_AXIS], devices[:n_dev])


def harvest_step_matrix(*, comms: Sequence[str] = COMMS,
                        overlaps: Sequence[bool] = (False, True),
                        forms: Sequence[str] = ("step",),
                        n_dev: int = N_DEVICES,
                        batch: int = BATCH_PER_DEVICE,
                        model: str = "mlp", param_scale: int = 1,
                        mesh="auto") -> List[CostRecord]:
    """Cost records for the comm x overlap DDP program matrix, built
    through the statics program builders (the audit's exact programs).
    `batch` is PER-DEVICE rows, matching the builders."""
    import jax

    from ..models.zoo import resolve_model
    from ..models import param_count
    from ..parallel import collectives
    from ..statics import jaxpr_audit

    spec = resolve_model(model, param_scale)
    params = spec.init(jax.random.PRNGKey(0))
    n_params = param_count(params)
    if mesh == "auto":
        mesh = _resolve_mesh(n_dev)
    out: List[CostRecord] = []
    for comm in comms:
        wire = collectives.bytes_on_wire(params, n_dev, comm)
        for overlap in overlaps:
            for form in forms:
                if form == "step":
                    prog, args = jaxpr_audit.build_step_program(
                        comm, overlap, n_dev=n_dev, batch=batch,
                        mesh=mesh, model=model, param_scale=param_scale)
                    n_steps = 1
                else:
                    # the scan body executes RUN_EPOCHS x RUN_STEPS train
                    # steps: the record's analytic/wire totals must price
                    # the whole program, not one step of it
                    prog, args = jaxpr_audit.build_run_program(
                        comm, overlap, n_dev=n_dev, batch=batch,
                        epochs=RUN_EPOCHS, steps=RUN_STEPS,
                        mesh=mesh, model=model, param_scale=param_scale)
                    n_steps = RUN_EPOCHS * RUN_STEPS
                out.append(harvest_program(
                    prog, args, label=_label(comm, overlap, form),
                    kind="ddp", n_devices=n_dev, comm=comm,
                    overlap=overlap, form=form, model=model,
                    param_scale=param_scale, n_params=n_params,
                    batch_per_device=batch, wire_bytes=wire * n_steps,
                    analytic_flops=(analytic_step_flops(spec.dims, batch)
                                    * n_steps)))
    return out


def register_compiled(label: str, compiled, *, kind: str, n_devices: int,
                      **meta) -> CostRecord:
    """A record from an ALREADY-compiled executable (the serve engine's
    warm bucket ladder: its compiles already happened under their own
    labels, so only the analyses run here)."""
    rec = CostRecord(program=label, kind=kind, n_devices=int(n_devices),
                     compiled=True, **meta)
    try:
        ca = _cost_dict(compiled.cost_analysis())
        rec.flops = _nonneg(ca.get("flops"))
        rec.transcendentals = _nonneg(ca.get("transcendentals"))
        rec.bytes_accessed = _nonneg(ca.get("bytes accessed"))
    except _HARVEST_ERRORS as e:
        rec.error = f"cost_analysis: {e}"[:300]
    try:
        _fill_memory(rec, compiled.memory_analysis())
    except _HARVEST_ERRORS as e:
        rec.error = ((rec.error or "")
                     + f" memory_analysis: {e}"[:200]).strip()
    register_program(rec)
    return rec


def harvest_engine(engine) -> List[CostRecord]:
    """Cost records for a serve `InferenceEngine`'s AOT bucket ladder —
    one per compiled bucket, `serve.bucket<N>` labels, forward-pass
    analytic floor."""
    from ..models.mlp import MLP_DIMS
    n_dev = 1 if engine.mesh is None else int(engine.mesh.devices.size)
    out = []
    for bucket, compiled in sorted(engine.compiled_programs().items()):
        out.append(register_compiled(
            f"serve.bucket{bucket}", compiled, kind="serve",
            n_devices=n_dev, batch_per_device=bucket // n_dev,
            wire_bytes=0,
            analytic_flops=analytic_forward_flops(MLP_DIMS,
                                                  bucket // n_dev)))
    return out


def emit_records(tracer, records: Sequence[CostRecord]) -> None:
    """One `program_cost` point event per record into the JSONL trace —
    the shape `analysis.cost_record_errors` / check_telemetry validate."""
    for rec in records:
        tracer.point(COST_POINT, **rec.to_json())


# -- the attribution / roofline decomposition (pure stdlib) ------------------

def attribution_from_artifact(artifact: dict,
                              per_chip_batch: Optional[int] = None) -> List[dict]:
    """The measured-vs-analytic decomposition, one row per strategies
    entry of a DDP bench artifact (MULTICHIP_r0X.json / `bench.py --mode
    ddp` lines): measured per-device step time T splits into

      compute_s  C = scaling_efficiency_vs_1dev * T  (the 1-device step
                 time of the same per-chip batch, by the efficiency
                 definition — no extra measurement needed),
      comm_s     M = collective_s_p50 (the isolated wire probe), and
      overhead_s O = T - bound,  bound = C + M serial, max(C, M)
                 overlapped (comm analytically hidden behind compute).

    Shares divide by T and sum to 1. `analytic_efficiency` = C / bound:
    what efficiency WOULD be if the step were only compute + wire;
    measured efficiency under it is dispatch/launch overhead, the
    arXiv:1810.11112 residual. `per_chip_batch` overrides rows that
    predate the stamp (legacy artifacts default to 128, the bench
    default; MULTICHIP_r07 was measured at 4 — pass it)."""
    rows = []
    for r in artifact.get("strategies") or []:
        if not isinstance(r, dict):
            continue
        n = r.get("n_devices", artifact.get("n_devices"))
        rate = r.get("images_per_sec")
        eff = r.get("scaling_efficiency_vs_1dev")
        m = r.get("collective_s_p50")
        b = per_chip_batch or r.get("per_chip_batch") \
            or DEFAULT_PER_CHIP_BATCH
        if not all(isinstance(v, (int, float)) and v > 0
                   for v in (n, rate, eff, b)) or n <= 1 \
                or not isinstance(m, (int, float)) or m < 0:
            continue
        t = float(b) * float(n) / float(rate)      # measured step seconds
        c = float(eff) * t                          # analytic compute
        overlap = bool(r.get("overlap"))
        bound = max(c, float(m)) if overlap else c + float(m)
        o = t - bound
        rows.append({
            "program": _label(str(r.get("strategy", "?")), overlap),
            "strategy": r.get("strategy"),
            "overlap": overlap,
            "n_devices": int(n),
            "per_chip_batch": int(b),
            "measured_step_s": round(t, 6),
            "compute_s": round(c, 6),
            "comm_s": round(float(m), 6),
            "bound_s": round(bound, 6),
            "overhead_s": round(o, 6),
            "shares": {
                "compute": round(c / t, 4),
                # the wire time the step actually EXPOSES: all of M when
                # serial, only the part compute can't hide when overlapped
                "comm_exposed": round(max(0.0, bound - c) / t, 4),
                "overhead": round(o / t, 4),
            },
            "measured_efficiency": round(float(eff), 4),
            "analytic_efficiency": round(c / bound, 4),
        })
    return rows


def build_cost_report(records: Sequence[CostRecord], *,
                      artifact: Optional[dict] = None,
                      per_chip_batch: Optional[int] = None,
                      meta: Optional[dict] = None) -> dict:
    """The COST_r0X.json shape: per-program records, compile attribution,
    the roofline attribution rows (when a bench artifact is supplied),
    and the summary the gate and the bench stamp read:
    {peak_hbm_bytes, analytic_efficiency, compile_s_total,
    compile_count}."""
    recs = [r.to_json() if isinstance(r, CostRecord) else dict(r)
            for r in records]
    peaks = [r["peak_bytes"] for r in recs
             if isinstance(r.get("peak_bytes"), (int, float))]
    compile_times = [r["compile_s"] for r in recs
                     if isinstance(r.get("compile_s"), (int, float))]
    attribution = (attribution_from_artifact(artifact, per_chip_batch)
                   if artifact else [])
    try:
        from .runtime import compile_attribution
        compile_attr = compile_attribution()
    except ImportError:
        compile_attr = {}
    report = {
        "report": COST_REPORT_TAG,
        "v": 1,
        "generated_unix": round(time.time(), 3),
        "records": recs,
        "attribution": attribution,
        "compile_attribution": compile_attr,
        "summary": {
            "programs": len(recs),
            "compile_count": sum(1 for r in recs if r.get("compiled")),
            "compile_s_total": round(sum(compile_times), 6),
            "peak_hbm_bytes": max(peaks) if peaks else None,
            "analytic_efficiency": {
                row["program"]: row["analytic_efficiency"]
                for row in attribution},
        },
    }
    if meta:
        report.update(meta)
    return report


# -- the gate ----------------------------------------------------------------

def compare_cost(new: dict, baseline: dict, threshold: float = 1.5) -> dict:
    """Diff two cost reports -> {"rows": [...], "regressions": [...]},
    the `compare`/`compare_data` shape. Three gated axes:

      * compile_count — ANY growth regresses (program counts are
        structural, not noisy: more compiles means a recompile storm or a
        silently widened ladder; a deliberate growth is acknowledged by
        refreshing the baseline);
      * peak HBM — summary peak and per-program peak_bytes for labels in
        both reports, new/old ratio past `threshold`;
      * analytic_efficiency — per program label in both, old/new ratio
        past `threshold` (better-is-bigger, the efficiency-gate
        convention).
    """
    rows, regressions = [], []

    def add(metric, program, old_v, new_v, ratio, regressed):
        row = {"metric": metric, "program": program, "baseline": old_v,
               "new": new_v, "ratio": ratio, "regressed": bool(regressed)}
        rows.append(row)
        if row["regressed"]:
            regressions.append(row)

    ns, bs = new.get("summary") or {}, baseline.get("summary") or {}
    oc, nc = bs.get("compile_count"), ns.get("compile_count")
    if isinstance(oc, int) and isinstance(nc, int):
        add("compile_count", "<total>", oc, nc,
            (nc / oc) if oc else float("inf") if nc else 1.0, nc > oc)
    op, np_ = bs.get("peak_hbm_bytes"), ns.get("peak_hbm_bytes")
    if isinstance(op, (int, float)) and isinstance(np_, (int, float)) \
            and op > 0:
        add("peak_hbm_bytes", "<max>", op, np_, np_ / op,
            np_ / op > threshold)
    old_recs = {r.get("program"): r for r in baseline.get("records") or []
                if isinstance(r, dict)}
    for r in new.get("records") or []:
        if not isinstance(r, dict):
            continue
        o = old_recs.get(r.get("program"))
        if not o:
            continue
        ob, nb = o.get("peak_bytes"), r.get("peak_bytes")
        if isinstance(ob, (int, float)) and isinstance(nb, (int, float)) \
                and ob > 0:
            add("peak_bytes", r["program"], ob, nb, nb / ob,
                nb / ob > threshold)
    oe = (bs.get("analytic_efficiency") or {})
    ne = (ns.get("analytic_efficiency") or {})
    for label in sorted(set(oe) & set(ne)):
        ov, nv = oe[label], ne[label]
        if not (isinstance(ov, (int, float)) and isinstance(nv, (int, float))
                and ov > 0):
            continue
        ratio = (ov / nv) if nv > 0 else float("inf")
        add("analytic_efficiency", label, ov, nv, ratio, ratio > threshold)
    return {"threshold": threshold, "rows": rows,
            "regressions": regressions}


# -- rendering ---------------------------------------------------------------

def format_cost_report(report: dict) -> str:
    lines = []
    s = report.get("summary") or {}
    lines.append(f"program cost report: {s.get('programs', 0)} program(s), "
                 f"{s.get('compile_count', 0)} compiled, "
                 f"compile_s_total {s.get('compile_s_total', 0.0):.3f}s, "
                 f"peak HBM "
                 f"{s.get('peak_hbm_bytes') if s.get('peak_hbm_bytes') is not None else 'n/a'}")
    recs = report.get("records") or []
    if recs:
        lines.append(f"{'program':<24} {'flops':>14} {'bytes_acc':>12} "
                     f"{'peak_bytes':>12} {'wire_bytes':>12} {'compile_s':>10}")
        for r in recs:
            def fmt(v, nd=0):
                return (f"{v:,.{nd}f}" if isinstance(v, (int, float))
                        else "-")
            lines.append(f"{str(r.get('program', '?')):<24} "
                         f"{fmt(r.get('flops')):>14} "
                         f"{fmt(r.get('bytes_accessed')):>12} "
                         f"{fmt(r.get('peak_bytes')):>12} "
                         f"{fmt(r.get('wire_bytes')):>12} "
                         f"{r.get('compile_s') if r.get('compile_s') is not None else '-':>10}")
    att = report.get("attribution") or []
    if att:
        lines.append("")
        lines.append(f"measured-step attribution "
                     f"(T = compute + exposed comm + overhead):")
        lines.append(f"{'program':<24} {'step_s':>9} {'compute':>8} "
                     f"{'comm_exp':>9} {'overhead':>9} {'eff meas':>9} "
                     f"{'eff bound':>9}")
        for row in att:
            sh = row["shares"]
            lines.append(f"{row['program']:<24} "
                         f"{row['measured_step_s']:>9.4f} "
                         f"{100 * sh['compute']:>7.1f}% "
                         f"{100 * sh['comm_exposed']:>8.1f}% "
                         f"{100 * sh['overhead']:>8.1f}% "
                         f"{row['measured_efficiency']:>9.4f} "
                         f"{row['analytic_efficiency']:>9.4f}")
    elif not recs:
        lines.append("no cost records and no attribution rows (harvest "
                     "with `trace cost`, or pass a DDP bench artifact)")
    return "\n".join(lines)


def format_compare_cost(diff: dict) -> str:
    lines = [f"cost gate (compile-count growth; peak-HBM / "
             f"analytic-efficiency ratio > {diff['threshold']:g}x):"]
    for row in diff["rows"]:
        verdict = "REGRESSION" if row["regressed"] else "ok"
        lines.append(f"  {row['metric']:<20} {row['program']:<24} "
                     f"{row['baseline']} -> {row['new']}  "
                     f"({row['ratio']:.2f}x)  {verdict}")
    if not diff["rows"]:
        lines.append("  (no cost metric overlaps baseline — nothing gated)")
    n = len(diff["regressions"])
    lines.append(f"regression gate: "
                 f"{f'FAIL — {n} metric(s) regressed' if n else 'PASS'}")
    return "\n".join(lines)


# -- report loading (shared with cli/trace.py) -------------------------------

def load_cost_report(target: str, per_chip_batch: Optional[int] = None):
    """(report, error) from `target`: a saved cost report (its
    COST_REPORT_TAG, plain or under the combined --baseline shape
    {"report": {...}}), or a DDP bench artifact with strategies rows
    (attribution-only report, framework-free). Anything else errors."""
    try:
        with open(target) as f:
            head = json.load(f)
    except OSError as e:
        return None, f"{target}: {e}"
    except ValueError as e:
        return None, f"{target}: not a JSON document ({e})"
    if not isinstance(head, dict):
        return None, f"{target}: not a JSON object"
    if head.get("report") == COST_REPORT_TAG:
        return head, None
    nested = head.get("report")
    if isinstance(nested, dict) and nested.get("report") == COST_REPORT_TAG:
        return nested, None
    if isinstance(head.get("strategies"), list):
        att = attribution_from_artifact(head, per_chip_batch)
        if not att:
            return None, (f"{target}: artifact carries no strategy rows "
                          f"the attribution can decompose (needs "
                          f"images_per_sec, scaling_efficiency_vs_1dev, "
                          f"collective_s_p50, n_devices > 1)")
        return build_cost_report(
            [], artifact=head, per_chip_batch=per_chip_batch,
            meta={"source": target}), None
    return None, (f"{target}: neither a {COST_REPORT_TAG} document nor a "
                  f"DDP bench artifact with strategies rows")


# -- the harvest front door (`trace cost`, cli/trace.py) ---------------------

def harvest_cli(a) -> int:
    """The `trace cost` subcommand body (argparse namespace from
    cli/trace.py): harvest the DDP matrix (+ the serve ladder), emit the
    records (JSONL trace when --telemetry, JSON artifact via -o), print
    the human report."""
    import os
    import sys

    from . import enable, disable, get_registry, get_tracer
    from . import flight
    from .runtime import (collect_memory, install_compile_listener,
                          install_memory_watermarks, record_memory_point)

    # the measured artifact is read FIRST: a mistyped --artifact path must
    # fail in milliseconds, not after minutes of compile harvest
    artifact = None
    if a.artifact:
        try:
            with open(a.artifact) as f:
                artifact = json.load(f)
        except OSError as e:
            print(f"trace cost: --artifact {e}", file=sys.stderr)
            return 1
        except ValueError as e:
            print(f"trace cost: --artifact {a.artifact}: not a JSON "
                  f"document ({e})", file=sys.stderr)
            return 1
        if not isinstance(artifact, dict) \
                or not isinstance(artifact.get("strategies"), list):
            print(f"trace cost: --artifact {a.artifact}: not a DDP bench "
                  f"artifact (no strategies rows)", file=sys.stderr)
            return 1

    reg = get_registry()
    install_compile_listener()
    install_memory_watermarks(reg)
    if a.telemetry:
        os.makedirs(a.telemetry, exist_ok=True)
        flight.set_dump_dir(a.telemetry)
        enable(a.telemetry, process_index=0)
    tracer = get_tracer()
    try:
        with tracer.span("cost_harvest", model=a.model,
                         param_scale=a.param_scale):
            forms = (("step", "run") if a.form == "both" else (a.form,))
            records = harvest_step_matrix(
                forms=forms, n_dev=a.n_devices, batch=a.batch,
                model=a.model, param_scale=a.param_scale)
            if a.serve_ladder:
                import jax
                from ..models.mlp import init_mlp
                from ..serve.engine import InferenceEngine
                engine = InferenceEngine(init_mlp(jax.random.key(0)),
                                         max_batch=a.serve_max_batch)
                records.extend(harvest_engine(engine))
            emit_records(tracer, records)
            record_memory_point(tracer)
        import jax
        meta = {
            "backend": jax.default_backend(),
            "jax_version": jax.__version__,
            "n_devices": a.n_devices,
            "model": a.model,
            "param_scale": a.param_scale,
            "batch_per_device": a.batch,
        }
        if a.artifact:
            meta["measured_artifact"] = a.artifact
        report = build_cost_report(records, artifact=artifact,
                                   per_chip_batch=a.per_chip_batch,
                                   meta=meta)
        collect_memory(reg)
        tracer.snapshot(reg)
    finally:
        if a.telemetry:
            disable()
    if a.out:
        with open(a.out, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
        print(f"trace cost: wrote {len(report['records'])} record(s) to "
              f"{a.out}")
    print(format_cost_report(report))
    failed = [r for r in report["records"] if r.get("error")]
    if failed:
        print(f"trace cost: note: {len(failed)} record(s) degraded "
              f"(uncompiled/partial) — deviceless fallback, see their "
              f"'error' fields", file=sys.stderr)
    return 0
