"""Runtime collectors: process identity, XLA compile counts, memory.

Everything here is observation of state other subsystems already produce —
no collector forces device work, and every probe degrades to None/no-op on
backends that do not expose it (CPU has no `memory_stats`; old jax builds
may lack `jax.monitoring`), so telemetry can be enabled unconditionally.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

_process_index: Optional[int] = None
# One lock for this module's lazy singletons (_process_index,
# _compile_counter): the Prometheus scrape thread and serve's event loop
# resolve these concurrently with the train loop (statics rule MUT002).
# Hot-path reads stay lock-free (double-checked; a benign duplicate
# resolution is idempotent, a torn install is not).
_LOCK = threading.Lock()


def process_index_cached() -> int:
    """`jax.process_index()` resolved once per process and cached.

    The uncached spelling imports jax and queries the backend on every
    call — `utils.logging.rank_zero_log` used to pay that on each factory
    invocation, and the event trace would pay it per record. Failure
    (jax absent, backend not up yet) is reported as rank 0 and NOT cached:
    the pre-`jax.distributed`-init behavior stays "treat as process 0", and
    the first post-init call still resolves the real rank."""
    global _process_index
    if _process_index is None:
        with _LOCK:
            if _process_index is None:
                try:
                    import jax
                    _process_index = int(jax.process_index())
                except Exception:  # statics-baseline: any client error
                    # pre-init (jax absent, backend down) deliberately
                    # degrades to rank 0 without caching
                    return 0
    return _process_index


# -- XLA compile counting + duration attribution -----------------------------

_compile_counter = None  # the one counter the process listener feeds
_compile_hist = None     # its sibling xla.compile_s duration histogram
# label -> {"count": int, "total_s": float}: per-program compile attribution,
# fed by the listener whenever a `label_compiles(...)` block is active on the
# compiling thread (jax compiles synchronously on the calling thread, so the
# thread-local label set around a `.compile()`/first-call is the program
# being compiled). Guarded by _LOCK like the other module singletons.
_compile_attr: dict = {}
_compile_label = threading.local()


def install_compile_listener(registry=None,
                             counter_name: str = "xla.compiles",
                             hist_name: str = "xla.compile_s") -> bool:
    """Count backend compiles into `registry.counter(counter_name)` AND
    record each compile's duration into `registry.histogram(hist_name)` via
    `jax.monitoring`'s duration events (one
    `/jax/core/compile/backend_compile_duration` event per XLA compile —
    jit cache hits fire nothing, so the counter reads true compile work,
    the cold-compile signal serve/'s bucket ladder exists to eliminate;
    the histogram's total is the process's whole compile-time bill, the
    `compile_s_total` bench/cost stamp).

    Returns True when the listener feeds the REQUESTED counter.
    jax.monitoring listeners cannot be unregistered individually, so
    exactly one counter per process can be fed: a repeat install for the
    same target is a no-op True, while a different registry/counter gets
    False (not armed there — no silent zero-reading counter), and the
    caller keeps the engine-probe pattern (`record_engine_compiles`) as
    the portable source. False likewise where jax.monitoring is
    unavailable."""
    global _compile_counter, _compile_hist
    from .registry import get_registry
    reg = registry or get_registry()
    with _LOCK:
        if _compile_counter is not None:
            # peek, don't create: a mismatched re-install must not leave a
            # zero-reading counter behind in the unfed registry
            return reg._counters.get(counter_name) is _compile_counter
        try:
            from jax import monitoring
        except ImportError:
            return False  # no counter created: the stamp reads absent, not 0
        counter = reg.counter(counter_name)
        hist = reg.histogram(hist_name)

        def _on_duration(key: str, duration: float, **kw) -> None:
            if "backend_compile" in key:
                counter.inc()
                hist.record(float(duration))
                label = getattr(_compile_label, "value", None)
                if label:
                    with _LOCK:
                        slot = _compile_attr.setdefault(
                            label, {"count": 0, "total_s": 0.0})
                        slot["count"] += 1
                        slot["total_s"] += float(duration)

        monitoring.register_event_duration_secs_listener(_on_duration)
        _compile_counter = counter
        _compile_hist = hist
        return True


class label_compiles:
    """Context manager naming the program whose compiles are about to run:
    every backend-compile duration the jax.monitoring listener sees while
    the block is active on THIS thread is attributed to `label` in
    `compile_attribution()` (the telemetry/costs.py per-program
    compile-time table). Nestable (inner label wins, outer restored);
    costs nothing when the listener is not armed."""

    def __init__(self, label: str):
        self.label = str(label)
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_compile_label, "value", None)
        _compile_label.value = self.label
        return self

    def __exit__(self, *exc):
        _compile_label.value = self._prev
        return None


def current_compile_label() -> Optional[str]:
    """The innermost `label_compiles` label active on this thread (None
    outside any block) — the OOM classifier names it in its forensics."""
    return getattr(_compile_label, "value", None)


def compile_attribution() -> dict:
    """{label: {"count": n, "total_s": s}} of every labeled compile the
    listener has seen — the per-program compile-time story
    (docs/OBSERVABILITY.md §Program forensics). Unlabeled compiles are in
    the xla.compiles/xla.compile_s registry metrics only."""
    with _LOCK:
        return {k: dict(v) for k, v in _compile_attr.items()}


def record_engine_compiles(registry, compile_count: int,
                           counter_name: str = "serve.engine_compiles") -> None:
    """The compile-cache probe fallback: adopt an engine's own
    `compile_count` (serve/engine.py's structural no-cold-compile
    instrument) into the registry, portable to builds without
    jax.monitoring. Counting-only by construction — the probe is an
    integer the engine kept, so no durations exist to feed
    `xla.compile_s` here (the listener path owns those)."""
    registry.counter(counter_name).set_total(compile_count)


# -- memory ------------------------------------------------------------------

def device_memory_stats() -> Optional[dict]:
    """`memory_stats()` of the first local device, or None where the
    backend does not implement it (CPU, some simulators)."""
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats()
        return dict(stats) if stats else None
    except (ImportError, RuntimeError, IndexError, AttributeError):
        # jax absent / backend not up / zero devices / no memory_stats on
        # this backend — all mean "no device memory picture", not an error
        return None


def host_rss_bytes() -> Optional[int]:
    """This process's resident set size in bytes (Linux /proc, with a
    getrusage fallback for other unixes); None when neither source
    exists."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    try:
        import resource
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # Linux reports KiB, macOS bytes; this branch only runs off-Linux
        return int(rss) if os.uname().sysname == "Darwin" else int(rss) * 1024
    except (ImportError, AttributeError, OSError, ValueError):
        return None  # no resource module / no uname: no RSS source


def _device_mem_field(key: str) -> Optional[int]:
    """One field of the first device's memory_stats, None when the backend
    has no memory picture (CPU) — the live-gauge provider body."""
    stats = device_memory_stats()
    if stats and key in stats:
        return int(stats[key])
    return None


# The HBM watermark gauge names (docs/OBSERVABILITY.md §Program forensics):
# live set_fn gauges, so every Prometheus scrape and registry snapshot reads
# the INSTANT — a None value (CPU, dead backend) renders as absent in
# Prometheus and null in snapshots, the memory_stats degrade contract.
MEM_GAUGES = (
    ("mem.device_in_use_bytes", lambda: _device_mem_field("bytes_in_use")),
    ("mem.device_peak_bytes",
     lambda: _device_mem_field("peak_bytes_in_use")),
    ("mem.host_rss_bytes", host_rss_bytes),
)


def install_memory_watermarks(registry=None) -> None:
    """Bind the `mem.*` watermark gauges as LIVE providers on `registry`:
    `mem.device_in_use_bytes` / `mem.device_peak_bytes` (guarded like the
    memory_stats probe — None off-accelerator) and `mem.host_rss_bytes`
    (always a number where /proc or getrusage exists). Idempotent —
    re-installing rebinds the same providers."""
    from .registry import get_registry
    reg = registry or get_registry()
    for name, fn in MEM_GAUGES:
        reg.gauge(name).set_fn(fn)


def collect_memory(registry=None) -> dict:
    """Stamp the current memory picture into registry gauges and return it:
    `host.rss_bytes` always, `device.bytes_in_use` / `device.peak_bytes_in_use`
    when the backend reports them. Also installs the live `mem.*` watermark
    gauges (install_memory_watermarks) so any snapshot taken after one
    collect carries the watermark names — the `--require mem.` gate's
    contract."""
    from .registry import get_registry
    reg = registry or get_registry()
    install_memory_watermarks(reg)
    out = {}
    rss = host_rss_bytes()
    if rss is not None:
        reg.gauge("host.rss_bytes").set(rss)
        out["host.rss_bytes"] = rss
    stats = device_memory_stats()
    if stats:
        for key in ("bytes_in_use", "peak_bytes_in_use"):
            if key in stats:
                reg.gauge(f"device.{key}").set(int(stats[key]))
                out[f"device.{key}"] = int(stats[key])
    return out


def record_memory_point(tracer, name: str = "mem_watermark") -> None:
    """Emit one `mem_watermark` point event carrying the current watermark
    values (device in-use/peak when the backend reports them, host RSS
    always) — the train loop calls this once per epoch so Perfetto renders
    an HBM counter track under the epoch spans (telemetry/export.py). Pure
    host-side probes: no device sync, no fetch (the loop's zero-sync
    contract holds); a NullTracer costs one attribute check."""
    if not getattr(tracer, "enabled", False):
        return
    attrs = {}
    for key, fn in MEM_GAUGES:
        v = fn()
        if v is not None:
            attrs[key] = v
    if attrs:
        tracer.point(name, **attrs)
