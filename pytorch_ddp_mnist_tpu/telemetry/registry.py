"""Unified metrics registry: counters, gauges, histograms — one JSON snapshot.

Before this module, observability was fragmented per subsystem:
`serve/metrics.py` owned a private latency histogram, `utils/profiling.py`
owned standalone timers, and the train loop printed an end-of-epoch line —
three disjoint surfaces with nothing correlated or exportable. The registry
is the one process-wide home for all of them: any subsystem creates named
metrics (get-or-create, so wiring order never matters), and
`MetricsRegistry.snapshot()` renders the whole process state as one
JSON-able dict — the payload of the `{"op": "stats"}` serve endpoint, the
final record of a `--telemetry` JSONL trace, and the compile/memory stamp
on bench artifacts.

`Histogram` generalizes what was `serve.metrics.LatencyHistogram` (that name
survives as a thin alias): values land in a log-spaced bucket map
(floor 2 us, 12 buckets/decade for the seconds-unit default) rather than an
unbounded sample list — constant memory at any rate, percentile error
bounded by the bucket ratio (~21%), always reported pessimistically (the
winning bucket's UPPER edge, clamped to the recorded max).
"""

from __future__ import annotations

import math
import threading
from typing import Callable, Dict, Optional

# 12 buckets per decade: ratio 10^(1/12) ~ 1.21 between edges.
_BUCKETS_PER_DECADE = 12
_FLOOR = 2e-6


class Counter:
    """Monotonic counter. `inc()` only goes up; `set_total` exists for
    absorbing an externally maintained total (e.g. an engine's
    compile_count probe) without double-counting increments."""

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n: int = 1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (n={n})")
        self.value += n

    def set_total(self, total: int) -> None:
        """Adopt an external running total; never moves the value down."""
        self.value = max(self.value, int(total))


class Gauge:
    """Point-in-time value: `set()` a number, or bind a zero-arg callable
    with `set_fn` so the snapshot reads the instant (the serve queue-depth
    pattern), not a stale write."""

    def __init__(self, name: str):
        self.name = name
        self._value: Optional[float] = None
        self._fn: Optional[Callable[[], float]] = None

    def set(self, value) -> None:
        self._value, self._fn = value, None

    def set_fn(self, fn: Callable[[], float]) -> None:
        self._fn = fn

    @property
    def value(self):
        if self._fn is not None:
            try:
                return self._fn()
            except Exception:
                return None  # a dead provider must not kill the snapshot
        return self._value


class Histogram:
    """Log-bucketed value recorder with percentile estimation (the
    generalized serve LatencyHistogram — see module docstring for the
    accuracy contract). Unit-agnostic: record seconds, bytes, rows."""

    def __init__(self, name: str = "", *, floor: float = _FLOOR,
                 buckets_per_decade: int = _BUCKETS_PER_DECADE):
        self.name = name
        self.floor = floor
        self.buckets_per_decade = buckets_per_decade
        self.counts: Dict[int, int] = {}
        self.n = 0
        self.total = 0.0
        self.max = 0.0

    def _index(self, value: float) -> int:
        if value <= self.floor:
            return 0
        return 1 + int(self.buckets_per_decade
                       * math.log10(value / self.floor))

    def _edge(self, index: int) -> float:
        # upper edge of bucket `index` (bucket 0 = [0, floor])
        return self.floor * 10 ** (index / self.buckets_per_decade)

    def record(self, value: float) -> None:
        i = self._index(value)
        self.counts[i] = self.counts.get(i, 0) + 1
        self.n += 1
        self.total += value
        self.max = max(self.max, value)

    def percentile(self, q: float) -> float:
        """Estimated q-quantile (q in [0, 1]); 0.0 when empty. Clamped to
        the recorded max so a sparse tail bucket cannot report a value
        larger than any sample actually reached."""
        if self.n == 0:
            return 0.0
        rank = q * self.n
        seen = 0
        for i in sorted(self.counts):
            seen += self.counts[i]
            if seen >= rank:
                return min(self._edge(i), self.max)
        return self.max

    @property
    def mean(self) -> float:
        return self.total / self.n if self.n else 0.0

    def snapshot(self) -> dict:
        return {
            "n": self.n,
            "mean": self.mean,
            "max": self.max,
            # the exact recorded sum (not a bucket estimate): the whole
            # bill a rate-style reader wants — e.g. xla.compile_s total is
            # the process's compile-time spend, the bench/cost stamp field
            "total": self.total,
            "p50": self.percentile(0.50),
            "p95": self.percentile(0.95),
            "p99": self.percentile(0.99),
        }


class MetricsRegistry:
    """Named metrics, get-or-create, one `snapshot()` dict.

    Creation is idempotent per (name, type): asking for an existing name
    returns the live instance, so producer and consumer never need to agree
    on wiring order; asking for it as a DIFFERENT type raises (a counter
    silently shadowing a gauge would corrupt both readings). Thread-safe
    creation — recording on the returned objects is plain attribute math,
    same as the pre-registry counters."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def _get(self, table: dict, name: str, make):
        others = [t for t in (self._counters, self._gauges, self._histograms)
                  if t is not table]
        with self._lock:
            if name not in table:
                if any(name in t for t in others):
                    raise ValueError(f"metric {name!r} already registered "
                                     f"as a different type")
                table[name] = make(name)
            return table[name]

    def counter(self, name: str) -> Counter:
        return self._get(self._counters, name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(self._gauges, name, Gauge)

    def histogram(self, name: str, **kw) -> Histogram:
        return self._get(self._histograms, name,
                         lambda n: Histogram(n, **kw))

    def register(self, name: str, metric) -> None:
        """Adopt an externally constructed metric instance (including
        subclasses — the serve LatencyHistogram alias) under `name`.
        Raises on any existing registration: two owners of one name would
        silently split the recorded stream."""
        table = (self._counters if isinstance(metric, Counter) else
                 self._gauges if isinstance(metric, Gauge) else
                 self._histograms if isinstance(metric, Histogram) else None)
        if table is None:
            raise TypeError(f"not a registry metric: {type(metric).__name__}")
        with self._lock:
            if any(name in t for t in (self._counters, self._gauges,
                                       self._histograms)):
                raise ValueError(f"metric {name!r} already registered")
            table[name] = metric

    def snapshot(self) -> dict:
        """The whole process's metric state as one JSON-able dict.

        The table LISTING is taken under the lock and rendered outside it:
        since the live `/metrics` scrape thread (telemetry/prom.py), a
        snapshot can run concurrently with another thread lazily creating
        metrics — a Python-level comprehension over the live dicts would
        die with "dictionary changed size during iteration". Rendering
        outside the lock keeps gauge provider callables (which may touch
        arbitrary code, including metric creation) deadlock-free; the
        per-metric reads are attribute math, worst case one update stale."""
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            histograms = sorted(self._histograms.items())
        return {
            "counters": {n: c.value for n, c in counters},
            "gauges": {n: g.value for n, g in gauges},
            "histograms": {n: h.snapshot() for n, h in histograms},
        }


# The process-wide registry every subsystem shares by default. Tests and
# hermetic benches construct private MetricsRegistry instances instead.
_GLOBAL = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _GLOBAL
