"""Trace export: schema-v1 JSONL -> Chrome trace-event JSON (Perfetto /
`chrome://tracing` loadable), plus the op-level profiler capture hook.

The JSONL trace is line-oriented for tools; humans want a timeline. This
module renders a merged multi-process trace as one Chrome trace-event file:

  * one track (pid) per process index, named in metadata events;
  * live spans (`with trace.span(...)`) become complete `X` events at their
    true start stamps; aggregate spans (`complete_span`: per-epoch
    data_wait / step_compute totals measured elsewhere) land on a separate
    `aggregates` thread so they cannot visually shadow the real timeline;
  * `point` records become instant `i` events — except `dispatch_phase`
    totals (--profile_dispatch runs), which render as slices on paired
    `host dispatch` / `device idle` lanes so the device's idle gaps are
    visible against the host work that causes them;
  * registry `snapshot` records become counter `C` tracks (counters and
    numeric gauges — e.g. `xla.compiles`, `host.rss_bytes` over time);
  * processes are aligned on WALL clock: every record carries t_wall and
    t_mono, so each stream's mono->wall offset is observable from the file
    alone (analysis.clock_offset), and cross-process skew shows up as real
    offset between tracks, not an artifact.

Timestamps are microseconds from the earliest aligned event, the
trace-event format's native unit.

`profiler_trace` is the op-level escalation hatch: it wraps a block in
`jax.profiler.trace` (XPlane protos for TensorBoard/XProf) — the microscope
`cli/train.py --profile DIR` points at one run after `trace report` has
found the slow phase cheaply on every run. Everything else here is pure
stdlib (jax is imported only inside `profiler_trace`), so export runs on
hosts without the framework's backend installed.
"""

from __future__ import annotations

import contextlib
import json
from typing import List, Optional

from .analysis import (DISPATCH_PHASE_POINT, DISPATCH_PHASES,
                       SERVE_BATCH_SPAN, SERVE_BATCH_STAGE_ORDER,
                       SERVE_REQUEST_SPAN, clock_offset, load_traces,
                       split_segments, _span_interval)

# Thread ids within each process track: the real span timeline, the
# per-epoch aggregate durations, and instants/counters ride on spans' tid.
# Serve traces add two more: concurrent request spans (which overlap
# without nesting — they would render as a garbled stack on the spans
# thread) and the batch pipeline, connected by flow arrows so clicking a
# request walks to the batch that carried it. Collective journals
# (--journal runs, telemetry/cluster.py) add a per-rank collectives
# track, with seq-aligned flow arrows binding the SAME collective across
# ranks — straggler skew renders as visible arrow slant.
_TID_SPANS = 0
_TID_AGGREGATES = 1
_TID_REQUESTS = 2
_TID_BATCHES = 3
_TID_COLLECTIVES = 4
# Dispatch forensics (--profile_dispatch runs): the per-epoch
# dispatch_phase points render as slices on a HOST lane (python_prestep /
# dispatch / sync_wait) and a DEVICE lane (device_idle) so the idle gaps
# are visible as slices against the host work that causes them.
_TID_HOST_LANE = 5
_TID_DEVICE_LANE = 6
_SERVE_BATCH_TRACK = (SERVE_BATCH_SPAN,) + SERVE_BATCH_STAGE_ORDER
# seq-aligned cross-rank arrows are capped (a long run journals thousands
# of collectives; Perfetto renders arrows per flow id, and the first few
# hundred seqs carry the alignment story) — the cap is stamped into
# otherData so a truncated arrow set never reads as complete
COLLECTIVE_ARROW_CAP = 512
# The performance-ledger track (`trace export --ledger DIR`): one counter
# per series, on its own pid so the repo's MULTI-RUN history renders as a
# scrubbable timeline besides (not inside) any single run's trace. Run
# ordinals are not wall stamps — each run renders one second apart.
LEDGER_PID = 999
LEDGER_RUN_SPACING_S = 1.0


def _scale_us(seconds: float) -> float:
    return round(seconds * 1e6, 3)


def _journal_slices(journal_paths: List[str]) -> List[tuple]:
    """Per-rank collective journal records as (start_wall_s, rank, rec)
    triples — wall stamps are comparable across ranks directly (each
    record carries t_wall at its enter), so they join the events' aligned
    timeline without an offset computation. Open entries (enter, no exit)
    render as zero-duration slices marked open=True — a stuck collective
    is visible as the track's abrupt end."""
    from .cluster import load_journal
    out = []
    for path in journal_paths:
        j = load_journal(path)
        rank = j["rank"]
        for rec in j["records"]:
            # t_wall is the window's ENTER wall stamp (the writer's
            # contract), directly comparable across ranks
            t_wall = rec.get("t_wall")
            if not isinstance(t_wall, (int, float)):
                continue
            out.append((float(t_wall), rank, rec))
        for e in j["open"]:
            t_wall = e.get("t_wall")
            if not isinstance(t_wall, (int, float)):
                continue
            rec = {"seq": e["seq"], "k": e["kind"], "t_wall": t_wall,
                   "t_enter": e.get("t_enter"),
                   "t_exit": e.get("t_enter"), "open": True}
            out.append((float(t_wall), rank, rec))
    return out


def _ledger_events(ledger_series: dict) -> List[dict]:
    """One Perfetto counter track per ledger series (`ledger.histories`
    shape: series key -> run-ordered rows). Successive runs render
    LEDGER_RUN_SPACING_S apart — the x axis is run order, not wall time —
    so scrubbing the ledger pid walks the whole committed history."""
    events: List[dict] = []
    if not ledger_series:
        return events
    events.append({"ph": "M", "name": "process_name", "pid": LEDGER_PID,
                   "tid": _TID_SPANS,
                   "args": {"name": "performance ledger"}})
    events.append({"ph": "M", "name": "thread_name", "pid": LEDGER_PID,
                   "tid": _TID_SPANS, "args": {"name": "ledger series"}})
    for series in sorted(ledger_series):
        for i, row in enumerate(ledger_series[series]):
            events.append({
                "ph": "C", "name": series, "cat": "ledger",
                "ts": _scale_us(i * LEDGER_RUN_SPACING_S),
                "pid": LEDGER_PID, "tid": _TID_SPANS,
                "args": {"value": row["value"]},
            })
    return events


def chrome_trace(paths: List[str],
                 journal_paths: Optional[List[str]] = None,
                 ledger_series: Optional[dict] = None) -> dict:
    """Merge per-process JSONL trace files into one Chrome trace-event
    object: `{"traceEvents": [...], "displayTimeUnit": "ms"}`.
    `journal_paths` (per-rank collective journals from a --journal run)
    add one `collectives` track per rank plus seq-aligned cross-rank flow
    arrows; `ledger_series` (ledger.histories) adds the multi-run
    performance-ledger counter tracks on their own pid."""
    records, _errors = load_traces(paths)
    by_file: dict = {}
    for rec in records:
        by_file.setdefault(rec["_file"], []).append(rec)

    # Pass 1: per-stream wall alignment and the global origin. Offsets are
    # per SEGMENT (the monotonic clock resets across the re-exec that
    # starts an appended segment — a file-wide median would throw one
    # segment's events off by the whole outage gap). A span's visible
    # start is its t0 stamp when live, else emission minus duration (an
    # aggregate's duration accumulated up to its emission point).
    aligned = []  # (start_wall_s, rec)
    for recs in by_file.values():
        for seg in split_segments(recs):
            off = clock_offset(seg)
            for rec in seg:
                kind = rec.get("kind")
                t_mono = rec.get("t_mono")
                has_mono = isinstance(t_mono, (int, float))
                if kind == "span":
                    iv = _span_interval(rec)
                    if iv is not None:
                        start = iv[0] + off
                    else:
                        dur = rec.get("dur_s")
                        if not (isinstance(dur, (int, float)) and has_mono):
                            continue  # torn/foreign record: skip, not crash
                        start = float(t_mono) - float(dur) + off
                elif kind in ("point", "snapshot") and has_mono:
                    start = float(t_mono) + off
                else:  # meta records / stamp-less records: no timeline
                    continue
                aligned.append((start, rec))
    jslices = _journal_slices(journal_paths or [])
    lev = _ledger_events(ledger_series or {})
    if not aligned and not jslices:
        # a ledger-only export is a valid timeline (the committed-artifact
        # history exists independently of any single run's events files)
        out = {"traceEvents": lev, "displayTimeUnit": "ms"}
        if lev:
            out["otherData"] = {
                "source": "pytorch_ddp_mnist_tpu telemetry schema v1",
                "ledger_series": len(ledger_series or {})}
        return out
    t_base = min([start for start, _rec in aligned]
                 + [start for start, _r, _rec in jslices])

    # serve flow arrows (request -> the batch that carried it) need the
    # batch slice's position BEFORE the request slices render: one pass
    # over the aligned records maps batch_id -> (pid, ts).
    batch_pos = {}
    for start, rec in aligned:
        if (rec.get("kind") == "span"
                and rec.get("name") == SERVE_BATCH_SPAN):
            bid = (rec.get("attrs") or {}).get("batch_id")
            if isinstance(bid, str) and bid:
                batch_pos[bid] = (int(rec.get("proc", 0)),
                                  _scale_us(start - t_base))

    events: List[dict] = []
    named_pids = set()
    dispatch_lanes_named = set()  # pids with host/device lane names out
    flow_seq = 0
    for start, rec in sorted(aligned, key=lambda it: it[0]):
        pid = int(rec.get("proc", 0))
        if pid not in named_pids:
            named_pids.add(pid)
            events.append({"ph": "M", "name": "process_name", "pid": pid,
                           "tid": _TID_SPANS,
                           "args": {"name": f"process {pid}"}})
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": _TID_SPANS, "args": {"name": "spans"}})
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": _TID_AGGREGATES,
                           "args": {"name": "aggregates"}})
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": _TID_REQUESTS,
                           "args": {"name": "serve requests"}})
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": _TID_BATCHES,
                           "args": {"name": "serve batches"}})
        ts = _scale_us(start - t_base)
        kind = rec.get("kind")
        if kind == "span":
            live = _span_interval(rec) is not None
            attrs = {k: v for k, v in (rec.get("attrs") or {}).items()
                     if k not in ("t0_mono", "t0_wall")}
            name = rec.get("name", "span")
            if name == SERVE_REQUEST_SPAN:
                tid = _TID_REQUESTS
            elif name in _SERVE_BATCH_TRACK:
                tid = _TID_BATCHES
            else:
                tid = _TID_SPANS if live else _TID_AGGREGATES
            events.append({
                "ph": "X", "name": name,
                "cat": "span" if live else "aggregate",
                "ts": ts, "dur": _scale_us(float(rec["dur_s"])),
                "pid": pid,
                "tid": tid,
                "args": attrs,
            })
            link = attrs.get("batch")
            if (name == SERVE_REQUEST_SPAN and isinstance(link, str)
                    and link in batch_pos):
                # one flow arrow per request: starts inside the request
                # slice, lands at the batch slice's start — Perfetto
                # renders the N-requests-into-one-batch coalescing
                bpid, bts = batch_pos[link]
                flow_seq += 1
                flow = {"cat": "serve_flow", "name": "batch",
                        "id": flow_seq}
                events.append({"ph": "s", "ts": ts, "pid": pid,
                               "tid": _TID_REQUESTS, **flow})
                events.append({"ph": "f", "bp": "e", "ts": bts,
                               "pid": bpid, "tid": _TID_BATCHES, **flow})
        elif kind == "point":
            name = rec.get("name", "point")
            if name == "mem_watermark":
                # HBM/RSS watermark samples (telemetry/runtime.py
                # record_memory_point, emitted per epoch by the train
                # loop): render each numeric field as its own counter
                # track beside the registry counters, so Perfetto shows
                # the memory envelope under the epoch spans instead of an
                # instant blip
                for metric, value in sorted(
                        (rec.get("attrs") or {}).items()):
                    if isinstance(value, (int, float)) \
                            and not isinstance(value, bool):
                        events.append({"ph": "C", "name": metric,
                                       "cat": "mem", "ts": ts, "pid": pid,
                                       "tid": _TID_SPANS,
                                       "args": {"value": value}})
                continue
            if name == DISPATCH_PHASE_POINT:
                # per-epoch phase totals (telemetry/dispatch.py flush):
                # render as slices ending at their emission point (the
                # aggregates idiom) — host phases on the host lane, the
                # sampled device_idle total on its own device lane, so
                # Perfetto shows the idle gap AGAINST the host work that
                # causes it
                attrs = rec.get("attrs") or {}
                phase, total = attrs.get("phase"), attrs.get("total_s")
                if phase in DISPATCH_PHASES \
                        and isinstance(total, (int, float)):
                    if pid not in dispatch_lanes_named:
                        dispatch_lanes_named.add(pid)
                        events.append({"ph": "M", "name": "thread_name",
                                       "pid": pid, "tid": _TID_HOST_LANE,
                                       "args": {"name": "host dispatch"}})
                        events.append({"ph": "M", "name": "thread_name",
                                       "pid": pid, "tid": _TID_DEVICE_LANE,
                                       "args": {"name": "device idle"}})
                    tid = (_TID_DEVICE_LANE if phase == "device_idle"
                           else _TID_HOST_LANE)
                    events.append({
                        "ph": "X", "name": str(phase), "cat": "dispatch",
                        "ts": _scale_us(start - float(total) - t_base),
                        "dur": _scale_us(float(total)),
                        "pid": pid, "tid": tid, "args": attrs,
                    })
                    continue
            events.append({"ph": "i", "name": name,
                           "cat": "point", "ts": ts, "pid": pid,
                           "tid": _TID_SPANS, "s": "t",
                           "args": rec.get("attrs") or {}})
        elif kind == "snapshot":
            snap = rec.get("attrs") or {}
            for table in ("counters", "gauges"):
                for metric, value in sorted((snap.get(table) or {}).items()):
                    if isinstance(value, (int, float)):
                        events.append({"ph": "C", "name": metric,
                                       "cat": "registry", "ts": ts,
                                       "pid": pid, "tid": _TID_SPANS,
                                       "args": {"value": value}})
    # -- per-rank collective tracks + seq-aligned cross-rank arrows ------
    arrows_capped = False
    if jslices:
        by_seq: dict = {}   # seq -> [(rank, ts_us)]
        for start, rank, rec in sorted(jslices, key=lambda it: it[0]):
            pid = int(rank)
            if pid not in named_pids:
                named_pids.add(pid)
                events.append({"ph": "M", "name": "process_name",
                               "pid": pid, "tid": _TID_SPANS,
                               "args": {"name": f"process {pid}"}})
            ts = _scale_us(start - t_base)
            t_enter, t_exit = rec.get("t_enter"), rec.get("t_exit")
            dur = (max(float(t_exit) - float(t_enter), 0.0)
                   if isinstance(t_enter, (int, float))
                   and isinstance(t_exit, (int, float)) else 0.0)
            seq = rec.get("seq")
            args = {"seq": seq, "bytes": rec.get("bytes"),
                    "bucket": rec.get("bucket"), "step": rec.get("step")}
            if rec.get("open"):
                args["open"] = True
            events.append({
                "ph": "X", "name": str(rec.get("k", "coll")),
                "cat": "collective", "ts": ts, "dur": _scale_us(dur),
                "pid": pid, "tid": _TID_COLLECTIVES,
                "args": {k: v for k, v in args.items() if v is not None},
            })
            if isinstance(seq, int):
                by_seq.setdefault(seq, []).append((pid, ts))
        for pid in sorted({int(r) for _s, r, _rec in jslices}):
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": _TID_COLLECTIVES,
                           "args": {"name": "collectives"}})
        # one flow per seq present on >= 2 ranks: the arrow binds the SAME
        # collective across ranks, so straggler skew renders as slant
        arrow_seqs = sorted(s for s, where in by_seq.items()
                            if len(where) >= 2)
        arrows_capped = len(arrow_seqs) > COLLECTIVE_ARROW_CAP
        for seq in arrow_seqs[:COLLECTIVE_ARROW_CAP]:
            where = sorted(by_seq[seq])
            flow_seq += 1
            flow = {"cat": "collective_flow", "name": f"seq {seq}",
                    "id": flow_seq}
            pid0, ts0 = where[0]
            events.append({"ph": "s", "ts": ts0, "pid": pid0,
                           "tid": _TID_COLLECTIVES, **flow})
            for pid_n, ts_n in where[1:]:
                events.append({"ph": "f", "bp": "e", "ts": ts_n,
                               "pid": pid_n, "tid": _TID_COLLECTIVES,
                               **flow})
    events.extend(lev)
    other = {"source": "pytorch_ddp_mnist_tpu telemetry schema v1",
             "files": sorted(by_file)}
    if journal_paths:
        other["journals"] = sorted(journal_paths)
        if arrows_capped:
            other["collective_arrow_cap"] = COLLECTIVE_ARROW_CAP
    if ledger_series:
        other["ledger_series"] = len(ledger_series)
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": other}


def write_chrome_trace(paths: List[str], out_path: str,
                       journal_paths: Optional[List[str]] = None,
                       ledger_series: Optional[dict] = None) -> int:
    """Render `paths` (+ optional per-rank collective journals + optional
    performance-ledger histories) and write the trace-event JSON to
    `out_path`; returns the event count."""
    trace = chrome_trace(paths, journal_paths=journal_paths,
                         ledger_series=ledger_series)
    with open(out_path, "w") as f:
        json.dump(trace, f)
        f.write("\n")
    return len(trace["traceEvents"])


@contextlib.contextmanager
def profiler_trace(logdir: Optional[str]):
    """Op-level capture: wrap a block in `jax.profiler.trace(logdir)`
    (no-op when logdir is falsy). Delegates to `utils.profiling.trace` —
    re-exported here so the telemetry package is the one front door from
    phase stats down to XPlane protos; `cli/train.py --profile` enters
    through this name."""
    from ..utils.profiling import trace
    with trace(logdir):
        yield
