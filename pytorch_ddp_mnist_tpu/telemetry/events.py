"""Structured JSONL event trace: schema-versioned records, nestable spans.

One line per record, append-only, line-buffered — a trace survives a crash
up to its last completed record, and any line-oriented tool (jq, the
`scripts/check_telemetry.py` validator) consumes it without a reader
library. Every record carries:

    v        schema version (SCHEMA_VERSION; checker rejects unknown)
    kind     "meta" | "span" | "point" | "snapshot"
    name     what the record describes ("epoch", "data_wait", "registry")
    t_wall   wall-clock seconds (time.time — correlate across hosts/logs)
    t_mono   monotonic seconds (time.perf_counter — order/duration truth;
             non-decreasing within one run segment, checked). Files open
             in APPEND mode so an outage-resume re-exec or a repeat run
             adds a new segment (fresh `trace_start`, fresh ids/clock)
             rather than losing the earlier trace.
    proc     jax process index (telemetry.runtime.process_index_cached)

Span records additionally carry `span` (id), `parent` (enclosing span's id
or null) and `dur_s`; `attrs` holds free-form per-record payload. A span is
ONE record emitted at exit (not a begin/end pair): the trace cannot hold a
dangling begin, and ordering validation stays a single pass.

Spans are async-dispatch aware exactly like `utils.profiling.Timer`: on
device work a naive wall pair measures only enqueue time, so
`span.sync(tree)` registers a pytree to `jax.block_until_ready` at exit —
strictly OPT-IN, so an instrumented loop that never calls sync adds zero
host syncs (the acceptance invariant tests pin). Aggregate child spans
(`complete_span`) publish durations measured elsewhere (e.g. a
CumulativeTimer total) under the currently open span without re-timing.

The process-wide tracer is a no-op `NullTracer` until `telemetry.enable()`
swaps in a real `EventTrace` — call sites never branch on "is telemetry
on".
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Optional

SCHEMA_VERSION = 1
KINDS = ("meta", "span", "point", "snapshot")


class _Span:
    """Context manager for one live span; emitted as a single record at
    exit. `sync(tree)` opts into blocking on `tree` first (returns the tree
    unchanged, the Timer.sync idiom)."""

    def __init__(self, trace: "EventTrace", name: str, attrs: dict):
        self._trace = trace
        self.name = name
        self.attrs = attrs
        self.span_id: Optional[int] = None
        self.parent_id: Optional[int] = None
        self._sync_tree: Any = None

    def sync(self, tree: Any) -> Any:
        self._sync_tree = tree
        return tree

    def __enter__(self) -> "_Span":
        self.span_id = self._trace._next_id()
        self.parent_id = self._trace._current_span_id()
        self._trace._stack.append(self.span_id)
        self._t0_mono = time.perf_counter()
        self._t0_wall = time.time()
        return self

    def __exit__(self, *exc) -> None:
        try:
            if self._sync_tree is not None:
                import jax
                jax.block_until_ready(self._sync_tree)
        finally:
            # pop + emit even when the drain raises (device failure): an
            # unpopped id would corrupt every later span's parent, and the
            # recorded (enqueue-side) duration of the failed span is still
            # evidence
            self._finish()

    def _finish(self) -> None:
        dur = time.perf_counter() - self._t0_mono
        self._trace._stack.pop()
        attrs = dict(self.attrs)
        # span START stamps travel in attrs; the record's own t_mono/t_wall
        # are EMISSION time like every other record, keeping the whole file
        # non-decreasing in t_mono (a parent span's record is written after
        # its children even though it started first)
        attrs["t0_mono"] = self._t0_mono
        attrs["t0_wall"] = self._t0_wall
        self._trace._emit("span", self.name, span_id=self.span_id,
                          parent_id=self.parent_id, dur_s=dur,
                          attrs=attrs)


class EventTrace:
    """JSONL trace writer bound to one file. Not thread-safe by design —
    one trace per process (the module-level tracer), written from the train
    or serve loop's thread, exactly like the print-based epoch line."""

    enabled = True

    def __init__(self, path: str, *, process_index: Optional[int] = None):
        self.path = str(path)
        if process_index is None:
            from .runtime import process_index_cached
            process_index = process_index_cached()
        self.process_index = int(process_index)
        self._f = open(self.path, "a", buffering=1)  # line-buffered
        self._ids = 0
        self._stack: "list[int]" = []
        self._emit("meta", "trace_start",
                   attrs={"schema": SCHEMA_VERSION, "pid": os.getpid()})

    # -- record plumbing ---------------------------------------------------

    def _next_id(self) -> int:
        self._ids += 1
        return self._ids

    def _current_span_id(self) -> Optional[int]:
        return self._stack[-1] if self._stack else None

    def _emit(self, kind: str, name: str, *, span_id=None, parent_id=None,
              dur_s=None, attrs=None) -> None:
        if kind not in KINDS:  # writer-side guard, mirrored by the checker
            raise ValueError(f"unknown record kind {kind!r}")
        rec = {
            "v": SCHEMA_VERSION,
            "kind": kind,
            "name": name,
            "t_wall": time.time(),
            "t_mono": time.perf_counter(),
            "proc": self.process_index,
        }
        if span_id is not None:
            rec["span"] = span_id
            rec["parent"] = parent_id
            rec["dur_s"] = round(float(dur_s), 9)
        if attrs:
            rec["attrs"] = attrs
        if self._f.closed:
            return
        self._f.write(json.dumps(rec) + "\n")

    # -- public surface ----------------------------------------------------

    def span(self, name: str, **attrs) -> _Span:
        """Open a nestable span: `with trace.span("epoch", epoch=3) as s:`.
        Emits one record at exit; `s.sync(tree)` opts into a device drain
        first."""
        return _Span(self, name, attrs)

    def complete_span(self, name: str, dur_s: float, **attrs) -> None:
        """Emit an already-measured span (e.g. a CumulativeTimer total)
        under the currently open span — the per-phase aggregate pattern:
        data-wait/step-compute totals are accumulated per step but emitted
        once per epoch, so the trace grows per epoch, not per step."""
        self._emit("span", name, span_id=self._next_id(),
                   parent_id=self._current_span_id(), dur_s=dur_s,
                   attrs=attrs or None)

    def emit_span(self, name: str, *, t0_mono: float, t0_wall: float,
                  dur_s: float, parent: Optional[int] = None,
                  attrs: Optional[dict] = None) -> int:
        """Emit a LIVE span (real [t0, t0+dur] interval) with EXPLICIT
        parentage, outside the context-manager stack — the serve-path
        contract: concurrent requests overlap without nesting, so the
        stack's strict-containment invariant cannot hold for them; each
        caller-threaded context stamps its own interval and names its own
        parent (or none). Returns the allocated span id so a caller can
        parent further spans under it (the per-batch stage children).
        Stamps must be in this process's perf_counter/time.time frames —
        the structure validator checks t0_mono + dur_s against the
        record's own emission stamp."""
        sid = self._next_id()
        a = dict(attrs) if attrs else {}
        a["t0_mono"] = float(t0_mono)
        a["t0_wall"] = float(t0_wall)
        self._emit("span", name, span_id=sid, parent_id=parent,
                   dur_s=dur_s, attrs=a)
        return sid

    def point(self, name: str, **attrs) -> None:
        """One instantaneous event record."""
        self._emit("point", name, attrs=attrs or None)

    def snapshot(self, registry) -> None:
        """Stamp a full registry snapshot into the trace — the record a
        completed `--telemetry` train run closes with (a crashed run's
        trace legitimately lacks it; the checker validates schema, not
        run completeness)."""
        self._emit("snapshot", "registry", attrs=registry.snapshot())

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()


class NullTracer:
    """The disabled-telemetry tracer: every call is a no-op, and span()
    returns a no-op context manager whose sync() forwards its tree
    untouched — so instrumented call sites cost nothing and never force a
    host sync when telemetry is off."""

    class _NullSpan:
        name = None
        span_id = parent_id = None

        def sync(self, tree):
            return tree

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return None

    _SPAN = _NullSpan()
    # call sites that must not even BUILD their attrs payload when
    # telemetry is off (the serve request path) branch on this instead of
    # an isinstance check
    enabled = False

    def span(self, name: str, **attrs) -> "_NullSpan":
        return self._SPAN

    def complete_span(self, name: str, dur_s: float, **attrs) -> None:
        pass

    def emit_span(self, name: str, *, t0_mono: float, t0_wall: float,
                  dur_s: float, parent: Optional[int] = None,
                  attrs: Optional[dict] = None) -> int:
        return 0

    def point(self, name: str, **attrs) -> None:
        pass

    def snapshot(self, registry) -> None:
        pass

    def close(self) -> None:
        pass


_NULL = NullTracer()
_tracer = _NULL
# enable()/disable() are close-then-swap sequences on the process-wide
# tracer; the serve event loop's worker threads and the Prometheus scrape
# thread call get_tracer() concurrently with a CLI toggling telemetry, so
# the swap must be atomic (statics rule MUT002 — the PR 6 registry race,
# closed rather than baselined). get_tracer() itself stays lock-free: it
# reads one reference, and a reader racing a swap gets either tracer,
# both valid.
_TRACER_LOCK = threading.Lock()


def get_tracer():
    """The process-wide tracer: a real EventTrace after `enable()`, the
    shared NullTracer otherwise."""
    return _tracer


def enable(out_dir: str, *, process_index: Optional[int] = None) -> EventTrace:
    """Switch the process-wide tracer to a real JSONL trace under
    `out_dir` (created if needed). Process 0 writes `events.jsonl`; other
    ranks write `events.rank{N}.jsonl` beside it — multi-host ranks cannot
    share a file, and the checker validates every `events*.jsonl` in the
    directory."""
    global _tracer
    if process_index is None:
        from .runtime import process_index_cached
        process_index = process_index_cached()
    os.makedirs(out_dir, exist_ok=True)
    name = ("events.jsonl" if process_index == 0
            else f"events.rank{process_index}.jsonl")
    with _TRACER_LOCK:
        if isinstance(_tracer, EventTrace):
            _tracer.close()
        _tracer = EventTrace(os.path.join(out_dir, name),
                             process_index=process_index)
        return _tracer


def disable() -> None:
    """Close any active trace and restore the no-op tracer."""
    global _tracer
    with _TRACER_LOCK:
        if isinstance(_tracer, EventTrace):
            _tracer.close()
        _tracer = _NULL
