"""Dispatch forensics: the per-step host-timeline profiler that decomposes
PR 12's overhead O = T - bound into NAMED phases.

The roofline attribution (telemetry/costs.py, MULTICHIP_r07) proved the
arXiv:1810.11112 finding at this scale: 38-69% of every DDP strategy's
step is neither compute nor comm — it is the host. But "overhead" is not
actionable until it has names. This module splits the step boundary the
way analysis.py's stage report split serve e2e latency:

  * ``python_prestep`` — loop bookkeeping between the previous jitted
    call returning and the next one being entered (batch fetch handoff,
    journal stamps, python glue);
  * ``dispatch``      — inside the jitted call until it returns the
    async arrays (argument flattening, executable lookup, enqueue);
  * ``device_idle``   — the DEVICE's view of the same boundary: how long
    the queue sits empty between consecutive executions. Probing this
    needs a drain, so it is sampled 1-in-K (``sample_every``) via a
    ``jax.block_until_ready`` bracket on the PREVIOUS step's outputs —
    steady-state steps stay sync-free, and the bracket is re-stamped so
    the drain itself pollutes neither ``python_prestep`` nor
    ``dispatch``;
  * ``sync_wait``     — the per-epoch loss/health fetch (the one
    deliberate sync the loop already performs).

Write side: per-step samples land in ``dispatch.<phase>`` registry
histograms plus the flight ring (constant memory, nothing on disk on the
happy path); per-epoch totals flush as two trace ``point`` kinds —
``dispatch_phase`` (one per phase) and ``dispatch_window`` (window vs
attributed seconds, the coverage numerator/denominator). The read side
(`trace report --overhead`, analysis.overhead_report) asserts the named
phases explain >= analysis.OVERHEAD_COVERAGE_MIN of the window.

The default is ``NullProfiler``: every hook a no-op, zero host syncs,
pinned bitwise-identical by tests/test_telemetry.py — instrumented call
sites never branch, exactly the NullTracer/NullJournal contract.

``measure_dispatch_phases`` is the bench-side probe: given a closure
that runs ONE streaming step and returns its async outputs, it measures
the same decomposition synchronously (block every step) so
``bench.py --mode ddp`` can stamp per-strategy phase attributions into
MULTICHIP artifacts without a live profiler.

Imports jax lazily (only on the sampled drain path): the module stays
importable on jax-less hosts, like the rest of telemetry/.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional

from . import flight
from .analysis import (DISPATCH_COVERAGE_PHASES, DISPATCH_PHASE_POINT,
                       DISPATCH_PHASES, DISPATCH_WINDOW_POINT)
from .events import get_tracer
from .registry import get_registry

# sampled-drain default: probe the device-idle gap on 1-in-16 steps
DEFAULT_SAMPLE_EVERY = 16


class NullProfiler:
    """The zero-overhead default: every hook a no-op. Call sites in
    train/loop.py and train/scan.py hold one of these unless
    ``--profile_dispatch`` armed a real DispatchProfiler, so the
    profiler-off path performs zero host syncs and stays bitwise
    identical (pinned by tests)."""

    armed = False

    def mark_prestep(self) -> None:
        pass

    def begin_dispatch(self, sync_tree: Any = None) -> None:
        pass

    def end_dispatch(self, step: int) -> None:
        pass

    def note_sync_wait(self, seconds: float) -> None:
        pass

    def flush_epoch(self, epoch: int, *, steps: int,
                    step_total_s: Optional[float] = None) -> None:
        pass


class DispatchProfiler(NullProfiler):
    """Per-step host-timeline profiler. Hook protocol (the loop calls, in
    step order)::

        prof.mark_prestep()              # top of the loop body
        prof.begin_dispatch(prev_out)    # just before the jitted call
        out = step(...)                  # the async dispatch
        prof.end_dispatch(step_idx)      # just after it returns
        ...
        prof.note_sync_wait(fetch_s)     # the per-epoch loss fetch
        prof.flush_epoch(epoch, steps=n, step_total_s=loop_timer_total)

    ``begin_dispatch``'s ``sync_tree`` is the previous step's OUTPUT tree
    (a live array — donated inputs are dead buffers); on a sampled
    1-in-K step it is drained so the device-idle bracket starts from an
    empty queue. ``step_total_s`` at flush lets the loop hand over its
    own step-timer total as the window denominator, so coverage checks
    the profiler against an independent clock instead of against itself.
    """

    armed = True

    def __init__(self, registry=None, tracer=None,
                 sample_every: int = DEFAULT_SAMPLE_EVERY):
        self._registry = registry
        self._tracer = tracer
        self.sample_every = max(0, int(sample_every))
        self._hists: Dict[str, Any] = {}
        self._t_pre: Optional[float] = None
        self._t_d0: Optional[float] = None
        self._t_idle0: Optional[float] = None
        self._n_steps = 0          # lifetime step counter (sampling phase)
        self._reset_epoch()

    # -- plumbing ----------------------------------------------------------

    def _reset_epoch(self) -> None:
        self._totals = {phase: 0.0 for phase in DISPATCH_PHASES}
        self._counts = {phase: 0 for phase in DISPATCH_PHASES}

    def _record(self, phase: str, seconds: float) -> None:
        seconds = max(0.0, seconds)
        self._totals[phase] += seconds
        self._counts[phase] += 1
        hist = self._hists.get(phase)
        if hist is None:
            reg = self._registry if self._registry is not None \
                else get_registry()
            hist = reg.histogram(f"dispatch.{phase}")
            self._hists[phase] = hist
        hist.record(seconds)

    # -- the hooks ---------------------------------------------------------

    def mark_prestep(self) -> None:
        self._t_pre = time.perf_counter()

    def begin_dispatch(self, sync_tree: Any = None) -> None:
        now = time.perf_counter()
        if self._t_pre is not None:
            self._record("python_prestep", now - self._t_pre)
            self._t_pre = None
        self._t_idle0 = None
        if (self.sample_every > 0 and sync_tree is not None
                and self._n_steps % self.sample_every == 0):
            import jax
            # drain the queue THROUGH the jax module attribute so
            # sanitize.no_host_sync counts the probe honestly
            jax.block_until_ready(sync_tree)
            self._t_idle0 = time.perf_counter()
        # (re-)stamp dispatch-begin AFTER any drain: the bracket must
        # pollute neither python_prestep nor dispatch
        self._t_d0 = time.perf_counter()

    def end_dispatch(self, step: int) -> None:
        now = time.perf_counter()
        dispatch_s = idle_s = None
        if self._t_d0 is not None:
            dispatch_s = now - self._t_d0
            self._record("dispatch", dispatch_s)
            self._t_d0 = None
        if self._t_idle0 is not None:
            # queue-empty -> enqueue-complete: the device's view of the
            # host boundary (a lower bound on the true idle gap — the
            # device may have drained before the bracket even started)
            idle_s = now - self._t_idle0
            self._record("device_idle", idle_s)
            self._t_idle0 = None
        self._n_steps += 1
        fields = {"step": int(step)}
        if dispatch_s is not None:
            fields["dispatch_s"] = round(dispatch_s, 9)
        if idle_s is not None:
            fields["idle_s"] = round(idle_s, 9)
        flight.record("dispatch", **fields)

    def note_sync_wait(self, seconds: float) -> None:
        self._record("sync_wait", float(seconds))

    def flush_epoch(self, epoch: int, *, steps: int,
                    step_total_s: Optional[float] = None) -> None:
        tracer = self._tracer if self._tracer is not None else get_tracer()
        for phase in DISPATCH_PHASES:
            if self._counts[phase] == 0:
                continue
            tracer.point(DISPATCH_PHASE_POINT, phase=phase,
                         total_s=round(self._totals[phase], 9),
                         n=self._counts[phase], epoch=int(epoch),
                         step=self._n_steps)
        attributed = sum(self._totals[p] for p in DISPATCH_COVERAGE_PHASES)
        # the window: the loop's own step-timer total when offered (an
        # independent clock), else the profiler's dispatch total
        in_call = step_total_s if step_total_s is not None \
            else self._totals["dispatch"]
        window = (self._totals["python_prestep"] + max(0.0, in_call)
                  + self._totals["sync_wait"])
        tracer.point(DISPATCH_WINDOW_POINT, window_s=round(window, 9),
                     attributed_s=round(attributed, 9),
                     coverage=round(attributed / window, 6)
                     if window > 0 else 1.0,
                     epoch=int(epoch), steps=int(steps))
        self._reset_epoch()


def measure_dispatch_phases(step_once: Callable[[], Any], *,
                            steps: int = 8) -> Dict[str, float]:
    """Bench-side probe: run ``step_once`` (one streaming training step
    returning its async output tree) ``steps`` times, blocking every
    iteration, and return the MEAN per-step phase decomposition::

        {"python_prestep": s, "dispatch": s, "sync_wait": s,
         "device_idle": s, "probe_step_s": s, "steps": n}

    ``python_prestep`` is the inter-call gap (previous block returning ->
    next call entered), ``dispatch`` the call itself, ``sync_wait`` the
    drain, ``device_idle`` the drain-to-enqueue-complete interval (the
    device-side view of prestep+dispatch). ``probe_step_s`` is the full
    per-step wall so shares sum to 1 by construction. One warmup
    iteration runs first (compile + cache fill, excluded)."""
    import jax
    steps = max(1, int(steps))
    jax.block_until_ready(step_once())    # warmup, excluded
    totals = {phase: 0.0 for phase in DISPATCH_PHASES}
    t_begin = prev_end = time.perf_counter()
    for _ in range(steps):
        t0 = time.perf_counter()
        out = step_once()
        t1 = time.perf_counter()
        jax.block_until_ready(out)
        t2 = time.perf_counter()
        totals["python_prestep"] += t0 - prev_end
        totals["dispatch"] += t1 - t0
        totals["sync_wait"] += t2 - t1
        totals["device_idle"] += t1 - prev_end
        prev_end = t2
    wall = prev_end - t_begin
    out = {phase: totals[phase] / steps for phase in DISPATCH_PHASES}
    out["probe_step_s"] = wall / steps
    out["steps"] = steps
    return out
