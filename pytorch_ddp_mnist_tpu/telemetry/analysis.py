"""Trace analysis: span-tree reconstruction, per-phase statistics, and the
step-time regression gate over schema-v1 JSONL traces (telemetry/events.py).

PR 2 made every train epoch and serve request emit spans; this module is the
read side that turns those write-only files into a signal:

  * `load_trace` / `load_traces` — parse one or many `events*.jsonl` files
    (one per process index: a real N-process run writes `events.jsonl` +
    `events.rank{N}.jsonl` siblings).
  * `split_segments` / `span_structure_errors` — the span-tree
    reconstructor, shared with `scripts/check_telemetry.py`: one segment
    per `trace_start` record, spans resolved by id, with structural
    validation (orphaned parents, duplicate ids, enter/exit stamp
    consistency, child intervals crossing their parent's).
  * `analyze` — the machine-readable report: per-phase step-time
    statistics (data_wait / step_compute / eval / fused_run: p50/p95/max),
    per-epoch trend, and straggler skew across processes, aligned on wall
    clock (each record carries both t_wall and t_mono, so the per-process
    offset is observable from the file alone).
  * `compare` — diff two reports' phase statistics; `cli/trace.py` turns a
    past-threshold ratio into a nonzero exit, giving bench.py and CI a
    step-time regression gate.

Pure stdlib, no jax import, by the same contract as the checker: analysis
must run wherever the trace lands, including hosts without the framework
installed (the checker file-loads this module to stay framework-free).
"""

from __future__ import annotations

import glob
import heapq
import json
import math
import os
from typing import Dict, List, Optional, Tuple

# Span names carrying the per-phase step-time story (train/loop.py,
# train/scan.py emit exactly these; serve spans would join by name).
PHASES = ("data_wait", "step_compute", "eval", "fused_run")
# Containment tolerance: both stamps come from the same perf_counter, but a
# parent's duration is computed a few instructions after its child's, so
# exact float equality is not guaranteed at the boundary.
_EPS = 1e-6

# -- the serve-side request/batch span contract (serve/tracing.py emits
# these; kept as LITERALS here so this module stays framework-free for the
# file-loading checker — tests pin the two catalogs against each other) --
SERVE_REQUEST_SPAN = "serve.request"
SERVE_BATCH_SPAN = "serve.batch"
SERVE_STAGES = ("admission", "queue", "batch_form", "pad_h2d", "compute",
                "reply")
SERVE_COALESCE_REASONS = ("size", "deadline", "drain", "manual")
# batch stage children in pipeline order: their start stamps must be
# monotone in this order within one batch (a violation means the stamps
# were reordered or two batches' ids collided)
SERVE_BATCH_STAGE_ORDER = ("serve.batch_form", "serve.pad_h2d",
                           "serve.compute")
# How many slowest-request exemplar trees a serve report carries.
SERVE_EXEMPLAR_K = 8

# -- the program-cost record contract (telemetry/costs.py emits these as
# `point` events; literals here so the file-loading checker stays
# framework-free — tests pin them against costs.py's catalog) --
COST_POINT = "program_cost"
# numeric cost fields: when present they must be non-negative numbers (a
# negative flop/byte count is a harvester bug masquerading as data)
COST_NUMERIC_FIELDS = (
    "flops", "transcendentals", "bytes_accessed", "argument_bytes",
    "output_bytes", "temp_bytes", "generated_code_bytes", "alias_bytes",
    "peak_bytes", "analytic_flops", "wire_bytes", "compile_s")

# -- the dispatch-forensics record contract (telemetry/dispatch.py emits
# these as `point` events at each epoch flush; literals here so the
# file-loading checker stays framework-free — tests pin them against
# dispatch.py's catalog). The phase catalog is the step-boundary
# decomposition of PR 12's overhead O (docs/OBSERVABILITY.md §Dispatch
# forensics): python_prestep (loop bookkeeping before the jitted call),
# dispatch (inside the jitted call until the async arrays return),
# device_idle (the DEVICE's view of the gap between consecutive
# executions, probed on sampled steps), sync_wait (the per-epoch
# loss/health fetch). --
DISPATCH_PHASE_POINT = "dispatch_phase"
DISPATCH_WINDOW_POINT = "dispatch_window"

# -- the fleet/reload record contract (serve/fleet.py + serve/reload.py
# emit these as `point` events at every replica state transition and
# reload verdict; literals here so the file-loading checker stays
# framework-free — tests pin them against the emitters). `swapped`
# events carry the hot-reload invariant itself: `outstanding_at_swap`
# must be 0 (a request that spanned a swap would have run half on the
# old weights, half on the new). --
FLEET_EVENT_POINT = "fleet_event"
RELOAD_EVENT_POINT = "reload_event"
FLEET_EVENTS = ("retry", "retry_exhausted", "quarantine", "dead",
                "restart")
RELOAD_EVENTS = ("swapped", "reloaded", "refused")
QUARANTINE_CAUSES = ("wedge", "crash")
DISPATCH_PHASES = ("python_prestep", "dispatch", "device_idle", "sync_wait")
# device_idle observes the SAME wall interval python_prestep + dispatch
# occupy on the host (queue empty until the next enqueue completes), so
# coverage counts each host interval exactly once.
DISPATCH_COVERAGE_PHASES = ("python_prestep", "dispatch", "sync_wait")
OVERHEAD_REPORT_TAG = "dispatch_overhead_report"
# the acceptance floor: measured phases must explain at least this share
# of the window (trace runs) / of the roofline's O (bench artifacts)
OVERHEAD_COVERAGE_MIN = 0.90
# share-ratio gate exemption: below this absolute phase total the
# numerator is scheduler noise (the data/serve sub-ms convention)
OVERHEAD_SUBMS_EXEMPT_S = 1e-3

# -- the performance-ledger record contract (telemetry/ledger.py emits
# `ledger_row` points when the ledger CLI runs with --telemetry; literals
# here so the file-loading checker stays framework-free — tests pin them
# against ledger.py's catalog). Every ledger row is direction-aware: the
# trend gate must know whether bigger is better before it can call a move
# a regression. --
LEDGER_ROW_POINT = "ledger_row"
LEDGER_DIRECTIONS = ("higher_better", "lower_better")

# The ONE workload normalizer (docs/OBSERVABILITY.md §Performance ledger):
# strategy rows that predate the --model/--param_scale stamps (the
# MULTICHIP_r06-generation artifacts) are the default 118k mlp at scale 1,
# and a row-less n_devices falls back to the artifact's. Both the PR 7
# efficiency-gate labels (`efficiency_report` below) and the ledger's
# series keys (telemetry/ledger.py) normalize through THIS function, so
# the two can never disagree about which legacy rows are comparable.
WORKLOAD_DEFAULTS = {"model": "mlp", "param_scale": 1}


def normalize_workload(row: dict, artifact: Optional[dict] = None) -> dict:
    """Canonical {model, param_scale, n_devices, per_chip_batch} for one
    strategy/bench row: absent model/param_scale pin to the documented
    defaults (mlp, x1 — un-stamped rows predate models/zoo.py); n_devices
    falls back row -> artifact -> None; per_chip_batch stays None when the
    row predates its stamp (r08 introduced it)."""
    art = artifact or {}
    model = row.get("model")
    if not isinstance(model, str) or not model:
        model = art.get("model")
    if not isinstance(model, str) or not model:
        model = WORKLOAD_DEFAULTS["model"]
    scale = row.get("param_scale", art.get("param_scale"))
    if not isinstance(scale, (int, float)) or isinstance(scale, bool):
        scale = WORKLOAD_DEFAULTS["param_scale"]
    ndev = row.get("n_devices", art.get("n_devices"))
    if isinstance(ndev, bool) or not isinstance(ndev, (int, float)):
        ndev = None
    pcb = row.get("per_chip_batch")
    if isinstance(pcb, bool) or not isinstance(pcb, (int, float)):
        pcb = None
    return {"model": model, "param_scale": int(scale),
            "n_devices": int(ndev) if ndev is not None else None,
            "per_chip_batch": int(pcb) if pcb is not None else None}


def strategy_row_label(row: dict, artifact: Optional[dict] = None) -> str:
    """The efficiency-gate row label: strategy, `+overlap` for
    bucket-pipelined rows, `@model xN` for non-default workloads and
    `@Ndev` for the device count — the key under which two artifacts'
    rows pair up for gating. Built on `normalize_workload`, the shared
    legacy-default rule."""
    wl = normalize_workload(row, artifact)
    label = str(row.get("strategy", "?"))
    if row.get("overlap"):
        label += "+overlap"
    if (wl["model"], wl["param_scale"]) != (WORKLOAD_DEFAULTS["model"],
                                            WORKLOAD_DEFAULTS["param_scale"]):
        label += f"@{wl['model']} x{wl['param_scale']}"
    if wl["n_devices"] is not None:
        label += f"@{wl['n_devices']}dev"
    return label


def ledger_row_errors(segment: List[dict]) -> List[Tuple[int, str]]:
    """Violations of the `ledger_row` point-record contract
    (telemetry/ledger.py emits these when the ledger CLI runs with
    --telemetry) within ONE segment, as (line_no, message) pairs — shared
    with the file-loading checker like `cost_record_errors`. A ledger row
    must carry a NON-EMPTY string `series` (the key the whole trend
    history joins on), a KNOWN direction (the gate is meaningless without
    one), and a FINITE numeric value (NaN/inf in a committed history would
    poison every later median)."""
    errors: List[Tuple[int, str]] = []
    for rec in segment:
        if rec.get("kind") != "point" or rec.get("name") != LEDGER_ROW_POINT:
            continue
        line = rec.get("_line", 0)
        attrs = rec.get("attrs") or {}
        series = attrs.get("series")
        if not (isinstance(series, str) and series):
            errors.append((line, f"ledger_row record missing a non-empty "
                                 f"series key (got {series!r})"))
        direction = attrs.get("direction")
        if direction not in LEDGER_DIRECTIONS:
            errors.append((line, f"ledger_row names unknown direction "
                                 f"{direction!r}; known: "
                                 f"{LEDGER_DIRECTIONS}"))
        value = attrs.get("value")
        if not isinstance(value, (int, float)) or isinstance(value, bool) \
                or not math.isfinite(value):
            errors.append((line, f"ledger_row value must be a finite "
                                 f"number; got {value!r}"))
    return errors


def skew(values) -> Tuple[float, float]:
    """(spread, spread as % of mean) of a set of durations — THE straggler
    math: max - min, and that spread relative to the mean. One function so
    the offline cross-process report below and the ONLINE drift detector
    (`telemetry/health.py`, which watches a rolling window of this
    process's own step times) can never disagree about what "skew" means.
    Empty/zero-mean input reads as no skew."""
    vals = list(values)
    if not vals:
        return 0.0, 0.0
    lo, hi = min(vals), max(vals)
    mean = sum(vals) / len(vals)
    spread = hi - lo
    return spread, (100.0 * spread / mean if mean > 0 else 0.0)


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------

def trace_files(target: str) -> List[str]:
    """Resolve a --telemetry dir (every `events*.jsonl` inside) or a single
    trace file to a sorted list of paths. Missing target -> []."""
    if os.path.isdir(target):
        return sorted(glob.glob(os.path.join(target, "events*.jsonl")))
    return [target] if os.path.exists(target) else []


def load_trace(path: str) -> Tuple[List[dict], List[str]]:
    """Parse one JSONL trace file -> (records, errors). Lenient: malformed
    lines become errors, not exceptions — a crashed run's torn last line
    must not hide the rest of the trace. Each record gains `_line` (1-based
    line number) and `_file` for error attribution."""
    records: List[dict] = []
    errors: List[str] = []
    with open(path) as f:
        for line_no, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                errors.append(f"{path}:{line_no}: malformed JSON ({e})")
                continue
            if not isinstance(rec, dict):
                errors.append(f"{path}:{line_no}: record is not an object")
                continue
            rec["_line"] = line_no
            rec["_file"] = path
            records.append(rec)
    return records, errors


def load_traces(paths: List[str]) -> Tuple[List[dict], List[str]]:
    """Concatenate several per-process trace files (order preserved within
    each file; files are independent streams, never interleaved)."""
    records: List[dict] = []
    errors: List[str] = []
    for p in paths:
        recs, errs = load_trace(p)
        records.extend(recs)
        errors.extend(errs)
    return records, errors


# ---------------------------------------------------------------------------
# span-tree reconstruction (shared with scripts/check_telemetry.py)
# ---------------------------------------------------------------------------

def split_segments(records: List[dict]) -> List[List[dict]]:
    """One ONE-FILE record stream -> run segments. Files open in append
    mode, so an outage-resume re-exec or repeat run adds a segment beginning
    with a fresh `trace_start` meta record; span ids and the monotonic clock
    reset per segment."""
    segments: List[List[dict]] = []
    current: List[dict] = []
    for rec in records:
        if rec.get("kind") == "meta" and rec.get("name") == "trace_start":
            if current:
                segments.append(current)
            current = []
        current.append(rec)
    if current:
        segments.append(current)
    return segments


def _span_interval(rec: dict) -> Optional[Tuple[float, float]]:
    """A LIVE span's [t0, t0+dur] monotonic interval, from the start stamp
    `_Span._finish` stores in attrs. Aggregate spans (`complete_span`: a
    duration measured elsewhere, no start stamp) have no interval."""
    attrs = rec.get("attrs") or {}
    t0 = attrs.get("t0_mono")
    dur = rec.get("dur_s")
    if isinstance(t0, (int, float)) and isinstance(dur, (int, float)):
        return float(t0), float(t0) + float(dur)
    return None


def span_structure_errors(segment: List[dict]) -> List[Tuple[int, str]]:
    """Structural violations of ONE segment's span records, as
    (line_no, message) pairs — the span-tree reconstructor the checker and
    `analyze` share. Checks:

      * orphaned parents — a span's `parent` id never recorded in the
        segment (parents close AFTER children, so ids resolve against the
        whole segment);
      * duplicate span ids — the writer's counter is unique per segment, a
        repeat means interleaved writers or a corrupted file;
      * enter/exit consistency — a live span's exit (t0_mono + dur_s) must
        not land after its emission stamp: every recorded exit must match
        a real enter (negative durations are a field-level violation the
        checker's schema pass owns);
      * crossing spans — a live child's interval must sit inside its live
        parent's (strict nesting is what the writer's stack guarantees;
        a violation means ids were reused or clocks mixed).
    """
    spans: Dict[object, dict] = {}
    errors: List[Tuple[int, str]] = []
    for rec in segment:
        if rec.get("kind") != "span" or "span" not in rec:
            continue
        sid, line = rec["span"], rec.get("_line", 0)
        if sid in spans:
            errors.append((line, f"duplicate span id {sid} in segment"))
            continue
        spans[sid] = rec
        iv = _span_interval(rec)
        if iv is not None:
            t0, t1 = iv
            t_emit = rec.get("t_mono")
            # (a negative dur_s is a FIELD-level violation, owned by the
            # checker's per-line schema pass — flagging it here too would
            # double-count one defect)
            if (isinstance(t_emit, (int, float))
                    and t1 > float(t_emit) + _EPS):
                errors.append((line, f"span {sid} exit (t0_mono + dur_s = "
                                     f"{t1:.6f}) is after its emission "
                                     f"stamp {float(t_emit):.6f} — no "
                                     f"matching enter for this exit"))
    for sid, rec in spans.items():
        parent = rec.get("parent")
        if parent is None:
            continue
        line = rec.get("_line", 0)
        if parent not in spans:
            errors.append((line, f"parent span {parent} never recorded"))
            continue
        child_iv, parent_iv = _span_interval(rec), _span_interval(spans[parent])
        if child_iv is None or parent_iv is None:
            continue  # aggregate durations have no interval to contain
        (c0, c1), (p0, p1) = child_iv, parent_iv
        if c0 < p0 - _EPS or c1 > p1 + _EPS:
            errors.append((line, f"span {sid} [{c0:.6f}, {c1:.6f}] crosses "
                                 f"its parent {parent} [{p0:.6f}, {p1:.6f}]"))
    errors.sort(key=lambda e: e[0])
    return errors


def serve_structure_errors(segment: List[dict]) -> List[Tuple[int, str]]:
    """Violations of the serve request/batch span contract within ONE
    segment, as (line_no, message) pairs — shared by the file-loading
    checker exactly like `span_structure_errors`. Checks:

      * every `serve.request` span carries a NON-EMPTY string
        `request_id` (the attribution key every reader joins on);
      * a request's `batch` link resolves to a real `serve.batch` span's
        `batch_id` in the same segment (N requests -> the one batch that
        carried them; a dangling link means the batch span was lost and
        the shared-stage attribution is unverifiable);
      * `serve.batch` spans carry a non-empty `batch_id`, a known
        `coalesce` reason, and a bucket >= n_real >= 1 (occupancy > 1
        would mean rows the engine never computed);
      * a batch's stage children start in pipeline order
        (batch_form -> pad_h2d -> compute, monotone t0).
    """
    errors: List[Tuple[int, str]] = []
    batch_ids = set()
    # parent span id -> [(t0, name, line)] for batch stage children
    children: Dict[object, List[Tuple[float, str, int]]] = {}
    requests: List[dict] = []
    for rec in segment:
        if rec.get("kind") != "span":
            continue
        name, line = rec.get("name"), rec.get("_line", 0)
        attrs = rec.get("attrs") or {}
        if name == SERVE_REQUEST_SPAN:
            requests.append(rec)
            rid = attrs.get("request_id")
            if not (isinstance(rid, str) and rid):
                errors.append((line, f"serve.request span missing a "
                                     f"non-empty request_id (got {rid!r})"))
        elif name == SERVE_BATCH_SPAN:
            bid = attrs.get("batch_id")
            if not (isinstance(bid, str) and bid):
                errors.append((line, f"serve.batch span missing a "
                                     f"non-empty batch_id (got {bid!r})"))
            else:
                batch_ids.add(bid)
            reason = attrs.get("coalesce")
            if reason not in SERVE_COALESCE_REASONS:
                errors.append((line, f"unknown coalesce reason {reason!r}; "
                                     f"known: {SERVE_COALESCE_REASONS}"))
            bucket, n_real = attrs.get("bucket"), attrs.get("n_real")
            if not (isinstance(bucket, int) and isinstance(n_real, int)
                    and not isinstance(bucket, bool)
                    and not isinstance(n_real, bool)):
                # absent or mistyped fields are themselves a contract
                # violation — a guard that silently skips them could not
                # catch the occupancy story going missing
                errors.append((line, f"serve.batch span missing int "
                                     f"bucket/n_real fields (got "
                                     f"bucket={bucket!r}, "
                                     f"n_real={n_real!r})"))
            elif not 1 <= n_real <= bucket:
                errors.append((line, f"batch n_real {n_real} outside "
                                     f"[1, bucket {bucket}]"))
        elif name in SERVE_BATCH_STAGE_ORDER:
            iv = _span_interval(rec)
            parent = rec.get("parent")
            if iv is not None and parent is not None:
                children.setdefault(parent, []).append(
                    (iv[0], name, line))
    for rec in requests:
        attrs = rec.get("attrs") or {}
        link = attrs.get("batch")
        if link is not None and link not in batch_ids:
            errors.append((rec.get("_line", 0),
                           f"request {attrs.get('request_id')!r} links to "
                           f"batch {link!r} but no serve.batch span with "
                           f"that batch_id exists in this segment"))
    order = {n: i for i, n in enumerate(SERVE_BATCH_STAGE_ORDER)}
    for stages in children.values():
        stages.sort(key=lambda it: it[0])   # by start stamp
        last = -1
        for _t0, name, line in stages:
            if order[name] < last:
                errors.append((line, f"batch stage {name} starts before "
                                     f"an earlier pipeline stage ended "
                                     f"its turn (stage order must be "
                                     f"{SERVE_BATCH_STAGE_ORDER})"))
            last = max(last, order[name])
    errors.sort(key=lambda e: e[0])
    return errors


def cost_record_errors(segment: List[dict]) -> List[Tuple[int, str]]:
    """Violations of the `program_cost` point-record contract
    (telemetry/costs.py emits these at harvest) within ONE segment, as
    (line_no, message) pairs — shared with the file-loading checker like
    `serve_structure_errors`. A cost record must carry a NON-EMPTY string
    `program` (the attribution key compile times, OOM dumps, and the gate
    all join on) and only non-negative numbers in its cost fields (a
    negative flop/byte count is harvester garbage, not data)."""
    errors: List[Tuple[int, str]] = []
    for rec in segment:
        if rec.get("kind") != "point" or rec.get("name") != COST_POINT:
            continue
        line = rec.get("_line", 0)
        attrs = rec.get("attrs") or {}
        program = attrs.get("program")
        if not (isinstance(program, str) and program):
            errors.append((line, f"program_cost record missing a "
                                 f"non-empty program label (got "
                                 f"{program!r})"))
        for fld in COST_NUMERIC_FIELDS:
            v = attrs.get(fld)
            if v is None:
                continue
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v < 0:
                errors.append((line, f"program_cost field {fld!r} must be "
                                     f"a non-negative number when "
                                     f"present; got {v!r}"))
    return errors


def dispatch_record_errors(segment: List[dict]) -> List[Tuple[int, str]]:
    """Violations of the dispatch-forensics point-record contract
    (telemetry/dispatch.py emits `dispatch_phase` / `dispatch_window`
    points at each epoch flush) within ONE segment, as (line_no, message)
    pairs — shared with the file-loading checker like
    `cost_record_errors`. A phase record must name a KNOWN phase
    (DISPATCH_PHASES — an unknown name means the writer and reader
    catalogs drifted), carry a non-negative `total_s`, and a non-negative
    int `step` index; a window record must carry non-negative `window_s`
    and `attributed_s`."""
    errors: List[Tuple[int, str]] = []
    for rec in segment:
        if rec.get("kind") != "point":
            continue
        name = rec.get("name")
        line = rec.get("_line", 0)
        attrs = rec.get("attrs") or {}
        if name == DISPATCH_PHASE_POINT:
            phase = attrs.get("phase")
            if phase not in DISPATCH_PHASES:
                errors.append((line, f"dispatch_phase record names unknown "
                                     f"phase {phase!r}; known: "
                                     f"{DISPATCH_PHASES}"))
            total = attrs.get("total_s")
            if not isinstance(total, (int, float)) \
                    or isinstance(total, bool) or total < 0:
                errors.append((line, f"dispatch_phase total_s must be a "
                                     f"non-negative number; got {total!r}"))
            step = attrs.get("step")
            if not isinstance(step, int) or isinstance(step, bool) \
                    or step < 0:
                errors.append((line, f"dispatch_phase step must be a "
                                     f"non-negative int index; got "
                                     f"{step!r}"))
        elif name == DISPATCH_WINDOW_POINT:
            for fld in ("window_s", "attributed_s"):
                v = attrs.get(fld)
                if not isinstance(v, (int, float)) or isinstance(v, bool) \
                        or v < 0:
                    errors.append((line, f"dispatch_window {fld} must be a "
                                         f"non-negative number; got "
                                         f"{v!r}"))
    return errors


def fleet_record_errors(segment: List[dict]) -> List[Tuple[int, str]]:
    """Violations of the fleet/reload point-record contract
    (serve/fleet.py state transitions, serve/reload.py verdicts) within
    ONE segment, as (line_no, message) pairs — shared with the
    file-loading checker like `dispatch_record_errors`.

    A `fleet_event` must name a KNOWN event (writer/reader catalog
    drift otherwise) and a non-negative int `replica`; a quarantine must
    name a known cause. A `reload_event` must name a known event; a
    `swapped` event must carry `outstanding_at_swap == 0` — THE
    drain-before-swap invariant (any other value means a request's
    batch was still in flight when the engine under it changed); a
    `refused` event must carry a non-empty string `reason` (refusal
    by name is the whole point)."""
    errors: List[Tuple[int, str]] = []
    for rec in segment:
        if rec.get("kind") != "point":
            continue
        name = rec.get("name")
        if name not in (FLEET_EVENT_POINT, RELOAD_EVENT_POINT):
            continue
        line = rec.get("_line", 0)
        attrs = rec.get("attrs") or {}
        event = attrs.get("event")
        if name == FLEET_EVENT_POINT:
            if event not in FLEET_EVENTS:
                errors.append((line, f"fleet_event names unknown event "
                                     f"{event!r}; known: {FLEET_EVENTS}"))
                continue
            rep = attrs.get("replica")
            if not isinstance(rep, int) or isinstance(rep, bool) \
                    or rep < 0:
                errors.append((line, f"fleet_event {event} replica must "
                                     f"be a non-negative int; got "
                                     f"{rep!r}"))
            if event == "quarantine" \
                    and attrs.get("cause") not in QUARANTINE_CAUSES:
                errors.append((line, f"fleet_event quarantine names "
                                     f"unknown cause "
                                     f"{attrs.get('cause')!r}; known: "
                                     f"{QUARANTINE_CAUSES}"))
            continue
        if event not in RELOAD_EVENTS:
            errors.append((line, f"reload_event names unknown event "
                                 f"{event!r}; known: {RELOAD_EVENTS}"))
            continue
        if event == "swapped":
            out = attrs.get("outstanding_at_swap")
            if out != 0 or isinstance(out, bool):
                errors.append((line, f"reload_event swapped violates the "
                                     f"drain-before-swap invariant: "
                                     f"outstanding_at_swap must be 0, "
                                     f"got {out!r}"))
        elif event == "refused":
            reason = attrs.get("reason")
            if not (isinstance(reason, str) and reason):
                errors.append((line, f"reload_event refused must carry a "
                                     f"non-empty string reason; got "
                                     f"{reason!r}"))
    return errors


# ---------------------------------------------------------------------------
# statistics
# ---------------------------------------------------------------------------

def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile of an already-sorted list; 0.0 when empty.
    Exact for the sample (no bucketing — the trace holds every duration)."""
    if not sorted_vals:
        return 0.0
    rank = max(1, math.ceil(q * len(sorted_vals)))
    return sorted_vals[min(rank, len(sorted_vals)) - 1]


def _stats(vals: List[float], with_p99: bool = False) -> dict:
    """n/p50/p95/max/mean/total over `vals`; the serve report adds p99
    (tail attribution is ABOUT the p99) via `with_p99` — one builder, so
    a fix to either caller's stats cannot miss the other."""
    s = sorted(vals)
    out = {
        "n": len(s),
        "p50_s": _percentile(s, 0.50),
        "p95_s": _percentile(s, 0.95),
    }
    if with_p99:
        out["p99_s"] = _percentile(s, 0.99)
    out.update({
        "max_s": s[-1] if s else 0.0,
        "mean_s": (sum(s) / len(s)) if s else 0.0,
        "total_s": sum(s),
    })
    return out


def clock_offset(records: List[dict]) -> float:
    """This stream's wall = mono + offset. Every record carries both stamps,
    so the offset is the median of their differences — robust to the few
    records delayed between the two clock reads (e.g. under a paging
    stall)."""
    diffs = sorted(float(r["t_wall"]) - float(r["t_mono"]) for r in records
                   if isinstance(r.get("t_wall"), (int, float))
                   and isinstance(r.get("t_mono"), (int, float)))
    return diffs[len(diffs) // 2] if diffs else 0.0


def _linear_trend_pct(values: List[float]) -> Optional[float]:
    """Least-squares slope of `values` over their index, as percent of the
    mean per step — the per-epoch drift signal (positive = getting slower).
    None with fewer than 2 points or a zero mean."""
    n = len(values)
    if n < 2:
        return None
    mean = sum(values) / n
    if mean <= 0:
        return None
    xbar = (n - 1) / 2
    num = sum((i - xbar) * (v - mean) for i, v in enumerate(values))
    den = sum((i - xbar) ** 2 for i in range(n))
    return 100.0 * (num / den) / mean


def analyze(paths: List[str]) -> dict:
    """One or many per-process trace files -> the machine-readable report.

    Phase statistics pool every process's spans (a straggler's slow steps
    belong in the distribution); the straggler section then separates the
    processes back out, comparing per-epoch durations and wall-aligned
    start times across ranks."""
    records, parse_errors = load_traces(paths)
    span_errors = list(parse_errors)
    # name -> [dur], pooled across processes
    phase_durs: Dict[str, List[float]] = {name: [] for name in PHASES}
    # (segment_ordinal, proc, epoch) -> dur / aligned wall start, from the
    # epoch spans. The segment ordinal keeps appended runs apart: a repeat
    # run re-emits epochs 0..N into the same file, and collapsing them to
    # (proc, epoch) would silently last-wins-overwrite the first run.
    epoch_dur: Dict[Tuple[int, int, int], float] = {}
    epoch_start: Dict[Tuple[int, int, int], float] = {}
    procs = set()
    snapshots = 0

    by_file: Dict[str, List[dict]] = {}
    for rec in records:
        by_file.setdefault(rec["_file"], []).append(rec)

    for path, recs in by_file.items():
        for seg_idx, seg in enumerate(split_segments(recs)):
            # Offset per SEGMENT, not per file: the monotonic clock resets
            # across the re-exec/reboot that starts an appended segment, so
            # one file-wide median would misalign whichever segment has
            # fewer records by the whole outage gap.
            off = clock_offset(seg)
            span_errors.extend(
                f"{path}:{line}: {msg}"
                for line, msg in span_structure_errors(seg))
            for rec in seg:
                proc = rec.get("proc", 0)
                procs.add(proc)
                kind = rec.get("kind")
                if kind == "snapshot":
                    snapshots += 1
                if kind != "span":
                    continue
                dur = rec.get("dur_s")
                if not isinstance(dur, (int, float)):
                    continue
                name = rec.get("name")
                if name in phase_durs:
                    phase_durs[name].append(float(dur))
                if name in ("epoch", "fused_run"):
                    attrs = rec.get("attrs") or {}
                    epoch = attrs.get("epoch", 0)
                    if not isinstance(epoch, int):
                        continue
                    key = (seg_idx, proc, epoch)
                    epoch_dur[key] = float(dur)
                    iv = _span_interval(rec)
                    if iv is not None:
                        epoch_start[key] = iv[0] + off

    phases = {name: _stats(durs)
              for name, durs in phase_durs.items() if durs}

    # per-epoch trend: mean duration across processes, in run order
    # (segment ordinal first — an appended repeat run's epochs follow the
    # first run's, they do not merge with them)
    epoch_ids = sorted({(s, e) for (s, _p, e) in epoch_dur})
    per_epoch_mean = []
    for s, e in epoch_ids:
        durs = [d for (ss, _p, ee), d in epoch_dur.items()
                if (ss, ee) == (s, e)]
        per_epoch_mean.append(sum(durs) / len(durs))
    epochs = {
        "count": len(epoch_ids),
        "mean_s": (sum(per_epoch_mean) / len(per_epoch_mean)
                   if per_epoch_mean else 0.0),
        "durations_s": per_epoch_mean,
        "trend_pct_per_epoch": _linear_trend_pct(per_epoch_mean),
    }

    # straggler skew: same epoch, different processes
    straggler = {"processes": len(procs), "epochs_compared": 0,
                 "max_skew_s": 0.0, "max_skew_pct": 0.0,
                 "mean_skew_pct": 0.0, "max_start_spread_s": 0.0,
                 "worst_epoch": None}
    skew_pcts = []
    for s, e in epoch_ids:
        durs = {p: d for (ss, p, ee), d in epoch_dur.items()
                if (ss, ee) == (s, e)}
        if len(durs) < 2:
            continue
        straggler["epochs_compared"] += 1
        skew_s, skew_pct = skew(durs.values())
        skew_pcts.append(skew_pct)
        if skew_s > straggler["max_skew_s"]:
            straggler.update(max_skew_s=skew_s, max_skew_pct=skew_pct,
                             worst_epoch={"epoch": e, "segment": s,
                                          "dur_s_by_proc": {str(p): d
                                                            for p, d
                                                            in sorted(
                                                                durs.items())}})
        starts = [epoch_start[(s, p, e)] for p in durs
                  if (s, p, e) in epoch_start]
        if len(starts) >= 2:
            straggler["max_start_spread_s"] = max(
                straggler["max_start_spread_s"], max(starts) - min(starts))
    if skew_pcts:
        straggler["mean_skew_pct"] = sum(skew_pcts) / len(skew_pcts)

    return {
        "report": "trace_phase_stats",
        "v": 1,
        "files": sorted(by_file),
        "processes": sorted(procs),
        "n_processes": len(procs),
        "records": len(records),
        "snapshots": snapshots,
        "span_errors": span_errors,
        "phases": phases,
        "epochs": epochs,
        "straggler": straggler,
    }


# ---------------------------------------------------------------------------
# the serve report: tail-latency attribution
# ---------------------------------------------------------------------------

def _serve_stats(vals: List[float]) -> dict:
    return _stats(vals, with_p99=True)


def serve_report(paths: List[str], exemplar_k: int = SERVE_EXEMPLAR_K) -> dict:
    """One or many serve trace files -> the tail-latency attribution
    report (`trace report --serve`):

      * per-stage latency statistics (p50/p95/p99) for every stage in
        `SERVE_STAGES`, with each stage's share of total end-to-end time
        (`pct_of_e2e`) — where the tail actually comes from;
      * `attribution_coverage`: sum of stage totals / e2e total. The
        stages telescope (each duration ends where the next begins), so
        this must sit near 1.0 — the acceptance test pins it within 5%.
        A hole here means a stage went missing, not jitter;
      * batch statistics: occupancy, padding waste (bucket rows computed
        that carried no request), coalesce-reason counts — the
        size-vs-deadline knob's observable output;
      * the slowest-`exemplar_k` requests as full stage trees (the same
        shape the live path leaves in the flight recorder at drain).

    Only completed requests with a full stage breakdown contribute to the
    stage table (a failed request has no honest decomposition); their
    count vs total is reported so silently dropped coverage is visible.
    """
    records, parse_errors = load_traces(paths)
    span_errors = list(parse_errors)
    stage_durs: Dict[str, List[float]] = {s: [] for s in SERVE_STAGES}
    e2e_durs: List[float] = []
    requests = attributed = 0
    exemplars: List[Tuple[float, int, dict]] = []
    batches: List[dict] = []
    procs = set()

    by_file: Dict[str, List[dict]] = {}
    for rec in records:
        by_file.setdefault(rec["_file"], []).append(rec)

    for path, recs in by_file.items():
        for seg in split_segments(recs):
            span_errors.extend(
                f"{path}:{line}: {msg}"
                for line, msg in span_structure_errors(seg))
            span_errors.extend(
                f"{path}:{line}: {msg}"
                for line, msg in serve_structure_errors(seg))
            for rec in seg:
                if rec.get("kind") != "span":
                    continue
                procs.add(rec.get("proc", 0))
                name = rec.get("name")
                attrs = rec.get("attrs") or {}
                if name == SERVE_BATCH_SPAN:
                    batches.append(attrs)
                if name != SERVE_REQUEST_SPAN:
                    continue
                requests += 1
                dur = rec.get("dur_s")
                stages = {s: attrs.get(f"{s}_s") for s in SERVE_STAGES}
                if (not isinstance(dur, (int, float))
                        or not all(isinstance(v, (int, float))
                                   for v in stages.values())):
                    continue   # failed / partial request: counted above
                attributed += 1
                e2e_durs.append(float(dur))
                for s, v in stages.items():
                    stage_durs[s].append(float(v))
                tree = {"request_id": attrs.get("request_id"),
                        "e2e_s": float(dur),
                        "stages": {f"{s}_s": float(v)
                                   for s, v in stages.items()},
                        "batch_id": attrs.get("batch")}
                item = (float(dur), attributed, tree)
                if len(exemplars) < exemplar_k:
                    heapq.heappush(exemplars, item)
                elif dur > exemplars[0][0]:
                    heapq.heapreplace(exemplars, item)

    e2e_total = sum(e2e_durs)
    stages_out = {}
    for s in SERVE_STAGES:
        durs = stage_durs[s]
        if not durs:
            continue
        st = _serve_stats(durs)
        st["pct_of_e2e"] = (100.0 * st["total_s"] / e2e_total
                            if e2e_total > 0 else 0.0)
        stages_out[s] = st
    stage_total = sum(st["total_s"] for st in stages_out.values())

    real_rows = sum(b.get("n_real", 0) for b in batches
                    if isinstance(b.get("n_real"), int))
    bucket_rows = sum(b.get("bucket", 0) for b in batches
                      if isinstance(b.get("bucket"), int))
    occs = [b["occupancy"] for b in batches
            if isinstance(b.get("occupancy"), (int, float))]
    coalesce: Dict[str, int] = {}
    for b in batches:
        r = b.get("coalesce")
        if isinstance(r, str):
            coalesce[r] = coalesce.get(r, 0) + 1

    return {
        "report": "serve_trace_attribution",
        "v": 1,
        "files": sorted(by_file),
        "processes": sorted(procs),
        "requests": requests,
        "attributed": attributed,
        "span_errors": span_errors,
        "e2e": _serve_stats(e2e_durs),
        "stages": stages_out,
        # stage totals / e2e total: the stages must ~cover the e2e story
        "attribution_coverage": (stage_total / e2e_total
                                 if e2e_total > 0 else None),
        "batches": {
            "count": len(batches),
            "mean_occupancy": (sum(occs) / len(occs) if occs else None),
            # bucket rows computed that carried no request — the padding
            # bill the coalescing knobs are paying
            "padding_waste_pct": (100.0 * (1.0 - real_rows / bucket_rows)
                                  if bucket_rows else None),
            "coalesce": coalesce,
        },
        "slowest": [t for _, _, t in sorted(exemplars,
                                            key=lambda it: -it[0])],
    }


# Below this per-request p95, a stage's share measures scheduler noise,
# not the pipeline: the share gate never regresses on a sub-ms stage —
# the same rule the step-time and data-share gates apply.
SERVE_SUBMS_EXEMPT_S = 1e-3


def compare_serve(new: dict, baseline: dict, threshold: float = 1.5) -> dict:
    """The serve stage-share regression gate (`trace report --serve
    --baseline OLD`): one row per stage present in both reports, gating
    each stage's SHARE of end-to-end time (`pct_of_e2e`). `compute` is
    the useful work — its share is better-BIGGER, so its ratio is old/new
    (the efficiency-gate convention: a drop reads as > 1); every other
    stage is overhead the fast path exists to shrink — better-smaller,
    ratio new/old. A regression is a ratio past `threshold`, UNLESS the
    stage's absolute per-request p95 is sub-millisecond in both runs
    (`SERVE_SUBMS_EXEMPT_S`: at that scale the share's numerator is
    scheduler noise — the step-time gate's exemption rule). The headline
    row this gate exists for: compute's share of e2e at saturation must
    not fall past threshold once the fast path lands (ROADMAP item 3)."""
    rows, regressions = [], []
    for stage in SERVE_STAGES:
        old_st = (baseline.get("stages") or {}).get(stage) or {}
        new_st = (new.get("stages") or {}).get(stage) or {}
        old_v, new_v = old_st.get("pct_of_e2e"), new_st.get("pct_of_e2e")
        if not (isinstance(old_v, (int, float))
                and isinstance(new_v, (int, float)) and old_v > 0):
            continue
        if stage == "compute":
            # a compute-share COLLAPSE to zero is the worst regression,
            # not a skippable row (the efficiency-gate convention)
            ratio = (old_v / new_v) if new_v > 0 else float("inf")
        else:
            ratio = new_v / old_v
        p95s = [v for v in (old_st.get("p95_s"), new_st.get("p95_s"))
                if isinstance(v, (int, float))]
        exempt = bool(p95s) and max(p95s) < SERVE_SUBMS_EXEMPT_S
        row = {"stage": stage, "stat": "pct_of_e2e",
               "baseline_pct": old_v, "new_pct": new_v, "ratio": ratio,
               "sub_ms_exempt": exempt,
               "regressed": ratio > threshold and not exempt}
        rows.append(row)
        if row["regressed"]:
            regressions.append(row)
    return {"threshold": threshold, "rows": rows,
            "regressions": regressions}


def format_compare_serve(diff: dict) -> str:
    lines = [f"serve stage-share gate (ratio > {diff['threshold']:g}x "
             f"regresses; compute share better-bigger, overhead shares "
             f"better-smaller; sub-ms stages exempt):"]
    for row in diff["rows"]:
        verdict = ("REGRESSION" if row["regressed"]
                   else "exempt (sub-ms)" if row["sub_ms_exempt"]
                   and row["ratio"] > diff["threshold"] else "ok")
        lines.append(f"  {row['stage']:<12} share "
                     f"{row['baseline_pct']:6.1f}% -> "
                     f"{row['new_pct']:6.1f}%  ({row['ratio']:.2f}x)  "
                     f"{verdict}")
    if not diff["rows"]:
        lines.append("  (no stage overlaps the baseline — nothing gated)")
    n = len(diff["regressions"])
    lines.append(f"regression gate: "
                 f"{f'FAIL — {n} stage share(s) past threshold' if n else 'PASS'}")
    return "\n".join(lines)


def format_serve_report(report: dict) -> str:
    """Human rendering of `serve_report` (the --json flag prints the dict
    itself)."""
    lines = [f"serve trace report: {report['requests']} request(s), "
             f"{report['attributed']} with full stage attribution, "
             f"{report['batches']['count']} batch(es)"]
    if report["stages"]:
        e2e = report["e2e"]
        lines.append(f"{'stage':<12} {'n':>6} {'p50_ms':>9} {'p95_ms':>9} "
                     f"{'p99_ms':>9} {'% of e2e':>9}")
        for s in SERVE_STAGES:
            st = report["stages"].get(s)
            if st:
                lines.append(f"{s:<12} {st['n']:>6} "
                             f"{st['p50_s'] * 1e3:>9.3f} "
                             f"{st['p95_s'] * 1e3:>9.3f} "
                             f"{st['p99_s'] * 1e3:>9.3f} "
                             f"{st['pct_of_e2e']:>8.1f}%")
        lines.append(f"{'e2e':<12} {e2e['n']:>6} {e2e['p50_s'] * 1e3:>9.3f} "
                     f"{e2e['p95_s'] * 1e3:>9.3f} "
                     f"{e2e['p99_s'] * 1e3:>9.3f} {'100.0%':>9}")
        cov = report["attribution_coverage"]
        lines.append(f"attribution coverage: {100.0 * cov:.1f}% of e2e "
                     f"accounted to stages" if cov is not None else
                     "attribution coverage: n/a")
    elif report["requests"]:
        lines.append(f"no fully attributed requests: {report['requests']} "
                     f"serve.request span(s) present but none carry a "
                     f"complete stage breakdown (all-failed requests, or "
                     f"a partial/torn trace)")
    else:
        lines.append("no serve.request spans found (serve with --telemetry "
                     "DIR to emit them)")
    b = report["batches"]
    if b["count"]:
        occ = (f"{b['mean_occupancy']:.3f}" if b["mean_occupancy"]
               is not None else "n/a")
        waste = (f"{b['padding_waste_pct']:.1f}%"
                 if b["padding_waste_pct"] is not None else "n/a")
        reasons = ", ".join(f"{k}={v}" for k, v in
                            sorted(b["coalesce"].items())) or "none"
        lines.append(f"batches: {b['count']} (mean occupancy {occ}, "
                     f"padding waste {waste}; coalesce: {reasons})")
    for i, t in enumerate(report["slowest"], 1):
        worst = max(t["stages"].items(), key=lambda kv: kv[1])
        lines.append(f"slow #{i}: {t['request_id']} "
                     f"e2e {t['e2e_s'] * 1e3:.3f}ms "
                     f"(worst stage {worst[0]} {worst[1] * 1e3:.3f}ms, "
                     f"batch {t['batch_id']})")
    if report["span_errors"]:
        lines.append(f"span structure: {len(report['span_errors'])} "
                     f"violation(s) — run scripts/check_telemetry.py")
    else:
        lines.append("span structure: OK")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# the data-wait attribution report (`trace report --data`)
# ---------------------------------------------------------------------------

# Below this, the data_wait p95 measures scheduler noise, not the input
# stack: the share gate never fires on a sub-millisecond wait (the same
# rule the step-time gate applies to its absolute values).
DATA_SUBMS_EXEMPT_S = 1e-3


def data_report(paths: List[str]) -> dict:
    """One or many train trace files -> the input-attribution report
    (`trace report --data`): per-epoch data_wait SHARE — the fraction of
    each `epoch` span its `data_wait` child occupies, i.e. how much of
    training the host spent blocked on the input pipeline. Shares pair a
    data_wait span with ITS OWN parent epoch span (per segment, per
    process), so appended resume runs and stragglers never cross-
    contaminate. The p95 share is what the regression gate rides
    (`compare_data`): the pipeline's whole job is driving it toward 0,
    and a silent regression here is invisible to the step-time gate when
    compute shrinks in proportion."""
    records, parse_errors = load_traces(paths)
    span_errors = list(parse_errors)
    shares: List[float] = []
    waits: List[float] = []
    epoch_durs: List[float] = []
    batch_counts: List[int] = []
    procs = set()

    by_file: Dict[str, List[dict]] = {}
    for rec in records:
        by_file.setdefault(rec["_file"], []).append(rec)

    for path, recs in by_file.items():
        for seg in split_segments(recs):
            span_errors.extend(
                f"{path}:{line}: {msg}"
                for line, msg in span_structure_errors(seg))
            spans = {rec["span"]: rec for rec in seg
                     if rec.get("kind") == "span" and "span" in rec}
            for rec in spans.values():
                procs.add(rec.get("proc", 0))
                if rec.get("name") != "data_wait":
                    continue
                dur = rec.get("dur_s")
                parent = spans.get(rec.get("parent"))
                if (not isinstance(dur, (int, float)) or parent is None
                        or parent.get("name") != "epoch"):
                    continue
                pdur = parent.get("dur_s")
                if not isinstance(pdur, (int, float)) or pdur <= 0:
                    continue
                waits.append(float(dur))
                epoch_durs.append(float(pdur))
                shares.append(float(dur) / float(pdur))
                nb = (rec.get("attrs") or {}).get("batches")
                if isinstance(nb, int) and not isinstance(nb, bool):
                    batch_counts.append(nb)

    s = sorted(shares)
    return {
        "report": "trace_data_stats",
        "v": 1,
        "files": sorted(by_file),
        "processes": sorted(procs),
        "records": len(records),
        "epochs": len(shares),
        "span_errors": span_errors,
        "data_wait": _stats(waits, with_p99=True),
        "epoch": _stats(epoch_durs),
        "batches": sum(batch_counts),
        # fractions of the epoch the host spent blocked on input
        "share": {
            "p50": _percentile(s, 0.50),
            "p95": _percentile(s, 0.95),
            "max": s[-1] if s else 0.0,
            "mean": (sum(s) / len(s)) if s else 0.0,
        },
    }


def compare_data(new: dict, baseline: dict, threshold: float = 1.5) -> dict:
    """The data_wait-share regression gate: one row per share stat
    (p50/p95) present in both reports; a regression is a share ratio
    (new/old) past `threshold` — mirroring the step-time gate's
    convention — UNLESS the new run's absolute data_wait p95 is
    sub-millisecond (`DATA_SUBMS_EXEMPT_S`: at that scale the share's
    numerator is scheduler noise). `cli/trace.py report --data
    --baseline` turns regressions into exit 3, the same contract as the
    step-time and efficiency gates."""
    rows, regressions = [], []
    new_wait_p95 = (new.get("data_wait") or {}).get("p95_s", 0.0)
    exempt = (isinstance(new_wait_p95, (int, float))
              and new_wait_p95 < DATA_SUBMS_EXEMPT_S)
    for stat in ("p50", "p95"):
        old_v = (baseline.get("share") or {}).get(stat)
        new_v = (new.get("share") or {}).get(stat)
        if not (isinstance(old_v, (int, float))
                and isinstance(new_v, (int, float)) and old_v > 0):
            continue
        ratio = new_v / old_v
        row = {"phase": "data_wait_share", "stat": stat,
               "baseline": old_v, "new": new_v, "ratio": ratio,
               "sub_ms_exempt": exempt,
               "regressed": ratio > threshold and not exempt}
        rows.append(row)
        if row["regressed"]:
            regressions.append(row)
    return {"threshold": threshold, "rows": rows,
            "regressions": regressions}


def format_data_report(report: dict) -> str:
    """Human rendering of `data_report` (the --json flag prints the dict
    itself)."""
    lines = [f"data report: {report['epochs']} epoch(s) with data_wait "
             f"attribution across {len(report['files'])} file(s), "
             f"{report['batches']} batch wait(s)"]
    if report["epochs"]:
        sh, dw = report["share"], report["data_wait"]
        lines.append(f"data_wait share of epoch: p50 {100 * sh['p50']:.1f}% "
                     f"p95 {100 * sh['p95']:.1f}% max {100 * sh['max']:.1f}% "
                     f"(mean {100 * sh['mean']:.1f}%)")
        lines.append(f"data_wait absolute: p50 {dw['p50_s']:.4f}s "
                     f"p95 {dw['p95_s']:.4f}s max {dw['max_s']:.4f}s "
                     f"total {dw['total_s']:.4f}s")
        lines.append(f"epoch absolute: p50 {report['epoch']['p50_s']:.4f}s "
                     f"p95 {report['epoch']['p95_s']:.4f}s")
    else:
        lines.append("no epoch spans with a data_wait child found (a "
                     "--telemetry STREAMING train run emits them; the "
                     "cached path has no host data wait)")
    if report["span_errors"]:
        lines.append(f"span structure: {len(report['span_errors'])} "
                     f"violation(s) — run scripts/check_telemetry.py")
    return "\n".join(lines)


def format_compare_data(diff: dict) -> str:
    lines = [f"data-wait share gate (ratio > {diff['threshold']:g}x "
             f"regresses; sub-ms data_wait exempt):"]
    for row in diff["rows"]:
        verdict = ("REGRESSION" if row["regressed"]
                   else "exempt (sub-ms)" if row["sub_ms_exempt"]
                   and row["ratio"] > diff["threshold"] else "ok")
        lines.append(f"  share {row['stat']:<4} "
                     f"{100 * row['baseline']:.1f}% -> "
                     f"{100 * row['new']:.1f}%  ({row['ratio']:.2f}x)  "
                     f"{verdict}")
    if not diff["rows"]:
        lines.append("  (no share stats overlap baseline — nothing gated)")
    n = len(diff["regressions"])
    lines.append(f"regression gate: "
                 f"{f'FAIL — {n} share stat(s) past threshold' if n else 'PASS'}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# the regression gate
# ---------------------------------------------------------------------------

EFFICIENCY_STAT = "scaling_efficiency_vs_1dev"


def efficiency_report(artifact: dict, path: str = "<artifact>") -> dict:
    """A report-shaped dict from a DDP bench artifact (the
    `MULTICHIP_r0X.json` shape: a `strategies` list of
    `bench.ddp_strategy_rows` rows). The phases section stays empty —
    what the artifact carries is per-strategy `scaling_efficiency_vs_1dev`
    under an `efficiency` key, which `compare` gates exactly like the
    step-time stats (ROADMAP item 2: efficiency regressions must exit 3
    like step-time regressions already do). Row labels are
    `strategy` plus `+overlap` for bucket-pipelined rows, plus
    `@model xN` for rows measured on a non-default workload and
    `@Ndev` for the device count (row-level, falling back to the
    artifact's) — efficiency is only comparable at MATCHED model size
    AND device count (per-chip efficiency always falls as devices grow),
    so rows from different `--model`/`--param_scale`/pool-size runs
    must never gate against each other (legacy artifacts without the
    workload fields are the default 118k mlp at scale 1 — the shared
    `normalize_workload` rule, which the performance ledger's series
    keys also use, so gate labels and ledger series can never
    disagree)."""
    eff = {}
    for row in artifact.get("strategies") or []:
        if not isinstance(row, dict):
            continue
        v = row.get(EFFICIENCY_STAT)
        if not isinstance(v, (int, float)):
            continue
        eff[strategy_row_label(row, artifact)] = float(v)
    return {
        "report": "trace_phase_stats", "v": 1,
        "files": [path], "processes": [], "n_processes": 0,
        "records": len(eff), "snapshots": 0, "span_errors": [],
        "phases": {},
        "epochs": {"count": 0, "mean_s": 0.0, "durations_s": [],
                   "trend_pct_per_epoch": None},
        "straggler": {"processes": 0, "epochs_compared": 0,
                      "max_skew_s": 0.0, "max_skew_pct": 0.0,
                      "mean_skew_pct": 0.0, "max_start_spread_s": 0.0,
                      "worst_epoch": None},
        "efficiency": eff,
    }


def compare(new: dict, baseline: dict, threshold: float = 1.5,
            stats: Tuple[str, ...] = ("p50_s", "p95_s")) -> dict:
    """Diff two reports' phase statistics -> {"rows": [...], "regressions":
    [...]}. A row per (phase, stat) present in both reports; a regression is
    a ratio past `threshold` (new/old > threshold means SLOWER). Tiny
    absolute values are not gated (< 1 ms both sides): at that scale the
    ratio measures scheduler noise, not the workload.

    Reports carrying an `efficiency` section (DDP bench artifacts via
    `efficiency_report`) gate scaling efficiency the same way, one row per
    strategy present in both. Efficiency is better-is-BIGGER, so its ratio
    is old/new — the same "ratio > threshold means regressed" convention
    as the time rows (a drop from 0.3 to 0.15 reads as 2.0x)."""
    rows, regressions = [], []
    for phase in sorted(set(new.get("phases", {}))
                        & set(baseline.get("phases", {}))):
        for stat in stats:
            old_v = baseline["phases"][phase].get(stat)
            new_v = new["phases"][phase].get(stat)
            if not (isinstance(old_v, (int, float))
                    and isinstance(new_v, (int, float)) and old_v > 0):
                continue
            ratio = new_v / old_v
            row = {"phase": phase, "stat": stat, "baseline_s": old_v,
                   "new_s": new_v, "ratio": ratio,
                   "regressed": (ratio > threshold
                                 and max(old_v, new_v) >= 1e-3)}
            rows.append(row)
            if row["regressed"]:
                regressions.append(row)
    eff_new = new.get("efficiency") or {}
    eff_old = baseline.get("efficiency") or {}
    for label in sorted(set(eff_new) & set(eff_old)):
        old_v, new_v = eff_old[label], eff_new[label]
        if not (isinstance(old_v, (int, float))
                and isinstance(new_v, (int, float)) and old_v > 0):
            continue
        # efficiency DROP reads as >1 (slower); a collapse to <= 0 (the
        # artifact rounds to 4 decimals, so a dead strategy lands as
        # exactly 0.0) is the WORST regression, not a skippable row
        ratio = (old_v / new_v) if new_v > 0 else float("inf")
        row = {"phase": label, "stat": EFFICIENCY_STAT,
               "baseline_s": old_v, "new_s": new_v, "ratio": ratio,
               "regressed": ratio > threshold}
        rows.append(row)
        if row["regressed"]:
            regressions.append(row)
    return {"threshold": threshold, "rows": rows, "regressions": regressions}


def format_report(report: dict) -> str:
    """The human rendering of `analyze`'s dict (the --json flag prints the
    dict itself)."""
    lines = [f"trace report: {report['n_processes']} process(es), "
             f"{len(report['files'])} file(s), {report['records']} "
             f"record(s)"]
    if report["phases"]:
        lines.append(f"{'phase':<14} {'n':>6} {'p50_s':>10} {'p95_s':>10} "
                     f"{'max_s':>10} {'total_s':>10}")
        for name in PHASES:
            st = report["phases"].get(name)
            if st:
                lines.append(f"{name:<14} {st['n']:>6} {st['p50_s']:>10.4f} "
                             f"{st['p95_s']:>10.4f} {st['max_s']:>10.4f} "
                             f"{st['total_s']:>10.4f}")
    else:
        lines.append("no phase spans found (not a --telemetry train trace?)")
    ep = report["epochs"]
    if ep["count"]:
        trend = ep["trend_pct_per_epoch"]
        trend_txt = (f", trend {trend:+.1f}%/epoch" if trend is not None
                     else "")
        lines.append(f"epochs: {ep['count']} "
                     f"(mean {ep['mean_s']:.4f}s{trend_txt})")
    st = report["straggler"]
    if st["epochs_compared"]:
        worst = st["worst_epoch"]
        lines.append(f"straggler skew: max {st['max_skew_s']:.4f}s "
                     f"({st['max_skew_pct']:.1f}% of epoch mean) at epoch "
                     f"{worst['epoch']}; mean {st['mean_skew_pct']:.1f}%; "
                     f"start spread {st['max_start_spread_s']:.4f}s")
    elif st["processes"] > 1:
        lines.append("straggler skew: no epoch seen on 2+ processes")
    else:
        lines.append("straggler skew: single process (nothing to compare)")
    if report["span_errors"]:
        lines.append(f"span structure: {len(report['span_errors'])} "
                     f"violation(s) — run scripts/check_telemetry.py")
    else:
        lines.append("span structure: OK")
    return "\n".join(lines)


def format_compare(diff: dict) -> str:
    lines = [f"baseline comparison (gate: ratio > {diff['threshold']:g}x "
             f"on p50/p95):"]
    for row in diff["rows"]:
        verdict = "REGRESSION" if row["regressed"] else "ok"
        u = "" if row["stat"] == EFFICIENCY_STAT else "s"
        lines.append(f"  {row['phase']:<14} {row['stat']:<6} "
                     f"{row['baseline_s']:.4f}{u} -> {row['new_s']:.4f}{u}  "
                     f"({row['ratio']:.2f}x)  {verdict}")
    if not diff["rows"]:
        lines.append("  (no phase overlaps baseline — nothing gated)")
    n = len(diff["regressions"])
    verdict = f"FAIL — {n} phase stat(s) past threshold" if n else "PASS"
    lines.append(f"regression gate: {verdict}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# dispatch-overhead attribution (trace report --overhead)
# ---------------------------------------------------------------------------

def _overhead_row(program: str, phases_s: dict, *, window_s: float,
                  steps: int, coverage: float,
                  overhead_share: Optional[float] = None,
                  note: Optional[str] = None) -> dict:
    """One report row: per-phase totals + shares of the window, coverage,
    and the worst HOST phase (device_idle is the device-side view of the
    same interval python_prestep + dispatch occupy — never 'worst')."""
    phases = {}
    for phase in DISPATCH_PHASES:
        total = phases_s.get(phase)
        if not isinstance(total, (int, float)):
            continue
        phases[phase] = {
            "total_s": float(total),
            "share": (float(total) / window_s) if window_s > 0 else 0.0,
        }
    host = [(p, phases[p]["total_s"]) for p in DISPATCH_COVERAGE_PHASES
            if p in phases]
    worst = max(host, key=lambda it: it[1]) if host else (None, 0.0)
    row = {
        "program": program,
        "window_s": window_s,
        "steps": int(steps),
        "phases": phases,
        "attributed_s": sum(s for _p, s in host),
        "coverage": coverage,
        "worst_phase": worst[0],
        "worst_share": ((worst[1] / window_s) if window_s > 0 else 0.0),
    }
    if overhead_share is not None:
        row["overhead_share"] = overhead_share
    if note:
        row["note"] = note
    return row


def overhead_report(paths: List[str]) -> dict:
    """The dispatch-overhead decomposition from one or many `--telemetry
    --profile_dispatch` traces: per-phase totals pooled across epochs and
    processes, shares of the profiled step-boundary window, coverage =
    attributed / window (what share of the window the named phases
    explain — falls below OVERHEAD_COVERAGE_MIN when someone grows
    unprofiled loop work), and the worst host phase. One row per
    process's trace (label `train` / `train@rankN`)."""
    records, errors = load_traces(paths)
    by_file: dict = {}
    for rec in records:
        by_file.setdefault(rec["_file"], []).append(rec)
    rows = []
    for fname in sorted(by_file):
        phases_s: dict = {}
        window_s = attributed_s = 0.0
        steps = 0
        proc = 0
        seen = False
        for rec in by_file[fname]:
            if rec.get("kind") != "point":
                continue
            attrs = rec.get("attrs") or {}
            if rec.get("name") == DISPATCH_PHASE_POINT:
                phase, total = attrs.get("phase"), attrs.get("total_s")
                if phase in DISPATCH_PHASES \
                        and isinstance(total, (int, float)):
                    phases_s[phase] = phases_s.get(phase, 0.0) \
                        + float(total)
                    seen = True
            elif rec.get("name") == DISPATCH_WINDOW_POINT:
                window_s += float(attrs.get("window_s") or 0.0)
                attributed_s += float(attrs.get("attributed_s") or 0.0)
                steps += int(attrs.get("steps") or 0)
                proc = int(rec.get("proc", 0))
                seen = True
        if not seen:
            continue
        coverage = (attributed_s / window_s) if window_s > 0 else 1.0
        label = "train" if proc == 0 else f"train@rank{proc}"
        rows.append(_overhead_row(label, phases_s, window_s=window_s,
                                  steps=steps, coverage=coverage))
    return {"report": OVERHEAD_REPORT_TAG, "v": 1,
            "files": sorted(by_file), "load_errors": errors, "rows": rows}


def overhead_from_artifact(artifact: dict,
                           path: str = "<artifact>") -> dict:
    """The same report shape from a DDP bench artifact (the
    `MULTICHIP_r0X.json` shape) whose rows carry the `overhead_phases`
    stamp (`bench.py --mode ddp` measures a streaming-step dispatch probe
    per strategy). The window is the probe's full step-boundary wall
    (host phases sum to it by construction); `coverage` is the stamped
    share of the roofline's O = T - bound that the host phases explain,
    clamped at 1.0 when the streaming probe's host cost exceeds the
    fused program's O (an upper-bound attribution — docs/PERF.md).
    Legacy rows without the stamp degrade to a named note, never a
    silent skip."""
    rows = []
    for row in artifact.get("strategies") or []:
        if not isinstance(row, dict):
            continue
        label = str(row.get("strategy", "?"))
        if row.get("overlap"):
            label += "+overlap"
        phases_s = row.get("overhead_phases")
        if not isinstance(phases_s, dict):
            rows.append({"program": label, "window_s": 0.0, "steps": 0,
                         "phases": {}, "attributed_s": 0.0,
                         "coverage": None, "worst_phase": None,
                         "worst_share": 0.0,
                         "note": "no overhead_phases stamp (artifact "
                                 "predates the dispatch probe)"})
            continue
        window_s = sum(float(phases_s.get(p) or 0.0)
                       for p in DISPATCH_COVERAGE_PHASES)
        cov = row.get("overhead_coverage")
        out = _overhead_row(
            label, phases_s, window_s=window_s,
            steps=int(row.get("overhead_probe_steps") or 0),
            coverage=(float(cov) if isinstance(cov, (int, float))
                      else None),
            overhead_share=row.get("overhead_share"))
        # bench computes worst over the O constituents only (the probe's
        # sync_wait is mostly the device computing, not overhead) —
        # prefer its stamp over the generic recomputation
        if row.get("overhead_worst_phase") in DISPATCH_PHASES:
            out["worst_phase"] = row["overhead_worst_phase"]
            if isinstance(row.get("overhead_worst_share"), (int, float)):
                out["worst_share"] = float(row["overhead_worst_share"])
        rows.append(out)
    return {"report": OVERHEAD_REPORT_TAG, "v": 1, "files": [path],
            "load_errors": [], "rows": rows}


def compare_overhead(new: dict, baseline: dict,
                     threshold: float = 1.5) -> dict:
    """The phase-SHARE regression gate (`trace report --overhead
    --baseline OLD`): one row per (program, phase) present in both
    reports. Every dispatch phase is overhead ROADMAP item 3 exists to
    shrink — better-smaller, so the ratio is new_share/old_share (the
    data/serve share-gate convention) and a regression is a ratio past
    `threshold`, UNLESS the new run's absolute phase total is
    sub-millisecond (`OVERHEAD_SUBMS_EXEMPT_S`: at that scale the
    numerator is scheduler noise). Returns the {"threshold", "rows",
    "regressions"} shape every other gate shares; cli/trace.py turns
    regressions into exit 3."""
    new_rows = {r["program"]: r for r in new.get("rows") or []
                if r.get("phases")}
    old_rows = {r["program"]: r for r in baseline.get("rows") or []
                if r.get("phases")}
    rows, regressions = [], []
    for program in sorted(set(new_rows) & set(old_rows)):
        np_, op = new_rows[program]["phases"], old_rows[program]["phases"]
        for phase in DISPATCH_PHASES:
            if phase not in np_ or phase not in op:
                continue
            old_v, new_v = op[phase]["share"], np_[phase]["share"]
            if old_v > 0:
                ratio = new_v / old_v
            else:
                ratio = math.inf if new_v > 0 else 1.0
            exempt = np_[phase]["total_s"] < OVERHEAD_SUBMS_EXEMPT_S
            row = {"program": program, "phase": phase,
                   "baseline_share": old_v, "new_share": new_v,
                   "ratio": ratio,
                   "regressed": ratio > threshold and not exempt}
            rows.append(row)
            if row["regressed"]:
                regressions.append(row)
    return {"threshold": threshold, "rows": rows,
            "regressions": regressions}


def format_overhead_report(report: dict) -> str:
    lines = [f"dispatch overhead report: {len(report['rows'])} program(s)"]
    for row in report["rows"]:
        if row.get("note"):
            lines.append(f"  {row['program']:<16} {row['note']}")
            continue
        cov = row.get("coverage")
        cov_txt = f"{cov:.0%}" if isinstance(cov, (int, float)) else "n/a"
        share_txt = ""
        if isinstance(row.get("overhead_share"), (int, float)):
            share_txt = f"  overhead_share={row['overhead_share']:.0%}"
        lines.append(f"  {row['program']:<16} window {row['window_s']:.4f}s"
                     f" over {row['steps']} step(s), coverage {cov_txt}"
                     f"{share_txt}")
        for phase in DISPATCH_PHASES:
            st = row["phases"].get(phase)
            if st:
                lines.append(f"    {phase:<16} {st['total_s']:>10.4f}s  "
                             f"{st['share']:>7.1%}")
        if row.get("worst_phase"):
            lines.append(f"    worst phase: {row['worst_phase']} "
                         f"({row['worst_share']:.1%} of window)")
    if not report["rows"]:
        lines.append("  (no dispatch records — not a --profile_dispatch "
                     "run or stamped artifact?)")
    return "\n".join(lines)


def format_compare_overhead(diff: dict) -> str:
    lines = [f"overhead baseline comparison (gate: share ratio > "
             f"{diff['threshold']:g}x):"]
    for row in diff["rows"]:
        verdict = "REGRESSION" if row["regressed"] else "ok"
        ratio = ("inf" if math.isinf(row["ratio"])
                 else f"{row['ratio']:.2f}x")
        lines.append(f"  {row['program']:<16} {row['phase']:<16} "
                     f"{row['baseline_share']:.1%} -> "
                     f"{row['new_share']:.1%}  ({ratio})  {verdict}")
    if not diff["rows"]:
        lines.append("  (no program/phase overlaps baseline — "
                     "nothing gated)")
    n = len(diff["regressions"])
    verdict = f"FAIL — {n} phase share(s) past threshold" if n else "PASS"
    lines.append(f"phase-share gate: {verdict}")
    return "\n".join(lines)
