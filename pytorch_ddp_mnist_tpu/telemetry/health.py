"""Training-health watchdog: rolling detectors over the values the loop
already fetches, severity-leveled `health` events, and a rescue policy.

Everything before this module was post-hoc: a NaN'd loss, an exploding
gradient, or a silently collapsing throughput is only discoverable after
the process exits and someone reads the trace (PRs 2-3's read side).
Production-scale training monitors these signals LIVE (the characterization
regime of arXiv:1810.11112) — and the watchdog does it without buying new
host syncs, the invariant the telemetry layer was built on:

  * every detector consumes values the loop ALREADY materializes on host —
    the once-per-epoch (or once-per-checkpoint-chunk) loss fetch, the
    epoch wall timers, and (opt-in) the health auxiliary vector the train
    step folds into its outputs (`device_health_aux` below: global grad
    norm + finite flag + param norm, computed in-program and fetched WITH
    the losses — zero extra per-step host syncs, pinned by test);
  * detectors are rolling EWMAs / windows, constant memory at any run
    length: loss spike, NaN/Inf, grad-norm explosion, update-to-param
    ratio drift, throughput collapse, and straggler drift (the online
    form of `analysis.skew` — the same spread/mean math the offline
    cross-process report uses, applied to a rolling window of this
    process's own per-step times);
  * every firing emits a `health` point into the event trace, an entry
    into the flight recorder, and `health.*` registry metrics (counters
    per detector, worst-severity gauge) — so the live `/metrics` endpoint
    (`telemetry/prom.py`), the post-hoc trace, and a post-mortem dump all
    tell the same story;
  * policy decides what a FATAL signal (non-finite loss/grads) does:
    `warn` logs, `checkpoint-and-warn` additionally hands the last
    known-good state to an `on_fatal` callback (cli/train wires it to an
    immediate `ckpt_manager` save — the run keeps an intact pre-NaN
    checkpoint even when the regular cadence would have missed it), and
    `abort` dumps the flight ring and raises `TrainingHealthError`.

The module is numpy + stdlib at import time (jax is imported only inside
`device_health_aux`, which builds device-side program fragments), so the
watchdog is constructible anywhere the registry is.
"""

from __future__ import annotations

import collections
import sys
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

import numpy as np

from .analysis import skew
from .events import get_tracer
from .registry import MetricsRegistry, get_registry
from . import flight

SEVERITIES = ("info", "warn", "fatal")
_SEVERITY_LEVEL = {"info": 0, "warn": 1, "fatal": 2}
POLICIES = ("warn", "checkpoint-and-warn", "abort")
DETECTORS = ("nan", "loss_spike", "grad_norm", "update_ratio",
             "throughput", "straggler")

# Layout of the per-step health auxiliary vector `device_health_aux`
# returns and the health-enabled train steps fold into their outputs.
AUX_FIELDS = ("grad_norm", "finite", "param_norm")


class TrainingHealthError(Exception):
    """A fatal health signal under the `abort` policy. Deliberately NOT a
    RuntimeError: the outage-retry machinery triages RuntimeErrors for
    backend-loss signatures, and a diverged model is not an outage —
    retrying would re-diverge."""


@dataclass
class HealthConfig:
    """Detector thresholds + the fatal-signal policy. The defaults are
    deliberately loose — a watchdog that cries wolf gets disabled; every
    band is a knob because every workload's 'normal' differs."""
    policy: str = "warn"
    # loss spike: max finite per-step loss > ratio x the EWMA of chunk
    # mean losses (armed after `warmup` observations)
    loss_spike_ratio: float = 4.0
    # grad-norm explosion: chunk max grad norm > ratio x its EWMA
    grad_norm_ratio: float = 10.0
    # update-to-param ratio lr*|g|/|p| outside [lo, hi]: the classic
    # "learning rate is effectively zero / is destroying the params" band
    update_ratio_band: Tuple[float, float] = (1e-9, 1e-1)
    # throughput collapse: imgs/s below ratio x its EWMA
    throughput_collapse_ratio: float = 0.3
    # straggler drift: skew (spread/mean, analysis.skew) of the rolling
    # per-step-time window above this percentage
    straggler_skew_pct: float = 75.0
    straggler_window: int = 8
    ewma_alpha: float = 0.3
    # ratio detectors stay silent for the first N observations: the EWMA
    # needs a baseline before "4x the baseline" means anything (step-1
    # loss IS the spike otherwise)
    warmup: int = 3

    def __post_init__(self):
        if self.policy not in POLICIES:
            raise ValueError(f"policy must be one of {POLICIES}; "
                             f"got {self.policy!r}")
        lo, hi = self.update_ratio_band
        if not 0 < lo < hi:
            raise ValueError(f"update_ratio_band must be 0 < lo < hi; "
                             f"got {self.update_ratio_band}")


@dataclass
class HealthEvent:
    """One detector firing. `value`/`threshold` are the number that fired
    and the bound it crossed; `step` is the global step at the END of the
    observation window (detection granularity is the fetch granularity —
    the event says 'within the window ending here')."""
    detector: str
    severity: str
    value: float
    threshold: float
    message: str
    epoch: int
    step: int


class _EWMA:
    """Exponentially weighted mean with an observation count (for warmup
    gating). `baseline()` is the value BEFORE the current observation is
    folded in — a spike must not dilute the bound it is tested against."""

    def __init__(self, alpha: float):
        self.alpha = float(alpha)
        self.value: Optional[float] = None
        self.n = 0

    def baseline(self) -> Optional[float]:
        return self.value

    def update(self, x: float) -> None:
        x = float(x)
        self.value = (x if self.value is None
                      else self.alpha * x + (1 - self.alpha) * self.value)
        self.n += 1


class Watchdog:
    """The live monitor. One per process; `observe()` at every point the
    loop already fetched a chunk of per-step losses (epoch end in the
    streaming loop, checkpoint-chunk boundaries in the scanned loop).

    `on_fatal(stash)` is the checkpoint-and-warn rescue hook: called with
    the last known-good stash `{"params", "key" (raw key words), "epoch",
    "offset", "step"}` when a fatal signal fires. The stash is refreshed
    (host copies) at every HEALTHY observation — only under the
    checkpoint-and-warn policy, since it costs one params D2H copy per
    observation; the other policies never touch device state.
    """

    def __init__(self, config: Optional[HealthConfig] = None, *,
                 registry: Optional[MetricsRegistry] = None,
                 lr: Optional[float] = None,
                 on_fatal: Optional[Callable[[dict], None]] = None,
                 rank: int = 0,
                 log: Callable[[str], None] = None):
        self.config = config or HealthConfig()
        self.registry = registry if registry is not None else get_registry()
        self.lr = lr
        self.on_fatal = on_fatal
        self.rank = int(rank)
        self._log = log or (lambda m: print(m, file=sys.stderr, flush=True))
        self._loss_ewma = _EWMA(self.config.ewma_alpha)
        self._gnorm_ewma = _EWMA(self.config.ewma_alpha)
        self._tput_ewma = _EWMA(self.config.ewma_alpha)
        self._step_times: "collections.deque[float]" = collections.deque(
            maxlen=self.config.straggler_window)
        self._n_timed = 0      # timing observations seen (straggler warmup)
        self._last_good: Optional[dict] = None
        self.events: List[HealthEvent] = []
        # eager metric creation: the /metrics endpoint shows the health
        # surface (worst severity 0 = healthy) from the first scrape, not
        # only after something already went wrong
        self._events_total = self.registry.counter("health.events_total")
        self._worst = self.registry.gauge("health.worst_severity_level")
        self._worst.set(0)
        self._worst_level = 0
        self._last_loss = self.registry.gauge("health.last_loss")
        self._last_gnorm = self.registry.gauge("health.grad_norm")
        self._last_ratio = self.registry.gauge("health.update_ratio")
        self._last_tput = self.registry.gauge("health.imgs_per_sec")

    # -- rescue stash ------------------------------------------------------

    def seed_good(self, state, *, epoch: int, offset: int, step: int) -> None:
        """Record the starting state as known-good, so a fatal signal in
        the very first observation window still has something intact to
        rescue (the initial params — epoch 0 offset 0, or the restored
        resume position). Only when the rescue hook exists (rank 0 under
        checkpoint-and-warn): other ranks must not pay the params copy
        for a save they will never perform."""
        if self.config.policy == "checkpoint-and-warn" \
                and self.on_fatal is not None:
            self._stash(state, epoch=epoch, offset=offset, step=step)

    def _stash(self, state, *, epoch: int, offset: int, step: int) -> None:
        import jax
        # the int8 strategy's error-feedback residual is part of the
        # resume state (PARITY.md: crash->resume continues the exact
        # quantization-error accounting), so the rescue carries it too.
        # It is dp-SHARDED device state: in a multi-host world rank 0
        # (the only rank that stashes) cannot fetch the other hosts'
        # shards without a collective, so the stash degrades to
        # params+key there — a rescue resume reseeds a zero residual,
        # losing at most one step's quantization error.
        resid = getattr(state, "resid", None)
        if resid is not None and getattr(resid, "is_fully_addressable",
                                         True):
            resid = np.asarray(resid)
        else:
            resid = None
        self._last_good = {
            "params": jax.tree_util.tree_map(np.asarray, state.params),
            "key": np.asarray(jax.random.key_data(state.key)),
            "resid": resid,
            "epoch": int(epoch), "offset": int(offset), "step": int(step),
        }

    # -- the one entry point ----------------------------------------------

    def observe(self, losses, *, epoch: int, step: int,
                state=None, aux=None,
                ckpt_epoch: Optional[int] = None,
                ckpt_offset: Optional[int] = None,
                dt_s: Optional[float] = None,
                imgs: Optional[int] = None) -> List[HealthEvent]:
        """Run every detector over one observation window.

        `losses`: the window's per-step mean losses, already on host (the
        fetch the loop performs anyway). `aux`: optional (n, 3) array of
        per-step `AUX_FIELDS` vectors from a health-enabled step. `state`:
        the live TrainState at the window's end, stashed as known-good
        when healthy (checkpoint-and-warn only); `ckpt_epoch`/
        `ckpt_offset` are the positions a checkpoint of that state must
        record (`step_ckpt_positions` semantics). `dt_s`/`imgs` feed the
        throughput and straggler detectors. Returns (and records) the
        events that fired; raises TrainingHealthError on a fatal signal
        under the abort policy."""
        cfg = self.config
        losses = np.asarray(losses, np.float64).ravel()
        fired: List[HealthEvent] = []

        def fire(detector, severity, value, threshold, message):
            fired.append(HealthEvent(detector, severity, float(value),
                                     float(threshold), message,
                                     int(epoch), int(step)))

        finite_mask = np.isfinite(losses)
        aux_bad = False
        gnorm = ratio = None
        if aux is not None:
            aux = np.asarray(aux, np.float64).reshape(-1, len(AUX_FIELDS))
            aux_bad = bool((aux[:, 1] < 1.0).any()
                           or not np.isfinite(aux[:, 0]).all())
            g_fin = aux[np.isfinite(aux[:, 0]), 0]
            if g_fin.size:
                gnorm = float(g_fin.max())
            if self.lr is not None:
                pn = aux[:, 2]
                ok = np.isfinite(aux[:, 0]) & np.isfinite(pn) & (pn > 0)
                if ok.any():
                    ratio = float((self.lr * aux[ok, 0] / pn[ok]).max())

        # 1. NaN/Inf — the one FATAL signal: a non-finite loss or a step
        # whose in-program finite-check tripped
        if not finite_mask.all() or aux_bad:
            bad = int((~finite_mask).sum())
            what = (f"{bad}/{losses.size} non-finite per-step losses"
                    if bad else "step finite-check tripped (grads/params)")
            fire("nan", "fatal", bad if bad else 1.0, 0.0,
                 f"non-finite training signal: {what}")

        # 2. loss spike (finite values only; a NaN is detector 1's job)
        base = self._loss_ewma.baseline()
        if finite_mask.any():
            mx = float(losses[finite_mask].max())
            if (base is not None and self._loss_ewma.n >= cfg.warmup
                    and base > 0 and mx > cfg.loss_spike_ratio * base):
                fire("loss_spike", "warn", mx, cfg.loss_spike_ratio * base,
                     f"loss {mx:.4g} > {cfg.loss_spike_ratio:g}x rolling "
                     f"mean {base:.4g}")
            self._loss_ewma.update(float(losses[finite_mask].mean()))
            self._last_loss.set(float(losses[finite_mask][-1]))

        # 3. grad-norm explosion
        if gnorm is not None:
            gbase = self._gnorm_ewma.baseline()
            if (gbase is not None and self._gnorm_ewma.n >= cfg.warmup
                    and gbase > 0 and gnorm > cfg.grad_norm_ratio * gbase):
                fire("grad_norm", "warn", gnorm, cfg.grad_norm_ratio * gbase,
                     f"grad norm {gnorm:.4g} > {cfg.grad_norm_ratio:g}x "
                     f"rolling mean {gbase:.4g}")
            self._gnorm_ewma.update(gnorm)
            self._last_gnorm.set(gnorm)

        # 4. update-to-param ratio drift
        if ratio is not None:
            lo, hi = cfg.update_ratio_band
            if not lo <= ratio <= hi:
                edge = hi if ratio > hi else lo
                fire("update_ratio", "warn", ratio, edge,
                     f"update/param ratio {ratio:.3g} outside "
                     f"[{lo:g}, {hi:g}]")
            self._last_ratio.set(ratio)

        # 5. throughput collapse + 6. straggler drift (online skew)
        if dt_s and imgs and dt_s > 0 and losses.size:
            tput = imgs / dt_s
            tbase = self._tput_ewma.baseline()
            if (tbase is not None and self._tput_ewma.n >= cfg.warmup
                    and tput < cfg.throughput_collapse_ratio * tbase):
                fire("throughput", "warn", tput,
                     cfg.throughput_collapse_ratio * tbase,
                     f"throughput {tput:.0f} img/s < "
                     f"{cfg.throughput_collapse_ratio:g}x rolling mean "
                     f"{tbase:.0f}")
            self._tput_ewma.update(tput)
            self._last_tput.set(tput)
            # straggler window opens AFTER warmup: the first observations
            # carry XLA compile time, which would read as a skew spike of
            # the run's own ramp-up, not of a sick rank
            self._n_timed += 1
            if self._n_timed > cfg.warmup:
                self._step_times.append(dt_s / losses.size)
            if len(self._step_times) >= max(4, cfg.straggler_window // 2):
                _, skew_pct = skew(self._step_times)
                if skew_pct > cfg.straggler_skew_pct:
                    fire("straggler", "warn", skew_pct,
                         cfg.straggler_skew_pct,
                         f"per-step time skew {skew_pct:.0f}% of mean over "
                         f"the last {len(self._step_times)} windows")

        self._publish(fired)
        fatal = [e for e in fired if e.severity == "fatal"]
        healthy = not fatal
        if healthy and state is not None and self.on_fatal is not None \
                and cfg.policy == "checkpoint-and-warn":
            self._stash(state,
                        epoch=epoch + 1 if ckpt_epoch is None else ckpt_epoch,
                        offset=0 if ckpt_offset is None else ckpt_offset,
                        step=step)
        if fatal:
            self._act_on_fatal(fatal[0])
        return fired

    # -- recording + policy ------------------------------------------------

    def _publish(self, fired: List[HealthEvent]) -> None:
        if not fired:
            return
        tracer = get_tracer()
        for e in fired:
            self.events.append(e)
            self._events_total.inc()
            self.registry.counter(f"health.fired.{e.detector}").inc()
            level = _SEVERITY_LEVEL[e.severity]
            if level > self._worst_level:
                self._worst_level = level
                self._worst.set(level)
            tracer.point("health", detector=e.detector, severity=e.severity,
                         value=e.value, threshold=e.threshold,
                         message=e.message, epoch=e.epoch, step=e.step)
            flight.record("health", detector=e.detector, severity=e.severity,
                          value=e.value, threshold=e.threshold,
                          rank=self.rank, epoch=e.epoch, step=e.step)
            self._log(f"[health] rank{self.rank} {e.severity.upper()} "
                      f"{e.detector} at epoch {e.epoch} step {e.step}: "
                      f"{e.message}")

    def _act_on_fatal(self, event: HealthEvent) -> None:
        policy = self.config.policy
        if policy == "checkpoint-and-warn" and self.on_fatal is not None:
            if self._last_good is not None:
                stash = self._last_good
                self._log(f"[health] rank{self.rank} rescue: saving last "
                          f"known-good state (step {stash['step']}, epoch "
                          f"{stash['epoch']}, offset {stash['offset']})")
                try:
                    self.on_fatal(dict(stash))
                except Exception as e:  # noqa: BLE001 — the rescue hook
                    # must never turn a detection into a crash; the run's
                    # fate belongs to the policy, not the hook
                    flight.record("health_rescue_failed", error=str(e)[:500])
                    self._log(f"[health] rescue checkpoint failed "
                              f"(training continues): {e}")
        elif policy == "abort":
            flight.dump(reason=f"health abort: {event.detector} "
                               f"({event.message})")
            raise TrainingHealthError(
                f"fatal health signal ({event.detector} at epoch "
                f"{event.epoch} step {event.step}: {event.message}) under "
                f"--health abort")

    def snapshot(self) -> dict:
        """JSON-able verdict: worst severity + per-detector fire counts —
        the `/healthz` payload and the bench `health_summary` stamp."""
        return health_summary(self.registry)


def device_health_aux(loss, grads, params, *, axis_name=None):
    """Device-side fragment the health-enabled train steps fold into their
    program: `[global grad norm, finite flag, param norm]` as one f32
    3-vector, computed from values the step already holds — it rides the
    same dispatch and gets fetched WITH the epoch's losses (no extra host
    sync; the zero-sync test pins it).

    `axis_name` (non-pmean DDP strategies, which never materialize the
    averaged grads): the local grad sum-of-squares is pmean'd over the
    axis — sqrt(mean-of-local-sumsq), a scale-faithful proxy for the
    global norm (exact when replica grads agree; the pmean strategy
    computes the exact norm of the averaged grads instead)."""
    import jax
    import jax.numpy as jnp

    def _sumsq(tree):
        return sum(jnp.sum(jnp.square(leaf.astype(jnp.float32)))
                   for leaf in jax.tree_util.tree_leaves(tree))

    gn2 = _sumsq(grads)
    if axis_name is not None:
        gn2 = jax.lax.pmean(gn2, axis_name)
    pn2 = _sumsq(params)
    gn, pn = jnp.sqrt(gn2), jnp.sqrt(pn2)
    finite = (jnp.isfinite(loss) & jnp.isfinite(gn)
              & jnp.isfinite(pn)).astype(jnp.float32)
    return jnp.stack([gn, finite, pn])


def health_summary(registry: Optional[MetricsRegistry] = None) -> dict:
    """{fired: {detector: count}, worst_severity} read back from the
    `health.*` registry metrics — the shape bench.py stamps into artifact
    lines (a failed round then shows WHAT degraded, not just rc != 0).
    A process that never ran a watchdog reads as `{fired: {},
    worst_severity: None}`.

    When the process hosts a serve replica fleet (serve/fleet.py
    publishes `serve.fleet.replicas` / `serve.fleet.healthy` gauges into
    the same registry), the summary carries a `fleet` section —
    `{replicas, healthy, degraded}` — so /healthz and the bench artifact
    see a quarantined-replica fleet as degraded, not silently fine."""
    snap = (registry if registry is not None else get_registry()).snapshot()
    prefix = "health.fired."
    fired = {name[len(prefix):]: v for name, v in snap["counters"].items()
             if name.startswith(prefix) and v}
    level = snap["gauges"].get("health.worst_severity_level")
    worst = None
    if level is not None:
        worst = {v: k for k, v in _SEVERITY_LEVEL.items()}.get(int(level))
        if int(level) == 0:
            worst = "ok"
    out = {"fired": fired, "worst_severity": worst}
    replicas = snap["gauges"].get("serve.fleet.replicas")
    if replicas is not None:
        healthy = int(snap["gauges"].get("serve.fleet.healthy") or 0)
        out["fleet"] = {"replicas": int(replicas), "healthy": healthy,
                        "degraded": healthy < int(replicas)}
    return out
