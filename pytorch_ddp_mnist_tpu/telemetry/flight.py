"""Flight recorder: a bounded ring buffer of recent wireup/serve events,
flushed to disk on failure so a dead backend leaves a structured
post-mortem instead of a log tail.

Every hardware bench round that died so far (BENCH_r01-r05) ended in an
opaque `backend_unavailable` line: the probe/retry loop in
`parallel/wireup.py` printed its progress to stderr, which the artifact
never captured. The recorder closes that gap without becoming a logger:

  * `record(kind, **fields)` appends one timestamped entry to a
    fixed-capacity deque — constant memory at any rate, the oldest entries
    drop first (with an exact `dropped` count), and nothing touches disk
    on the happy path;
  * producers are the paths that only matter when things go wrong:
    `wait_for_backend`'s probe/retry loop (every error, hang, health poll
    and recovery), `serve/admission.py`'s reject path (incl. the
    predicted-p99 SLO boundary, with the predicted value that fired), and
    `serve/tracing.py`'s drain-time slowest-request exemplars (the full
    stage trees of the worst tails a killed server ever served);
  * `dump(reason)` flushes the ring as one JSON file — into the configured
    dump dir (`set_dump_dir`, wired to `--telemetry DIR` by cli/train),
    else `$PDMT_FLIGHT_DIR`, else the system temp dir — and returns the
    path, which `bench.py` stamps into its `backend_unavailable` artifact
    line so failed rounds are diagnosable from the JSON alone;
  * `install_sigterm_flush()` chains a dump in front of the existing
    SIGTERM disposition, so a caller-killed run (the bench driver's
    timeout pattern) still leaves the post-mortem.

Dumping is deliberately infallible-by-contract: any write failure returns
None rather than raising — the recorder must never turn a primary failure
into a secondary crash. Pure stdlib; safe to import from anywhere
(including `parallel/wireup.py`, which must not pull jax at import time).
"""

from __future__ import annotations

import collections
import json
import os
import signal
import tempfile
import threading
import time
from typing import List, Optional

DEFAULT_CAPACITY = 256
# v2: every entry carries a `rank` field stamped at RECORD time (merged
# multi-rank dumps are attributable; previously only some wireup entries
# carried process identity). Backward-compatible: v1 dumps stay readable,
# and the checker (scripts/check_telemetry.py) only enforces the rank
# field on v2 payloads.
_SCHEMA = 2


def _env_rank() -> int:
    """Pre-wireup default: the launcher's $RANK (the env wireup chain's
    spelling), else 0 — the same seed faultpoints uses. cli/train rebinds
    the real process index after rendezvous via `set_rank`."""
    try:
        return int(os.environ.get("RANK", "0"))
    except ValueError:
        return 0


class FlightRecorder:
    """The ring. One per process (module-level singleton below); thread-safe
    — probe threads, the asyncio serve loop, and signal handlers all
    record."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1; got {capacity}")
        self.capacity = int(capacity)
        self._entries: "collections.deque[dict]" = collections.deque(
            maxlen=self.capacity)
        # RLock, not Lock: a SIGTERM handler dumps the ring from the main
        # thread, and the signal can land while that same thread is inside
        # record()'s critical section — a non-reentrant lock would deadlock
        # the dying process instead of writing its post-mortem.
        self._lock = threading.RLock()
        self._recorded = 0  # total ever recorded (dropped = this - len)
        self.dump_dir: Optional[str] = None
        self.rank = _env_rank()

    def record(self, kind: str, **fields) -> None:
        entry = {"t_wall": time.time(), "t_mono": time.perf_counter(),
                 "kind": str(kind)}
        entry.update(fields)
        # rank stamped at record time (a producer that knows better — the
        # fault injector's rank-gated specs — passes its own and wins)
        if "rank" not in entry:
            entry["rank"] = self.rank
        with self._lock:
            entry["seq"] = self._recorded
            self._recorded += 1
            self._entries.append(entry)

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [dict(e) for e in self._entries]

    @property
    def recorded(self) -> int:
        return self._recorded

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._recorded - len(self._entries)

    def _resolve_dir(self) -> str:
        return (self.dump_dir or os.environ.get("PDMT_FLIGHT_DIR")
                or tempfile.gettempdir())

    def dump(self, reason: str, path: Optional[str] = None) -> Optional[str]:
        """Flush the ring to `path` (default: `flight.<pid>.json` under the
        resolved dump dir) and return the written path; None when nothing
        was ever recorded or the write fails (a post-mortem writer must
        never crash the path that is already failing). Atomic via
        write-then-replace: a reader (the bench driver following the
        artifact stamp) never sees a torn file."""
        entries = self.snapshot()
        if not entries:
            return None
        payload = {
            "v": _SCHEMA,
            "reason": str(reason),
            "pid": os.getpid(),
            "rank": self.rank,
            "dumped_t_wall": time.time(),
            "recorded": self._recorded,
            "dropped": self._recorded - len(entries),
            "entries": entries,
        }
        try:
            if path is None:
                out_dir = self._resolve_dir()
                os.makedirs(out_dir, exist_ok=True)
                path = os.path.join(out_dir, f"flight.{os.getpid()}.json")
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(payload, f, default=str)
                f.write("\n")
            os.replace(tmp, path)
            return path
        except OSError:
            return None


_RECORDER = FlightRecorder()


def get_flight_recorder() -> FlightRecorder:
    return _RECORDER


def record(kind: str, **fields) -> None:
    """Append one entry to the process-wide ring (constant cost, no I/O)."""
    _RECORDER.record(kind, **fields)


def set_dump_dir(path: Optional[str]) -> None:
    """Route dumps next to the JSONL trace (cli/train wires `--telemetry
    DIR` here, so the post-mortem lands with the run's other evidence)."""
    _RECORDER.dump_dir = path


def set_rank(rank: int) -> None:
    """Late rank binding, the faultpoints.set_rank twin: cli/train calls
    this after wireup so every later entry is stamped with the real
    process index (pre-wireup entries carry the $RANK-seeded default)."""
    _RECORDER.rank = int(rank)


def dump(reason: str, path: Optional[str] = None) -> Optional[str]:
    return _RECORDER.dump(reason, path)


_sigterm_installed = False
# signal.signal only works from the main thread, but nothing stops two
# threads RACING the installed-flag check (each would chain the other's
# handler — the "never a loop" promise breaks); the lock makes the
# check-then-install atomic (statics rule MUT002).
_SIGTERM_LOCK = threading.Lock()


def install_sigterm_flush() -> bool:
    """Chain a flight dump in front of the current SIGTERM disposition.
    Returns False (and installs nothing) off the main thread or where
    signals are unsupported; repeat installs are no-ops (one chain link,
    never a loop)."""
    global _sigterm_installed
    with _SIGTERM_LOCK:
        if _sigterm_installed:
            return True

        try:
            prev = signal.getsignal(signal.SIGTERM)

            def _flush_and_chain(signum, frame):
                _RECORDER.dump(reason="SIGTERM")
                if callable(prev):
                    prev(signum, frame)
                elif prev is signal.SIG_IGN:
                    # the run was launched ignoring SIGTERM (supervisor
                    # choice): preserve that — dump, keep living
                    return
                else:
                    # SIG_DFL (or an unknowable non-Python handler, prev is
                    # None): restore the default disposition and re-deliver,
                    # so the process still dies by SIGTERM (exit status
                    # intact)
                    signal.signal(signum, signal.SIG_DFL)
                    os.kill(os.getpid(), signum)

            signal.signal(signal.SIGTERM, _flush_and_chain)
        except (ValueError, OSError):  # non-main thread / unsupported
            return False               # platform
        _sigterm_installed = True
        return True
