"""Performance ledger: every committed artifact, one direction-aware history.

The repo measures everything but — before this module — remembered nothing
across rounds: BENCH_r01–r05, MULTICHIP_r01–r08, COST_r01, SERVE_r01,
INPUT_r01 and the bench_matrix artifacts carry five generations of schema,
and every regression gate was a pairwise `--baseline OLD` diff that could
only see one step back. This module is the repo's long-term memory:

  * `ingest` — load every committed artifact generation (the bare r01
    `parsed` wrapper, legacy MULTICHIP ok-bit smokes, r06+ `strategies`
    rows, COST/SERVE/INPUT reports, bench_matrix cells) and normalize each
    metric into one canonical row: series key (metric, variant, model,
    param_scale, n_devices, per_chip_batch, backend), a finite value, a
    declared direction (higher_better / lower_better), the run ordinal and
    the source artifact. Legacy defaults are pinned by the SAME
    `analysis.normalize_workload` rule the PR 7 efficiency-gate labels use
    (un-stamped rows = mlp x1), so the ledger and the gate can never
    disagree about which rows are comparable. Unknown schemas and unknown
    future `schema_version`s fail BY NAME — never silently drop.
  * `trend` / `gate` — per-series robust history stats: median + MAD band
    over the last K runs plus consecutive-worse streaks. A regression is a
    direction-aware move past `threshold` vs the history band, not just
    the previous artifact — the pairwise gates are the 1-point degenerate
    case (history of one -> MAD 0 -> the band collapses to the old
    pairwise ratio test).
  * `report` / `render_markdown` — the per-series trajectory table
    (first -> latest, best, current-vs-best %, streak) that replaces the
    hand-maintained before/after tables in docs/PERF.md.

Pure stdlib (json/math/os/re) by the analysis.py contract: the ledger must
run wherever the artifacts land, including hosts without jax installed.
Front door: `python -m pytorch_ddp_mnist_tpu ledger` (cli/ledger.py).
"""

from __future__ import annotations

import json
import math
import os
import re
from typing import Dict, List, Optional, Tuple

from .analysis import (LEDGER_DIRECTIONS, WORKLOAD_DEFAULTS,
                       normalize_workload)

HIGHER, LOWER = LEDGER_DIRECTIONS  # ("higher_better", "lower_better")

# Artifacts written from this round on stamp `schema_version`; absent means
# the artifact predates the ledger and is grandfathered as generation 1.
# Versions ABOVE this are someone else's future: refuse by name rather
# than guess at fields that may have changed meaning.
SCHEMA_VERSION = 2

# Default trend-gate knobs (cli/ledger.py exposes both as flags). The 1.5
# ratio matches the repo's pairwise step-time/efficiency gates; the window
# bounds how much history the band is computed over.
DEFAULT_THRESHOLD = 1.5
DEFAULT_WINDOW = 5
# The MAD band multiplier: a point must fall outside center +/- 3*MAD on
# the WORSE side (direction-aware) before the ratio test may fire, so a
# noisy-but-stable series doesn't gate on its own jitter. With a 1-point
# history MAD is 0 and the band collapses — the pairwise degenerate case.
MAD_BAND = 3.0

# Committed-artifact filename shapes. discover() matches exactly these —
# BASELINE.json and friends are prose-bearing configs, not metric
# artifacts, and must not trip the unknown-schema error.
ARTIFACT_GLOBS = ("BENCH_r*.json", "MULTICHIP_r*.json", "COST_r*.json",
                  "SERVE_r*.json", "INPUT_r*.json", "bench_matrix_r*.json")

_RUN_ORD_RE = re.compile(r"_r(\d+)\.json$")
_ACCURACY_RE = re.compile(r"^mnist_\d+epoch_test_accuracy$")

# -- the direction registry (docs/OBSERVABILITY.md §Performance ledger) --
# Every ledger metric declares which way is better ONCE, here. A bench.py
# line whose metric name is missing from these tables fails ingestion by
# name ("teach telemetry/ledger.py its direction") — a metric without a
# direction cannot be trend-gated and must not silently join the history.

# stdout bench-line metric -> (ledger metric, direction). Covers both the
# BENCH_r01 driver-wrapped `parsed` form and bare stamped lines.
BENCH_LINE_METRICS = {
    "mnist_train_images_per_sec_per_chip":
        ("bench.train_images_per_sec_per_chip", HIGHER),
    "mnist_ddp_train_images_per_sec_per_chip":
        ("bench.ddp_train_images_per_sec_per_chip", HIGHER),
    "mnist_eval_images_per_sec_per_chip":
        ("bench.eval_images_per_sec_per_chip", HIGHER),
    "mnist_serve_requests_per_sec": ("serve.requests_per_sec", HIGHER),
    "mnist_netcdf_stream_images_per_sec":
        ("input.netcdf_stream_images_per_sec", HIGHER),
    "mnist_input_pipeline_batches_per_sec":
        ("input.batches_per_sec", HIGHER),
}

# MULTICHIP `strategies` row field -> (ledger metric, direction). Only
# these fields are measurements; the rest of a row (strategy, overlap,
# n_params, overhead_phases, ...) is configuration or structure.
STRATEGY_ROW_METRICS = {
    "images_per_sec": ("ddp.images_per_sec", HIGHER),
    "per_chip_images_per_sec": ("ddp.per_chip_images_per_sec", HIGHER),
    "scaling_efficiency_vs_1dev":
        ("ddp.scaling_efficiency_vs_1dev", HIGHER),
    "bytes_on_wire_per_step_per_device":
        ("ddp.bytes_on_wire_per_step_per_device", LOWER),
    "collective_s_p50": ("ddp.collective_s_p50", LOWER),
    "parity_max_rel_diff_vs_pmean":
        ("ddp.parity_max_rel_diff_vs_pmean", LOWER),
    "parity_max_abs_diff_vs_pmean":
        ("ddp.parity_max_abs_diff_vs_pmean", LOWER),
    "analytic_efficiency": ("ddp.analytic_efficiency", HIGHER),
    "journal_overhead_share": ("ddp.journal_overhead_share", LOWER),
    "overhead_share": ("ddp.overhead_share", LOWER),
    "overhead_coverage": ("ddp.overhead_coverage", HIGHER),
    "overhead_worst_share": ("ddp.overhead_worst_share", LOWER),
}

# INPUT artifact legacy/pipeline sub-dict field -> (metric, direction).
INPUT_VARIANT_METRICS = {
    "batches_per_sec": ("input.batches_per_sec", HIGHER),
    "images_per_sec": ("input.images_per_sec", HIGHER),
    "data_wait_share_p50": ("input.data_wait_share_p50", LOWER),
    "data_wait_share_p95": ("input.data_wait_share_p95", LOWER),
}

# Serve-bench robustness companions: bench.py --mode serve stamps these
# ALONGSIDE the headline requests/sec line (PR 20); absent on pre-fleet
# artifacts, so old rounds contribute no rows and the pinned ingest
# counts hold. availability is the kept-promise fraction
# (completed/(completed+failed) over ADMITTED requests); retried_requests
# counts fleet failovers (lower is better — each one is a replica
# failure a request had to ride out); reloads counts hot checkpoint
# swaps served without downtime.
SERVE_ROBUSTNESS_METRICS = {
    "availability": ("serve.availability", HIGHER),
    "retried_requests": ("serve.retried_requests", LOWER),
    "reloads": ("serve.reloads", HIGHER),
}

# Fixed-name metrics the generation loaders emit directly.
FIXED_METRICS = {
    "multichip.ok": HIGHER,
    "bench.test_accuracy": HIGHER,
    "cost.compile_count": LOWER,
    "cost.compile_s_total": LOWER,
    "cost.peak_hbm_bytes": LOWER,
    "cost.analytic_efficiency": HIGHER,
    "serve.max_sustained_qps": HIGHER,
    "serve.p50_ms": LOWER,
    "serve.p99_ms": LOWER,
    "serve.reject_rate": LOWER,
    "serve.qps_gain": HIGHER,
    "input.xla_compiles": LOWER,
    "matrix.images_per_sec_per_chip": HIGHER,
}


def metric_directions() -> Dict[str, str]:
    """The full metric -> direction registry, one flat view (docs + the
    smoke's family-coverage assert read this)."""
    out = dict(FIXED_METRICS)
    for table in (BENCH_LINE_METRICS, STRATEGY_ROW_METRICS,
                  INPUT_VARIANT_METRICS, SERVE_ROBUSTNESS_METRICS):
        for name, direction in table.values():
            out[name] = direction
    return out


class LedgerError(Exception):
    """An artifact the ledger refuses to ingest — unknown schema, unknown
    future schema_version, or a metric without a registered direction.
    Always names the offending path/field: fail by name, never drop."""


def run_ordinal(doc: dict, path: str) -> int:
    """The run ordinal a row sorts under: an explicit `run_ord` stamp
    (schema v2+), the driver wrapper's `n`, or the `_rNN` filename
    convention — in that precedence order; 0 when nothing claims one."""
    for key in ("run_ord", "n"):
        v = doc.get(key)
        if isinstance(v, int) and not isinstance(v, bool):
            return v
    m = _RUN_ORD_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else 0


def check_schema_version(doc: dict, path: str) -> int:
    """Grandfather version-absent artifacts as v1; refuse unknown FUTURE
    versions by name (a v3 artifact may have re-keyed its fields — better
    a loud error here than a silently wrong history)."""
    v = doc.get("schema_version")
    if v is None:
        return 1
    if isinstance(v, bool) or not isinstance(v, int):
        raise LedgerError(f"{path}: schema_version must be an int, got "
                          f"{v!r}")
    if v > SCHEMA_VERSION:
        raise LedgerError(
            f"{path}: schema_version {v} is newer than this ledger "
            f"understands (max {SCHEMA_VERSION}); update "
            f"telemetry/ledger.py before ingesting it")
    return v


def series_key(metric: str, variant: Optional[str], workload: dict,
               backend: Optional[str]) -> str:
    """One canonical, human-readable key per comparable series. Matching
    is STRICT: a row measured on an unknown backend (None) does not join
    a tpu-backend series — better two short honest series than one long
    lying one."""
    parts = [metric]
    if variant:
        parts.append(variant)
    parts.append(f"{workload['model']} x{workload['param_scale']}")
    if workload.get("n_devices") is not None:
        parts.append(f"{workload['n_devices']}dev")
    if workload.get("per_chip_batch") is not None:
        parts.append(f"b{workload['per_chip_batch']}")
    parts.append(backend if backend else "?")
    return "/".join(parts)


def _row(metric: str, direction: str, value: float, run_ord: int,
         source: str, workload: dict, backend: Optional[str],
         variant: Optional[str] = None,
         unit: Optional[str] = None) -> dict:
    if not (isinstance(value, (int, float)) and not isinstance(value, bool)
            and math.isfinite(value)):
        raise LedgerError(f"{source}: metric {metric!r} carries a "
                          f"non-finite value {value!r}")
    return {
        "series": series_key(metric, variant, workload, backend),
        "metric": metric, "variant": variant,
        "model": workload["model"],
        "param_scale": workload["param_scale"],
        "n_devices": workload.get("n_devices"),
        "per_chip_batch": workload.get("per_chip_batch"),
        "backend": backend, "value": float(value),
        "direction": direction, "run_ord": run_ord, "source": source,
        "unit": unit,
    }


def _bench_line_row(doc: dict, run_ord: int, source: str) -> dict:
    """One stdout bench line (bare, or the BENCH_rNN `parsed` payload)."""
    raw = doc.get("metric")
    if raw in BENCH_LINE_METRICS:
        metric, direction = BENCH_LINE_METRICS[raw]
    elif isinstance(raw, str) and _ACCURACY_RE.match(raw):
        metric, direction = "bench.test_accuracy", HIGHER
    else:
        raise LedgerError(
            f"{source}: bench metric {raw!r} has no registered direction; "
            f"teach telemetry/ledger.py (BENCH_LINE_METRICS) its direction "
            f"before it can join the history")
    return _row(metric, direction, doc.get("value"), run_ord, source,
                normalize_workload(doc), doc.get("backend"),
                unit=doc.get("unit"))


def _serve_robustness_rows(doc: dict, run_ord: int,
                           source: str) -> List[dict]:
    """Companion rows off a serve bench line (SERVE_ROBUSTNESS_METRICS):
    only the serve headline carries them, and only post-fleet artifacts
    stamp them — both absences are silent, not skips, so pre-fleet
    histories ingest unchanged."""
    if doc.get("metric") != "mnist_serve_requests_per_sec":
        return []
    wl = normalize_workload(doc)
    backend = doc.get("backend")
    rows = []
    for field, (metric, direction) in SERVE_ROBUSTNESS_METRICS.items():
        v = doc.get(field)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            rows.append(_row(metric, direction, v, run_ord, source,
                             wl, backend))
    return rows


# -- per-generation loaders: each returns (rows, skipped) ----------------

def _load_bench_line(doc: dict, run_ord: int,
                     source: str) -> Tuple[List[dict], List[dict]]:
    """A bare stamped stdout line. A null value with a recorded `error`
    (bench.py's _emit_backend_error shape) is a SKIP, same rule as the
    driver-wrapped failures."""
    if doc.get("value") is None:
        return [], [{"source": source, "reason":
                     doc.get("error") or "null value"}]
    return ([_bench_line_row(doc, run_ord, source)]
            + _serve_robustness_rows(doc, run_ord, source)), []


def _load_bench_wrapped(doc: dict, run_ord: int,
                        source: str) -> Tuple[List[dict], List[dict]]:
    """BENCH_rNN.json: the driver wrapper {n, cmd, rc, tail, parsed}.
    A failed round (parsed null / value null) is a SKIP with its recorded
    reason, not a zero — a backend that never ran is not a regression."""
    parsed = doc.get("parsed")
    if not isinstance(parsed, dict) or not isinstance(
            parsed.get("value"), (int, float)):
        reason = (parsed or {}).get("error") if isinstance(parsed, dict) \
            else None
        return [], [{"source": source, "reason":
                     reason or f"no parsed metric (rc={doc.get('rc')})"}]
    merged = dict(doc)
    merged.update(parsed)
    return ([_bench_line_row(merged, run_ord, source)]
            + _serve_robustness_rows(merged, run_ord, source)), []


def _load_multichip(doc: dict, run_ord: int,
                    source: str) -> Tuple[List[dict], List[dict]]:
    """MULTICHIP_rNN.json — both generations. Every one carries the ok
    bit (the 8-round health series); r06+ adds `strategies` rows. The ok
    bit is a HEALTH metric, not a workload measurement, so its series
    pins the default workload (splitting mnist-smoke ok from mlp-x8 ok
    would hide exactly the flakiness the series exists to show)."""
    backend = doc.get("backend")
    rows: List[dict] = []
    skipped: List[dict] = []
    if isinstance(doc.get("ok"), bool):
        wl = dict(WORKLOAD_DEFAULTS, n_devices=None, per_chip_batch=None)
        ndev = doc.get("n_devices")
        if isinstance(ndev, int) and not isinstance(ndev, bool):
            wl["n_devices"] = ndev
        rows.append(_row("multichip.ok", HIGHER,
                         1.0 if doc["ok"] else 0.0, run_ord, source, wl,
                         backend))
    for srow in doc.get("strategies") or []:
        if not isinstance(srow, dict):
            continue
        variant = str(srow.get("strategy", "?"))
        if srow.get("overlap"):
            variant += "+overlap"
        wl = normalize_workload(srow, doc)
        for field, (metric, direction) in STRATEGY_ROW_METRICS.items():
            v = srow.get(field)
            if v is None:
                continue
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                skipped.append({"source": source, "reason":
                                f"{variant}.{field} non-numeric: {v!r}"})
                continue
            rows.append(_row(metric, direction, v, run_ord, source, wl,
                             backend, variant=variant))
    return rows, skipped


def _load_cost(doc: dict, run_ord: int,
               source: str) -> Tuple[List[dict], List[dict]]:
    """COST_rNN.json (telemetry/costs.py `program_cost_report`): the
    compile/HBM budget summary plus per-program analytic efficiency."""
    wl = normalize_workload(
        {"per_chip_batch": doc.get("batch_per_device")}, doc)
    backend = doc.get("backend")
    summary = doc.get("summary") or {}
    rows: List[dict] = []
    for field, metric in (("compile_count", "cost.compile_count"),
                          ("compile_s_total", "cost.compile_s_total"),
                          ("peak_hbm_bytes", "cost.peak_hbm_bytes")):
        v = summary.get(field)
        if v is not None:
            rows.append(_row(metric, FIXED_METRICS[metric], v, run_ord,
                             source, wl, backend))
    for program, eff in sorted(
            (summary.get("analytic_efficiency") or {}).items()):
        rows.append(_row("cost.analytic_efficiency",
                         FIXED_METRICS["cost.analytic_efficiency"], eff,
                         run_ord, source, wl, backend, variant=program))
    return rows, []


def _load_serve(doc: dict, run_ord: int,
                source: str) -> Tuple[List[dict], List[dict]]:
    """SERVE_rNN.json (`serve_fast_path_before_after`): per path, the max
    SUSTAINED throughput point and its latency/reject shape — the knee of
    the curve is the only point worth trending."""
    backend = (doc.get("host") or {}).get("platform")
    wl = normalize_workload({}, doc)
    rows: List[dict] = []
    skipped: List[dict] = []
    for side in ("before", "after"):
        sweep = doc.get(side) or {}
        variant = str(sweep.get("path") or side)
        best = None
        for pt in sweep.get("points") or []:
            if isinstance(pt, dict) and pt.get("sustained") \
                    and isinstance(pt.get("value"), (int, float)):
                if best is None or pt["value"] > best["value"]:
                    best = pt
        if best is None:
            skipped.append({"source": source, "reason":
                            f"{variant}: no sustained point"})
            continue
        rows.append(_row("serve.max_sustained_qps", HIGHER, best["value"],
                         run_ord, source, wl, backend, variant=variant))
        for field, metric in (("p50_ms", "serve.p50_ms"),
                              ("p99_ms", "serve.p99_ms"),
                              ("reject_rate", "serve.reject_rate")):
            v = best.get(field)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                rows.append(_row(metric, FIXED_METRICS[metric], v,
                                 run_ord, source, wl, backend,
                                 variant=variant))
    gain = doc.get("qps_gain")
    if isinstance(gain, (int, float)) and not isinstance(gain, bool):
        rows.append(_row("serve.qps_gain", HIGHER, gain, run_ord, source,
                         wl, backend))
    return rows, skipped


def _load_input(doc: dict, run_ord: int,
                source: str) -> Tuple[List[dict], List[dict]]:
    """INPUT_rNN.json: the headline batches/sec line plus the paired
    legacy/pipeline variants (data-wait share is the ROADMAP item-3
    trajectory) and the compile count."""
    backend = doc.get("backend")
    wl = normalize_workload({}, doc)
    rows = [_bench_line_row(doc, run_ord, source)]
    for variant in ("legacy", "pipeline"):
        sub = doc.get(variant) or {}
        for field, (metric, direction) in INPUT_VARIANT_METRICS.items():
            v = sub.get(field)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                rows.append(_row(metric, direction, v, run_ord, source,
                                 wl, backend, variant=variant))
    compiles = doc.get("xla_compiles")
    if isinstance(compiles, (int, float)) and not isinstance(
            compiles, bool):
        rows.append(_row("input.xla_compiles",
                         FIXED_METRICS["input.xla_compiles"], compiles,
                         run_ord, source, wl, backend))
    return rows, []


def _load_bench_matrix(doc: dict, run_ord: int,
                       source: str) -> Tuple[List[dict], List[dict]]:
    """bench_matrix_rNN.json: one series per variant label. A null value
    (backend probe failed) is a SKIP with the artifact's recorded reason.
    Backend matching stays strict: r05's backend-null cells do NOT join
    r03's tpu series — an unprobed backend is not a measurement of it."""
    backend = doc.get("backend")
    wl = normalize_workload({}, doc)
    rows: List[dict] = []
    skipped: List[dict] = []
    for variant in doc.get("variants") or []:
        if not isinstance(variant, dict):
            continue
        label = str(variant.get("label", "?"))
        v = variant.get("value")
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            skipped.append({"source": source, "reason":
                            f"{label}: no value "
                            f"({doc.get('backend_probe_error') or 'null'})"
                            })
            continue
        rows.append(_row("matrix.images_per_sec_per_chip", HIGHER, v,
                         run_ord, source, wl, backend, variant=label,
                         unit=variant.get("unit")))
    return rows, skipped


def detect_generation(doc: dict, path: str) -> str:
    """Name the artifact generation, or refuse by name. Order matters:
    INPUT artifacts carry a bare `metric` too, so their legacy/pipeline
    pair is tested first."""
    if not isinstance(doc, dict):
        raise LedgerError(f"{path}: artifact is not a JSON object")
    if doc.get("report") == "program_cost_report":
        return "cost_report"
    if doc.get("artifact") == "serve_fast_path_before_after":
        return "serve_before_after"
    if isinstance(doc.get("legacy"), dict) \
            and isinstance(doc.get("pipeline"), dict):
        return "input_pipeline"
    if isinstance(doc.get("variants"), list) and "timestamp" in doc:
        return "bench_matrix"
    if isinstance(doc.get("strategies"), list):
        return "multichip_strategies"
    if "parsed" in doc and "rc" in doc:
        return "bench_wrapped"
    if "n_devices" in doc and "ok" in doc and "rc" in doc:
        return "multichip_legacy"
    if "metric" in doc and "value" in doc:
        return "bench_line"
    raise LedgerError(
        f"{path}: unrecognized artifact schema (keys: "
        f"{sorted(doc)[:12]}); teach telemetry/ledger.py its generation "
        f"— the ledger never silently drops an artifact")


_LOADERS = {
    "bench_wrapped": _load_bench_wrapped,
    "bench_line": _load_bench_line,
    "multichip_legacy": _load_multichip,
    "multichip_strategies": _load_multichip,
    "cost_report": _load_cost,
    "serve_before_after": _load_serve,
    "input_pipeline": _load_input,
    "bench_matrix": _load_bench_matrix,
}


def load_artifact(path: str) -> Tuple[List[dict], List[dict]]:
    """(rows, skipped) for ONE artifact file."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        raise LedgerError(f"{path}: unreadable artifact: {e}")
    check_schema_version(doc if isinstance(doc, dict) else {}, path)
    generation = detect_generation(doc, path)
    source = os.path.basename(path)
    return _LOADERS[generation](doc, run_ordinal(doc, path), source)


def discover(root: str) -> List[str]:
    """Every committed-artifact path under `root`, sorted by name."""
    paths: List[str] = []
    for pattern in ARTIFACT_GLOBS:
        rx = re.compile("^" + re.escape(pattern).replace(r"\*", ".*")
                        + "$")
        for name in os.listdir(root):
            if rx.match(name):
                paths.append(os.path.join(root, name))
    return sorted(paths)


def ingest(paths: List[str]) -> dict:
    """All rows from `paths`, sorted into series order."""
    rows: List[dict] = []
    skipped: List[dict] = []
    for path in paths:
        r, s = load_artifact(path)
        rows.extend(r)
        skipped.extend(s)
    rows.sort(key=lambda r: (r["series"], r["run_ord"], r["source"]))
    return {"rows": rows, "skipped": skipped, "artifacts": len(paths)}


def histories(rows: List[dict]) -> Dict[str, List[dict]]:
    """series key -> rows sorted by (run_ord, source)."""
    out: Dict[str, List[dict]] = {}
    for row in rows:
        out.setdefault(row["series"], []).append(row)
    for series in out.values():
        series.sort(key=lambda r: (r["run_ord"], r["source"]))
    return out


def _median(values: List[float]) -> float:
    s = sorted(values)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def _worse_ratio(newest: float, center: float, direction: str) -> float:
    """How many times WORSE the newest point is than the center, >= 1.0
    when it regressed, < 1.0 when it improved. A positive history that
    collapses to <= 0 is infinitely worse (the pairwise gates' rule)."""
    if direction == HIGHER:
        num, den = center, newest
    else:
        num, den = newest, center
    if den <= 0:
        return math.inf if num > 0 else 1.0
    if num <= 0:
        return 0.0
    return num / den


def trend(history: List[dict], window: int = DEFAULT_WINDOW,
          threshold: float = DEFAULT_THRESHOLD) -> dict:
    """Robust trend stats for ONE series (rows already run-ordered).

    The newest point is judged against the median of the last `window`
    PRIOR points; the MAD band only ever widens tolerance (a move inside
    center +/- MAD_BAND*MAD is jitter, never a regression). With one
    prior point MAD is 0 and this degenerates to the repo's existing
    pairwise ratio gates.
    """
    values = [r["value"] for r in history]
    direction = history[-1]["direction"]
    newest = values[-1]
    prior = values[:-1][-window:]
    best = max(values) if direction == HIGHER else min(values)
    worse_than = (lambda a, b: a < b) if direction == HIGHER \
        else (lambda a, b: a > b)
    streak = 0
    for i in range(len(values) - 1, 0, -1):
        if worse_than(values[i], values[i - 1]):
            streak += 1
        else:
            break
    stats = {
        "series": history[-1]["series"], "metric": history[-1]["metric"],
        "direction": direction, "n": len(values),
        "first": values[0], "latest": newest, "best": best,
        "vs_best_pct": ((newest - best) / abs(best) * 100.0)
        if best else 0.0,
        "streak": streak, "unit": history[-1]["unit"],
        "runs": [r["run_ord"] for r in history],
        "sources": [r["source"] for r in history],
        "regressed": False, "ratio": None, "center": None, "mad": None,
    }
    if not prior:
        return stats
    center = _median(prior)
    mad = _median([abs(v - center) for v in prior])
    ratio = _worse_ratio(newest, center, direction)
    band = MAD_BAND * mad
    outside_band = (newest < center - band) if direction == HIGHER \
        else (newest > center + band)
    stats.update(center=center, mad=mad, ratio=ratio,
                 regressed=bool(ratio > threshold and outside_band))
    return stats


def report(rows: List[dict], window: int = DEFAULT_WINDOW,
           threshold: float = DEFAULT_THRESHOLD) -> dict:
    """The full trajectory report: one trend entry per series."""
    series = [trend(h, window=window, threshold=threshold)
              for h in histories(rows).values()]
    series.sort(key=lambda s: s["series"])
    return {
        "report": "performance_ledger", "v": 1,
        "schema_version": SCHEMA_VERSION,
        "series": series,
        "n_series": len(series),
        "n_rows": len(rows),
        "families": sorted({s["metric"].split(".", 1)[0]
                            for s in series}),
        "regressions": [s for s in series if s["regressed"]],
    }


def gate(rows: List[dict], window: int = DEFAULT_WINDOW,
         threshold: float = DEFAULT_THRESHOLD) -> dict:
    """The trend gate: report plus the exit-3 verdict. Regressions name
    the series AND the offending run/source — a gate that can't say which
    run went bad is a gate nobody acts on."""
    rep = report(rows, window=window, threshold=threshold)
    failures = []
    for s in rep["regressions"]:
        failures.append(
            f"{s['series']}: run r{s['runs'][-1]:02d} "
            f"({s['sources'][-1]}) is {s['ratio']:.2f}x worse than the "
            f"last-{min(window, s['n'] - 1)}-run median "
            f"{s['center']:.6g} ({s['direction']}, latest "
            f"{s['latest']:.6g}, threshold {threshold:g})")
    rep["failures"] = failures
    rep["ok"] = not failures
    return rep


def _fmt(v: Optional[float]) -> str:
    if v is None:
        return "-"
    if isinstance(v, float) and (abs(v) >= 1e6 or
                                 (v != 0 and abs(v) < 1e-3)):
        return f"{v:.4g}"
    return f"{v:g}"


def render_markdown(rep: dict) -> str:
    """The trajectory table docs/PERF.md embeds instead of hand-edited
    before/after tables."""
    lines = ["| series | n | first | latest | best | vs best | streak |",
             "|---|---|---|---|---|---|---|"]
    for s in rep["series"]:
        arrow = "+" if s["vs_best_pct"] >= 0 else ""
        lines.append(
            f"| {s['series']} | {s['n']} | {_fmt(s['first'])} "
            f"| {_fmt(s['latest'])} | {_fmt(s['best'])} "
            f"| {arrow}{s['vs_best_pct']:.1f}% | {s['streak']} |")
    lines.append("")
    lines.append(f"{rep['n_series']} series / {rep['n_rows']} rows across "
                 f"{len(rep['families'])} families: "
                 f"{', '.join(rep['families'])}.")
    return "\n".join(lines)
