"""Rank-aware logging and progress display.

The reference prints its epoch summary on EVERY rank (SURVEY.md §5.5) and
drives tqdm bars per step (ddp_tutorial_multi_gpu.py:85,98); it also defines
a DISABLE_TQDM flag it never honors (ddp_tutorial_cpu.py:9 — dead). Here:
process-0-gated logging is the default surface (matching the mp scripts'
rank-0 banner, mnist_cpu_mp.py:278-299), and the progress wrapper actually
honors its disable switch. No per-step device sync is ever forced for
display — the reference's `.item()`-per-step pattern is the antipattern this
framework exists to avoid.
"""

from __future__ import annotations

import os
import sys
from typing import Callable, Iterable, TypeVar

T = TypeVar("T")

def _env_flag(name: str, default: bool = False) -> bool:
    """Tolerant boolean env parsing: 1/true/yes/on (any case) enable, 0/
    false/no/off/'' disable, anything else falls back to `default` rather
    than raising at import time."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    val = raw.strip().lower()
    if val in ("1", "true", "yes", "on"):
        return True
    if val in ("", "0", "false", "no", "off"):
        return False
    return default


DISABLE_TQDM = _env_flag("DISABLE_TQDM")


def rank_zero_log(log: Callable[[str], None] = print) -> Callable[[str], None]:
    """Return `log` on process 0, a no-op elsewhere. Safe before
    jax.distributed init (treats that as single-process).

    Process identity comes from the cached telemetry helper — the previous
    spelling imported jax and queried the backend on EVERY factory call;
    the cached resolve is shared with the event trace's per-record `proc`
    tag, and a pre-init failure still reads as rank 0 without being
    cached."""
    from ..telemetry.runtime import process_index_cached
    if process_index_cached() == 0:
        return log
    return lambda _msg: None


def progress(iterable: Iterable[T], desc: str = "", *,
             disable: bool | None = None) -> Iterable[T]:
    """tqdm-style progress iteration (reference: tqdm wraps both hot loops,
    ddp_tutorial_multi_gpu.py:85,101). Falls back to a plain iterator when
    tqdm is unavailable, `disable` is set, DISABLE_TQDM=1, stderr is not a
    TTY (so batch logs stay clean), or this is not process 0 (N ranks
    interleaving carriage returns on one terminal garble each other — the
    reference does exactly that; rank-0 gating is the fix)."""
    if disable is None:
        disable = DISABLE_TQDM or not sys.stderr.isatty()
        if not disable:
            from ..telemetry.runtime import process_index_cached
            disable = process_index_cached() != 0
    if disable:
        return iter(iterable)
    try:
        from tqdm import tqdm
    except ImportError:
        return iter(iterable)
    # the tqdm INSTANCE, not iter(instance): tqdm is itself iterable, and
    # callers (train.loop._LiveLoss) need its set_postfix_str for the async
    # live-loss display — iter() would hand back a bare generator without it
    return tqdm(iterable, desc=desc)
