"""Cross-cutting utilities: timing/profiling and rank-aware logging."""

from .profiling import Timer, CumulativeTimer, trace, device_sync  # noqa: F401
from .logging import rank_zero_log, progress  # noqa: F401
