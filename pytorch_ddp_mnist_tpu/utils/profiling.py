"""Timing and profiler capture.

The reference repo descends from an I/O-cost-evaluation harness — its argparse
still self-describes as "Evaluate cost of reading input files"
(mnist_cpu_mp.py:210) — but no timing code survives in it (SURVEY.md §5.1).
This module restores that capability the TPU way:

  * `Timer` / `CumulativeTimer` — wall-clock timing that understands XLA's
    async dispatch: on device work, a naive `time.time()` pair measures only
    enqueue time, so timers take an optional pytree to `block_until_ready` on
    exit. Both take an optional `registry=` (telemetry.MetricsRegistry):
    every measured section then ALSO lands in the unified
    `timer.{name}_s` histogram — percentiles, snapshot export, and bench
    artifact stamps for free. The standalone `.seconds`/`.total`/`.count`
    attributes remain for callers that hold the timer object, but the
    registry hook is the preferred export path: it deprecates bespoke
    accumulate-then-print plumbing around these attributes (the
    pre-telemetry pattern).
  * `trace(logdir)` — one-line capture of a real profiler trace
    (jax.profiler: XPlane protos viewable in TensorBoard/XProf), covering
    device compute, HBM transfers, and ICI collectives — the data the
    reference's lost I/O-cost harness wanted, plus the device side it never
    had.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Optional

import jax


def device_sync(tree: Any = None) -> None:
    """Drain async dispatch: block until `tree`'s arrays (or, with no
    argument, all live arrays on all local devices) are computed."""
    if tree is not None:
        jax.block_until_ready(tree)
        return
    for a in jax.live_arrays():
        jax.block_until_ready(a)


class Timer:
    """Context-manager wall timer, async-dispatch aware.

        with Timer("epoch") as t:
            out = step(...)
            t.sync(out)          # timer exit blocks on `out` first
        print(t.seconds)

    Without `sync`, measures plain wall time of the block. With
    `registry=`, each completed block also records into the registry's
    `timer.{name}_s` histogram (the unified-telemetry bridge).
    """

    def __init__(self, name: str = "timer", registry=None):
        self.name = name
        self.seconds: Optional[float] = None
        self._sync_tree: Any = None
        self._hist = (registry.histogram(f"timer.{name}_s")
                      if registry is not None else None)

    def sync(self, tree: Any) -> Any:
        """Register a pytree to block on at exit; returns it unchanged."""
        self._sync_tree = tree
        return tree

    def __enter__(self) -> "Timer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        if self._sync_tree is not None:
            jax.block_until_ready(self._sync_tree)
        self.seconds = time.perf_counter() - self._t0
        if self._hist is not None:
            self._hist.record(self.seconds)


class CumulativeTimer:
    """Accumulates wall time over repeated sections (e.g. data-loading vs
    step time inside an epoch) — the per-phase cost split the reference's
    ancestral I/O harness was built to report.

        t = CumulativeTimer("io")
        for ...:
            with t:
                batch = next(loader)
        t.total, t.count, t.mean

    With `registry=`, every section additionally records into the
    `timer.{name}_s` histogram — constant memory at any rate, and the
    per-section DISTRIBUTION (p50/p95/max) rides the unified snapshot
    where the standalone total/count pair could only ever report a mean.
    """

    def __init__(self, name: str = "section", registry=None):
        self.name = name
        self.total = 0.0
        self.count = 0
        self._hist = (registry.histogram(f"timer.{name}_s")
                      if registry is not None else None)

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def __enter__(self) -> "CumulativeTimer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        dt = time.perf_counter() - self._t0
        self.total += dt
        self.count += 1
        if self._hist is not None:
            self._hist.record(dt)

    def __repr__(self) -> str:
        return (f"CumulativeTimer({self.name}: total={self.total:.4f}s "
                f"count={self.count} mean={self.mean * 1e3:.3f}ms)")


@contextlib.contextmanager
def trace(logdir: Optional[str]):
    """Capture a jax.profiler trace of the enclosed block into `logdir`
    (no-op when logdir is falsy, so call sites need no branching). View with
    TensorBoard's profile plugin or XProf."""
    if not logdir:
        yield
        return
    with jax.profiler.trace(str(logdir)):
        yield
