"""Deterministic fault injection — the chaos layer the resilience paths
are tested against.

The framework carries several failure-handling claims: `cli/train.py`'s
outage retry + re-exec resume, `parallel/wireup.py`'s hang-bounded probe
loop, the checkpoint manager's crash consistency (`train/ckpt_manager.py`),
and the loaders' stall accounting. At scale those paths run MORE often than
the happy path (arXiv:1711.00705: failures are the norm across large
distributed systems) — so they must be *injectable on demand*, not waited
for. This module is the single switchboard: a fault spec names a failure,
the instrumented code paths ask `fire(point, ...)` at their fault points,
and a matching spec performs the failure deterministically.

Spec syntax (comma-separated specs; `key=value` constraints after the kind):

    PDMT_FAULT="kill:rank=2:step=5"              # SIGKILL this process
    PDMT_FAULT="ckpt_save_io:step=3"             # OSError inside ckpt save
    PDMT_FAULT="loader_stall:batch=3:delay_s=0.5"  # sleep in the loader
    PDMT_FAULT="collective_timeout:rank=1"       # DEADLINE_EXCEEDED barrier
    PDMT_FAULT="nan:step=5"                      # NaN the step-5 loss
    PDMT_FAULT="engine_crash:after=40:replica=0" # serve engine dies mid-burst
    PDMT_FAULT="engine_wedge:delay_s=2:replica=1"  # staged fetch hangs
    PDMT_FAULT="reload_torn"                     # hot-reload sees a torn ckpt

or `--fault SPEC` on the trainer CLI (env and flag merge). Each spec fires
at its own fault point:

    kind                fires at           action
    ----                --------           ------
    kill                "step"             flight-dump + SIGKILL (no cleanup,
                                           no atexit — a real preemption)
    ckpt_save_io        "ckpt_save"        raise OSError before the payload
                                           rename (save fails, nothing torn)
    loader_stall        "loader_next"      time.sleep(delay_s) (default 0.5)
    collective_timeout  "barrier"          raise a DEADLINE_EXCEEDED-shaped
                                           RuntimeError (matches
                                           wireup.looks_like_backend_loss —
                                           the signature triage sees exactly
                                           what a dead collective produces)
    nan                 "loss"             poison the reported per-step loss
                                           with NaN (params stay finite —
                                           the health watchdog's detection
                                           path becomes deterministically
                                           testable, and a rescue
                                           checkpoint stays intact). Fired
                                           through `poison`/`poison_array`,
                                           which RETURN the (possibly
                                           NaN'd) value instead of acting.
    engine_crash        "serve_engine"     raise a RuntimeError from the
                                           serve engine's bucket dispatch —
                                           a replica dying mid-batch. Gate
                                           with `after=N` (fires on the
                                           engine's Nth executable call,
                                           first-crossing >=) and
                                           `replica=R` (a fleet replica
                                           index) so chaos legs kill ONE
                                           replica at a deterministic
                                           point in the burst.
    engine_wedge        "serve_wedge"      wedge the just-dispatched
                                           in-flight batch (fired through
                                           `claim`, which RETURNS the spec
                                           for the engine to act on): its
                                           results report not-ready for
                                           delay_s and the staged fetch
                                           blocks until then — the reply
                                           thread hangs exactly as on a
                                           device that stopped answering,
                                           in-flight batches age, and the
                                           fleet supervisor's batch
                                           watchdog (serve/fleet.py) is
                                           what must notice.
    reload_torn         "reload_validate"  raise from the hot-reload
                                           watcher's off-loop checkpoint
                                           validation — a torn manifest
                                           surfacing mid-swap. The watcher
                                           must refuse BY NAME and keep
                                           the incumbent serving
                                           (serve/reload.py).

Determinism contract: a spec with `step=K` fires at the FIRST fault-point
crossing where the reported step is >= K (the epoch-scanned trainer only
surfaces steps at checkpoint-chunk boundaries, so equality alone could
never match); `after=N` has the same first-crossing semantics over the
serve engine's per-call ordinal; `epoch=`/`batch=`/`replica=` match
exactly; `rank=` gates on the injecting process's rank (set by the CLI
after wireup, seeded from $RANK before it). Every spec fires at most
`times=` times (default 1). Every
fired fault lands in the telemetry flight recorder as a `fault_injected`
entry BEFORE the failure happens, so a post-mortem shows what was injected
even when the action is SIGKILL.

`fire()` with no faults installed is a few-ns no-op (one attribute test) —
the instrumented hot paths pay nothing in production.
"""

from __future__ import annotations

import math
import os
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

FAULT_ENV = "PDMT_FAULT"

# kind -> the fault point it fires at. One place to extend.
POINTS = {
    "kill": "step",
    "ckpt_save_io": "ckpt_save",
    "loader_stall": "loader_next",
    "collective_timeout": "barrier",
    "nan": "loss",
    "engine_crash": "serve_engine",
    "engine_wedge": "serve_wedge",
    "reload_torn": "reload_validate",
}

# constraint keys with first-crossing (>=) semantics; all others match ==
_THRESHOLD_KEYS = ("step", "after")
_KNOWN_KEYS = ("step", "epoch", "batch", "rank", "delay_s", "times",
               "after", "replica")


class FaultSpecError(ValueError):
    """A malformed fault spec — named so the CLI can fail at parse time."""


@dataclass
class FaultSpec:
    kind: str
    point: str
    where: Dict[str, float] = field(default_factory=dict)  # constraint keys
    delay_s: float = 0.5
    times: int = 1
    fired: int = 0

    def matches(self, rank: int, ctx: Dict[str, float]) -> bool:
        if self.fired >= self.times:
            return False
        if "rank" in self.where and int(self.where["rank"]) != int(rank):
            return False
        for key, want in self.where.items():
            if key == "rank":
                continue
            got = ctx.get(key)
            if got is None:
                return False
            if key in _THRESHOLD_KEYS:
                if got < want:
                    return False
            elif got != want:
                return False
        return True

    def describe(self) -> str:
        cons = ":".join(f"{k}={int(v) if float(v).is_integer() else v}"
                        for k, v in sorted(self.where.items()))
        return self.kind + (f":{cons}" if cons else "")


def parse_faults(text: Optional[str]) -> List[FaultSpec]:
    """Parse a comma-separated fault-spec string; [] for empty/None.

    Unknown kinds and malformed constraints raise FaultSpecError by name —
    a chaos run with a typo'd spec must refuse to start, not silently run
    fault-free and "pass"."""
    specs: List[FaultSpec] = []
    for raw in (text or "").split(","):
        raw = raw.strip()
        if not raw:
            continue
        parts = raw.split(":")
        kind = parts[0].strip()
        if kind not in POINTS:
            raise FaultSpecError(
                f"unknown fault kind {kind!r} in {raw!r}; known: "
                f"{sorted(POINTS)}")
        spec = FaultSpec(kind=kind, point=POINTS[kind])
        for item in parts[1:]:
            if "=" not in item:
                raise FaultSpecError(
                    f"fault constraint {item!r} in {raw!r} is not key=value")
            key, _, val = item.partition("=")
            key = key.strip()
            if key not in _KNOWN_KEYS:
                raise FaultSpecError(
                    f"unknown fault constraint {key!r} in {raw!r}; known: "
                    f"{_KNOWN_KEYS}")
            try:
                num = float(val)
            except ValueError:
                raise FaultSpecError(
                    f"fault constraint {item!r} in {raw!r}: {val!r} is not "
                    f"a number") from None
            if key == "delay_s":
                spec.delay_s = num
            elif key == "times":
                spec.times = int(num)
            else:
                spec.where[key] = num
        specs.append(spec)
    return specs


class FaultInjector:
    """Holds the parsed specs + this process's rank; `fire` is the one
    entry point the instrumented paths call."""

    def __init__(self, specs: List[FaultSpec], rank: int = 0):
        self.specs = list(specs)
        self.rank = int(rank)

    def fire(self, point: str, **ctx) -> None:
        for spec in self.specs:
            # value faults ("nan") only fire through poison()/poison_array()
            # — they must RETURN a poisoned value, which fire() cannot do
            if (spec.kind == "nan" or spec.point != point
                    or not spec.matches(self.rank, ctx)):
                continue
            spec.fired += 1
            self._act(spec, ctx)

    def _record(self, spec: FaultSpec, ctx: Dict[str, float]) -> None:
        # flight first: the record must exist before the failure does
        from ..telemetry import flight
        flight.record("fault_injected", fault=spec.describe(),
                      point=spec.point, rank=self.rank,
                      **{k: v for k, v in ctx.items()
                         if k not in ("fault", "point", "rank")})

    def claim(self, point: str, **ctx) -> Optional[FaultSpec]:
        """Caller-acted twin of `fire` (the control-flow analogue of
        `poison`): match a due spec at `point`, mark it fired, land the
        flight record, and RETURN the spec so the instrumented site can
        perform a failure `fire` cannot express — the serve engine wedges
        its just-dispatched in-flight handle with the spec's `delay_s`.
        None when nothing is due (the common case)."""
        for spec in self.specs:
            if (spec.kind == "nan" or spec.point != point
                    or not spec.matches(self.rank, ctx)):
                continue
            spec.fired += 1
            self._record(spec, ctx)
            return spec
        return None

    def poison(self, point: str, value, **ctx):
        """Value-fault twin of `fire`: returns `value`, NaN-poisoned when a
        matching value spec (kind 'nan') is due at `point`. Works on jax
        scalars and numpy values alike (`value * nan` stays on device for a
        traced/device value — the poison never forces a host sync)."""
        for spec in self.specs:
            if (spec.kind != "nan" or spec.point != point
                    or not spec.matches(self.rank, ctx)):
                continue
            spec.fired += 1
            self._record(spec, ctx)
            value = value * float("nan")
        return value

    def poison_array(self, point: str, values, *, first_step: int, **ctx):
        """Chunk form of `poison` for per-step value arrays fetched in one
        go (the epoch-scanned trainer): `values[i]` is the value of global
        step `first_step + i`. The FIRST index crossing a matching spec's
        `step` threshold is NaN'd (the same first-crossing >= K semantics
        as every step-gated spec). Returns the (possibly copied) array."""
        import numpy as np
        n = len(values)
        if n == 0:
            return values
        for spec in self.specs:
            if spec.kind != "nan" or spec.point != point:
                continue
            want = spec.where.get("step")
            if want is None:
                idx = 0
            else:
                if first_step + n - 1 < want:   # threshold not reached yet
                    continue
                idx = max(0, int(math.ceil(want)) - int(first_step))
            step_at = int(first_step) + idx
            if not spec.matches(self.rank, {**ctx, "step": step_at}):
                continue
            spec.fired += 1
            self._record(spec, {**ctx, "step": step_at})
            values = np.array(values, copy=True)
            values[idx] = float("nan")
        return values

    def _act(self, spec: FaultSpec, ctx: Dict[str, float]) -> None:
        # flight first: the record must exist before the failure does,
        # because two of the actions never return control.
        self._record(spec, ctx)
        if spec.kind == "kill":
            # a real preemption: dump the ring (SIGKILL outruns any atexit),
            # then die uncleanly — no flushes, no context managers.
            from ..telemetry import flight
            flight.dump(reason=f"injected fault: {spec.describe()}")
            os.kill(os.getpid(), signal.SIGKILL)
        elif spec.kind == "ckpt_save_io":
            raise OSError(f"injected fault: {spec.describe()} "
                          f"(simulated checkpoint I/O failure)")
        elif spec.kind == "loader_stall":
            time.sleep(spec.delay_s)
        elif spec.kind == "collective_timeout":
            # the exact failure class wireup's signature triage handles:
            # looks_like_backend_loss matches "deadline exceeded"
            raise RuntimeError(
                f"DEADLINE_EXCEEDED: injected fault: {spec.describe()} "
                f"(simulated collective timeout)")
        elif spec.kind == "engine_crash":
            # a replica dying mid-batch: surfaces from the bucket dispatch
            # exactly where a device reset / lost executable would, so the
            # fleet's quarantine-and-retry path sees the real error shape
            raise RuntimeError(f"injected fault: {spec.describe()} "
                               f"(simulated serve engine crash)")
        elif spec.kind == "reload_torn":
            raise RuntimeError(f"injected fault: {spec.describe()} "
                               f"(simulated torn checkpoint during reload "
                               f"validation)")


_INJECTOR: Optional[FaultInjector] = None


def _env_injector() -> FaultInjector:
    rank = 0
    try:
        rank = int(os.environ.get("RANK", "0"))
    except ValueError:
        pass
    return FaultInjector(parse_faults(os.environ.get(FAULT_ENV)), rank=rank)


# install()/get_injector() lazily (re)build the process-wide injector;
# loader readahead threads hit fire() concurrently with a late install
# (statics rule MUT002). fire()'s fast path reads one reference unlocked —
# a reader racing a swap gets either injector, both consistent.
_INJ_LOCK = threading.Lock()


def install(extra: Optional[str] = None, rank: Optional[int] = None) -> "FaultInjector":
    """(Re)build the process-wide injector: $PDMT_FAULT specs + `extra`
    (the CLI --fault value), rank-gated to `rank` when given. Returns the
    injector (tests hold it to inspect fired counts)."""
    global _INJECTOR
    inj = _env_injector()
    inj.specs.extend(parse_faults(extra))
    if rank is not None:
        inj.rank = int(rank)
    with _INJ_LOCK:
        _INJECTOR = inj
    return inj


def set_rank(rank: int) -> None:
    """Late rank binding: the CLI learns its process index only after
    wireup; specs parsed earlier start gating on the real rank from here."""
    get_injector().rank = int(rank)


def get_injector() -> FaultInjector:
    global _INJECTOR
    if _INJECTOR is None:
        with _INJ_LOCK:
            if _INJECTOR is None:
                _INJECTOR = _env_injector()
    return _INJECTOR


def fire(point: str, **ctx) -> None:
    """Ask the switchboard whether a fault is due at `point`. The no-fault
    fast path is one None-check plus an empty-list check — safe on hot
    per-step paths."""
    inj = _INJECTOR
    if inj is None:
        if FAULT_ENV not in os.environ:
            return  # never configured: stay lazy, stay free
        inj = get_injector()
    if inj.specs:
        inj.fire(point, **ctx)


def claim(point: str, **ctx) -> Optional[FaultSpec]:
    """Caller-acted entry point: return the due spec at `point` (marked
    fired + flight-recorded) for the call site to act on, or None. Same
    few-ns no-fault fast path as `fire`."""
    inj = _INJECTOR
    if inj is None:
        if FAULT_ENV not in os.environ:
            return None
        inj = get_injector()
    if inj.specs:
        return inj.claim(point, **ctx)
    return None


def poison(point: str, value, **ctx):
    """Value-fault entry point: return `value`, NaN-poisoned when a 'nan'
    spec is due at `point`. Same few-ns no-fault fast path as `fire` —
    safe on per-step hot paths."""
    inj = _INJECTOR
    if inj is None:
        if FAULT_ENV not in os.environ:
            return value
        inj = get_injector()
    if inj.specs:
        return inj.poison(point, value, **ctx)
    return value


def poison_array(point: str, values, *, first_step: int, **ctx):
    """Chunk form of `poison` (see FaultInjector.poison_array)."""
    inj = _INJECTOR
    if inj is None:
        if FAULT_ENV not in os.environ:
            return values
        inj = get_injector()
    if inj.specs:
        return inj.poison_array(point, values, first_step=first_step, **ctx)
    return values


def active() -> bool:
    """True when any spec is installed (cheap gate for optional plumbing)."""
    inj = _INJECTOR
    if inj is None and FAULT_ENV in os.environ:
        inj = get_injector()
    return bool(inj and inj.specs)
