"""The ONE torch re-statement of the reference model.

Both the torch-parity unit tests (tests/test_torch_parity.py) and the
10-epoch golden-accuracy generator (scripts/golden_accuracy.py) certify
this framework against an independent torch implementation of the
reference trainer's model (create_model, ddp_tutorial_cpu.py:43-53:
dropout 0.2 only after layer 1, no bias on the output layer, torch
default Linear init). Keeping that re-statement — and the
state_dict -> params-pytree weight-transpose convention — in one place
means the two certifications can never silently drift onto different
models.

torch is imported lazily: the framework itself never needs it.
"""

from __future__ import annotations


def build_reference_model(seed: int):
    """The reference create_model graph under torch.manual_seed(seed)."""
    import torch
    import torch.nn as nn

    torch.manual_seed(seed)
    return nn.Sequential(
        nn.Linear(784, 128), nn.ReLU(), nn.Dropout(0.2),
        nn.Linear(128, 128), nn.ReLU(),
        nn.Linear(128, 10, bias=False),
    )


def params_from_torch(model):
    """Torch state_dict -> the framework's params pytree, weights
    transposed to the (fan_in, fan_out) `x @ w` layout of models/mlp.py."""
    import jax.numpy as jnp

    sd = {k: v.detach().numpy() for k, v in model.state_dict().items()}
    return {
        "fc1": {"w": jnp.asarray(sd["0.weight"].T),
                "b": jnp.asarray(sd["0.bias"])},
        "fc2": {"w": jnp.asarray(sd["3.weight"].T),
                "b": jnp.asarray(sd["3.bias"])},
        "fc3": {"w": jnp.asarray(sd["5.weight"].T)},
    }
