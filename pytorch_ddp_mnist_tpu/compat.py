"""jax version compatibility — the ONE place API-surface drift is absorbed.

The framework targets the current jax spelling (`jax.shard_map` with
`check_vma`, `jax.lax.pvary`); older installed versions (<= 0.4.x, like the
pinned CI image) spell these `jax.experimental.shard_map.shard_map` with
`check_rep` and have no pvary at all. Semantics are unchanged by the shim:

* `shard_map` — same call, with `check_vma` translated to the old
  `check_rep` flag. Both are STATIC replication/varying-axis checks; every
  grad computation in this codebase runs inside the mapped body (jax.grad
  is called within the shard function, never differentiated THROUGH the
  shard_map boundary), so no transpose-rule difference is in play.
* `pvary` — the new-jax varying-axis cast exists purely to satisfy the
  check_vma type system; old jax has no vma tracking, so the cast is the
  identity there.

Import from here, not from jax, for any of these names.
"""

from __future__ import annotations

import os

import jax

try:
    from jax import shard_map as _shard_map
    _NEW_SPELLING = True
except ImportError:                      # jax <= 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map
    _NEW_SPELLING = False

# jax flipped jax_threefry_partitionable on by default in 0.5; the
# framework's RNG parity story (the in-kernel threefry kernel reproduces
# jax's stream bit-for-bit, and train/scan.py REJECTS the legacy stream by
# name) is written against the new default. Align older jax at import so
# the same seeds draw the same masks everywhere — UNLESS the user opted
# out explicitly via the env var, which is a deliberate legacy-stream
# request on any version and stays honored (the framework paths that
# require the partitionable stream still fail by name in scan.py, exactly
# as on new jax; this just never overrides user intent silently).
if (not jax.config.jax_threefry_partitionable
        and os.environ.get("JAX_THREEFRY_PARTITIONABLE", "").strip()
        .lower() not in ("0", "false", "no", "off")):
    jax.config.update("jax_threefry_partitionable", True)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """`jax.shard_map` under either spelling of the replication check."""
    if _NEW_SPELLING:
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=check_vma)
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def tpu_compiler_params(**kw):
    """`pltpu.CompilerParams(**kw)` under either spelling (0.4.x named the
    class TPUCompilerParams). Fields the installed class does not know
    (e.g. 0.4.x has no `has_side_effects`) are dropped rather than fatal:
    they are compiler HINTS (DCE/reordering fences), and every caller here
    consumes the kernel's outputs, so correctness does not hinge on them —
    old-jax hosts are the CPU/interpreter CI environment, not hardware."""
    import dataclasses

    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    known = {f.name for f in dataclasses.fields(cls)}
    return cls(**{k: v for k, v in kw.items() if k in known})


def abstract_mesh(axis_sizes, axis_names):
    """`jax.sharding.AbstractMesh` under either constructor: new jax takes
    (axis_sizes, axis_names); 0.4.x takes one ((name, size), ...) tuple."""
    from jax.sharding import AbstractMesh
    try:
        return AbstractMesh(tuple(axis_sizes), tuple(axis_names))
    except TypeError:
        return AbstractMesh(tuple(zip(axis_names, axis_sizes)))


def set_cpu_device_count(n: int) -> bool:
    """Resize the virtual CPU pool via the jax_num_cpu_devices config
    (honored at backend (re-)creation). Returns False on jax versions
    without the option — there the pool can only be sized by XLA_FLAGS
    before the process's FIRST client creation, which is the caller's
    fallback to arrange."""
    try:
        jax.config.update("jax_num_cpu_devices", n)
        return True
    except (AttributeError, KeyError):
        return False


def pvary(tree, axis: str):
    """Cast a replicated pytree to device-varying along `axis` (per-replica
    copies). jax >= 0.9 spells this pcast, 0.5-0.8 pvary; 0.4.x has no vma
    tracking to satisfy, so the cast is the identity."""
    if hasattr(jax.lax, "pcast"):
        return jax.tree_util.tree_map(
            lambda a: jax.lax.pcast(a, axis, to="varying"), tree)
    if hasattr(jax.lax, "pvary"):
        return jax.tree_util.tree_map(
            lambda a: jax.lax.pvary(a, axis), tree)
    return tree
