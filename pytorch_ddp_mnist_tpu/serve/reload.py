"""Hot reload: a checkpoint-directory watcher that swaps the fleet to new
weights with zero downtime — and refuses bad checkpoints BY NAME.

The training side commits step-granular checkpoints (`train/ckpt_manager`:
payload fsync -> rename, manifest rename as the commit point); this
watcher is the serve side of ROADMAP item 2's "live model update": poll
the directory, and when a step newer than what the fleet serves commits,
promote it. What "promotable" means is NOT re-implemented here — the
watcher calls the SAME `CheckpointManager.scan_restorable` walk that
`--resume` uses (newest intact AND finite, every rejection named), so the
two consumers can never drift. One deliberate divergence, pinned by test:
where a resume falls back to a non-finite checkpoint with a warning
(refusing would strand a pre-watchdog resume), a reload REFUSES it — the
incumbent weights are healthy and serving, and swapping diverged NaN
weights under live traffic is strictly worse than staying put.

The promotion itself is `FleetService.apply_reload`: every validation,
payload read, CRC check, decode, and bucket-ladder compile happens in the
executor (off the event loop — traffic keeps flowing through a reload),
then replicas swap one at a time behind a drain so no request ever spans
a swap. A refused candidate (torn payload, CRC mismatch, non-finite
params, or an injected `reload_torn` fault) is recorded ONCE by name —
`serve.reload.refused` counter, `reload_event` telemetry point, flight
record — and the watcher keeps polling for the next step; a refused step
never RE-TRIGGERS a poll (an idle directory stays one listdir per
interval), and the incumbent keeps serving throughout. A NEWER commit
reopens the question, and the shared walk then promotes the newest
intact-and-finite step beyond what's serving — which may be an earlier
candidate whose refusal was transient (a validation crash, not a torn
payload): newest-promotable wins, exactly as a resume would choose.

`serve.reload.*` metrics: `reloads` / `refused` counters,
`serving_step` / `last_reload_s` gauges. `cli/serve.py --reload_dir`
runs the watcher next to the TCP server; the chaos smoke's
torn-checkpoint-swap leg drives every branch.
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional

from ..telemetry import flight
from ..telemetry.events import get_tracer
from ..train.ckpt_manager import CheckpointManager
from ..utils import faultpoints

# Poll cadence default: fast enough that "commit to first request on new
# weights" is dominated by the ladder compile, slow enough that an idle
# watcher is one listdir per interval.
POLL_INTERVAL_S = 0.25


class ReloadWatcher:
    """Watch a `CheckpointManager` directory and hot-swap the fleet.

    `poll_once()` is the whole decision, separately callable so tests and
    the chaos smoke drive reloads deterministically without the timer:
    returns "idle" (nothing newer), "reloaded" (fleet now serves the new
    step), or "refused" (a newer candidate exists but nothing newer is
    promotable — named, counted, incumbent untouched). `run()` loops
    `poll_once` every `poll_interval_s` until `stop()`.
    """

    def __init__(self, fleet, directory: str, *,
                 poll_interval_s: float = POLL_INTERVAL_S,
                 clock=None):
        if poll_interval_s <= 0:
            raise ValueError(
                f"poll_interval_s must be > 0; got {poll_interval_s}")
        self.fleet = fleet
        self.manager = CheckpointManager(directory)
        self.poll_interval_s = float(poll_interval_s)
        self.clock = clock or time.monotonic
        self._stopped = False
        self._task: Optional[asyncio.Task] = None
        # steps already refused by name: a torn step_000042 stays torn —
        # re-validating it every poll would re-pay the payload read and
        # re-spam the named refusal; a NEWER commit resets the question
        self._refused_steps: "set[int]" = set()
        reg = fleet.metrics.registry
        self._reloads = reg.counter("serve.reload.reloads")
        self._refused = reg.counter("serve.reload.refused")
        reg.gauge("serve.reload.serving_step").set_fn(
            lambda: self.fleet.serving_step)
        self._last_reload_s = reg.gauge("serve.reload.last_reload_s")

    @property
    def reloads(self) -> int:
        return self._reloads.value

    @property
    def refused(self) -> int:
        return self._refused.value

    # -- the decision -------------------------------------------------------

    def _newest_candidate(self) -> Optional[int]:
        steps = self.manager.steps()   # one listdir — the idle-poll cost
        if not steps:
            return None
        newest = steps[-1]
        if newest <= self.fleet.serving_step or newest in self._refused_steps:
            return None
        return newest

    def _scan(self, serving_step: int):
        """Executor-side validation: fire the injectable fault point,
        then run the SHARED newest-intact-and-finite walk bounded to
        steps beyond what the fleet serves. Everything expensive —
        payload read, CRC, msgpack decode, finiteness walk — happens
        here, off the loop."""
        faultpoints.fire("reload_validate")
        return self.manager.scan_restorable(self.fleet._params,
                                            newer_than=serving_step)

    def _refuse(self, step: int, reason: str) -> None:
        self._refused_steps.add(step)
        self._refused.inc()
        reason = reason[:400]
        flight.record("reload_event", event="refused", step=step,
                      reason=reason)
        get_tracer().point("reload_event", event="refused", step=step,
                           serving_step=self.fleet.serving_step,
                           reason=reason)

    async def poll_once(self) -> str:
        """One watch cycle: cheap manifest peek, off-loop validation,
        drain-and-swap promotion. See class docstring for the verdicts."""
        newest = self._newest_candidate()
        if newest is None:
            return "idle"
        serving = self.fleet.serving_step
        loop = asyncio.get_running_loop()
        t0 = time.monotonic()
        try:
            scan = await loop.run_in_executor(None, self._scan, serving)
        except Exception as e:  # noqa: BLE001 — a validation crash (the
            # injected reload_torn fault, an unreadable directory) must
            # refuse by name, never take the watcher or the fleet down
            self._refuse(newest, f"validation failed: "
                                 f"{type(e).__name__}: {e}")
            return "refused"
        if scan.best is None:
            # a newer commit exists but nothing newer is promotable:
            # torn/corrupt candidates carry their defect in scan.tried;
            # an intact-but-non-finite one is the resume path's fallback
            # and the reload path's NAMED refusal (see module docstring)
            if scan.newest_nonfinite is not None:
                reason = (f"step {scan.newest_nonfinite.step} is intact "
                          f"but non-finite (a diverged run's checkpoint) "
                          f"— refusing to serve it")
            elif scan.tried:
                reason = scan.tried[0]
            else:
                reason = "no intact checkpoint newer than serving step"
            self._refuse(newest, reason)
            return "refused"
        ckpt = scan.best
        swapped = await self.fleet.apply_reload(ckpt.params, ckpt.step)
        dur = time.monotonic() - t0
        self._reloads.inc()
        self._last_reload_s.set(round(dur, 4))
        flight.record("reload_event", event="reloaded", step=ckpt.step,
                      swapped=swapped, dur_s=round(dur, 4),
                      skipped=len(scan.tried))
        get_tracer().point("reload_event", event="reloaded", step=ckpt.step,
                           swapped=swapped, dur_s=round(dur, 4),
                           skipped=len(scan.tried))
        return "reloaded"

    # -- the loop -----------------------------------------------------------

    async def run(self) -> None:
        """Poll until `stop()`; one failed cycle is counted and survived
        (`poll_once` already converts validation failures into refusals —
        anything else would be a watcher bug, logged to flight and
        retried next interval)."""
        while not self._stopped:
            try:
                await self.poll_once()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — the watcher outlives
                # its own bugs: a reload must never be able to stop
                # FUTURE reloads
                flight.record("reload_event", event="watcher_error",
                              error=f"{type(e).__name__}: {e}"[:400])
            await asyncio.sleep(self.poll_interval_s)

    def start(self) -> asyncio.Task:
        """Spawn `run()` on the running loop (idempotent)."""
        if self._task is None or self._task.done():
            self._stopped = False
            self._task = asyncio.get_running_loop().create_task(self.run())
        return self._task

    async def stop(self) -> None:
        self._stopped = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
